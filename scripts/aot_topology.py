# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""AOT-compile the engines against a REAL TPU topology (no hardware).

Round-3 verdict: every multi-chip claim was audited on XLA-CPU HLO, which
provably differs from the TPU partitioner's output (all-reduce where
reduce-scatter is intended; f8 collectives upcast to f16; no async
-start/-done pairs).  JAX can lower + compile against a *compile-only* TPU
topology via `jax.experimental.topologies` — libtpu compiles locally, no
devices needed.  This script does exactly that for each engine stage and
feeds the TPU-partitioned HLO to `utils.hlo_comm.collective_ledger`,
settling three questions one chip cannot answer:

  1. Does the TPU partitioner emit TRUE reduce-scatter for ZeRO-2/3 grads
     (XLA CPU emits all-reduce instead — PROFILE.md caveat 1)?
  2. Does the fp8 weight gather (gather_quant="fp8") move f8 bytes on the
     wire, or is the feature dead on TPU too (CPU: +1.34x bytes)?
  3. Do async `-start`/`-done` pairs appear — the first compiled evidence
     for the "XLA latency-hides the collectives" overlap claim
     (engine.py:14-18 vs reference ddp/module.py:36-78)?

Usage:  python scripts/aot_topology.py [--topology v5e:4x2] [--json OUT]
Writes a JSON summary; PROFILE.md's "TPU topology HLO" section is the
human-readable digest.
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

# Trace/constant-fold on local CPU; the TPU compilation happens via the
# compile-only topology client (libtpu), NOT the axon tunnel.  The image's
# sitecustomize imports jax early and pins the platform, so the env var is
# ignored — jax.config is the authoritative override (see
# .claude/skills/verify/SKILL.md).
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import Mesh

from tiny_deepspeed_tpu import (
    AdamW, DDP, GPT2Model, GPTConfig, Zero1, Zero2, Zero3,
)
from tiny_deepspeed_tpu.parallel.engine import TrainState
from tiny_deepspeed_tpu.ops.dispatch import kernel_target_forced
from tiny_deepspeed_tpu.utils.hlo_comm import collective_ledger
from tiny_deepspeed_tpu.utils.profiling import comm_report

# real async op pairs (ppermute compiles to these on TPU)
_COLLECTIVE_START_RE = re.compile(
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"-start\("
)
# collectives the TPU backend scheduled async WITHOUT renaming the op: the
# frontend attribute records the start half of the pair
_ASYNC_ATTR_RE = re.compile(r'async_collective_name="([\w\.\-]+)"')
# every all-gather result shape, to split gathered bytes by dtype (the fp8
# question: do the ZeRO-3 layer gathers move f8 on the TPU wire?)
_GATHER_RESULT_RE = re.compile(r"=\s*((?:\([^)]*\)|\S+))\s*all-gather\(")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
                "f8e4m3": 1, "s32": 4, "u32": 4}


def _state_structs(engine):
    """Abstract TrainState + batch matching the engine's jit shardings —
    engine.init() would need executable devices; a topology has none.
    (Shared with tests/test_aot_topology.py — keep the single copy here.)"""
    params = jax.eval_shape(engine.model.init, jax.random.PRNGKey(0))
    opt = jax.eval_shape(engine.optimizer.init, params)

    def attach(avals, shardings):
        return jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            avals, shardings,
        )

    dropout_base = None
    if engine._dropout_shardings is not None:
        key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        dropout_base = jax.ShapeDtypeStruct(
            key.shape, key.dtype, sharding=engine._dropout_shardings
        )
    scaler = None
    if engine._scaler_shardings is not None:  # loss_scale="dynamic"
        scaler = {
            "scale": jax.ShapeDtypeStruct(
                (), jnp.float32,
                sharding=engine._scaler_shardings["scale"]),
            "good": jax.ShapeDtypeStruct(
                (), jnp.int32, sharding=engine._scaler_shardings["good"]),
        }
    return TrainState(
        params=attach(params, engine._param_shardings),
        opt_state=attach(opt, engine._opt_shardings),
        scaler=scaler,
        dropout_base=dropout_base,
    )


def _batch_structs(engine, b, t):
    shape = (b, t)
    if engine.accum_steps > 1:  # microbatched step: (accum, B, T)
        shape = (engine.accum_steps,) + shape
    s = jax.ShapeDtypeStruct(shape, jnp.int32,
                             sharding=engine._batch_sharding)
    return (s, s)


def analyze(engine, b, t, label, dump_dir=None):
    state = _state_structs(engine)
    batch = _batch_structs(engine, b, t)
    # trace with the TPU kernel gates ON: the process backend is CPU, but
    # the program targets TPU — without the force every Pallas gate picks
    # the XLA fallback and the compiled program differs from the chip's
    # (ops/dispatch.py; found in round 4 via chip-vs-AOT memory mismatch)
    with kernel_target_forced("tpu"):
        compiled = engine._step.lower(state, batch).compile()
    text = compiled.as_text()
    if dump_dir:
        os.makedirs(dump_dir, exist_ok=True)
        with open(os.path.join(dump_dir, f"{label}.hlo"), "w") as f:
            f.write(text)
    ledger = collective_ledger(text)
    starts = {}
    for m in _COLLECTIVE_START_RE.finditer(text):
        starts[m.group(1)] = starts.get(m.group(1), 0) + 1
    gather_by_dtype = {}
    for m in _GATHER_RESULT_RE.finditer(text):
        for dt, dims in _SHAPE_RE.findall(m.group(1)):
            if dt not in _DTYPE_BYTES:
                continue
            nel = 1
            for d in dims.split(","):
                if d:
                    nel *= int(d)
            gather_by_dtype[dt] = (gather_by_dtype.get(dt, 0)
                                   + nel * _DTYPE_BYTES[dt])
    predicted = comm_report(engine)
    return {
        "label": label,
        "ledger": {
            k: ledger[k] for k in
            ("payload_bytes", "wire_bytes", "count", "total_wire_bytes",
             "unresolved_loops", "unresolved_groups")
        },
        "async_start_pairs": starts,
        "async_attr_collectives": len(_ASYNC_ATTR_RE.findall(text)),
        "gather_result_bytes_by_dtype": gather_by_dtype,
        "comm_report_total": predicted.get("total_bytes_per_step"),
        "comm_report": predicted,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:4x2")
    ap.add_argument("--json", default="/tmp/aot_topology.json")
    ap.add_argument("--dump-hlo", default=None, metavar="DIR",
                    help="also write each config's compiled HLO text to "
                         "DIR/<label>.hlo (the PROFILE.md evidence files)")
    args = ap.parse_args()

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=args.topology)
    devs = np.array(topo.devices)
    n = devs.size
    print(f"topology {args.topology}: {n}x {topo.devices[0].device_kind}",
          flush=True)

    cfg = GPTConfig(block_size=128, vocab_size=512, n_layer=4, n_head=8,
                    n_embd=256)
    b, t = n, cfg.block_size
    opt = lambda: AdamW(lr=1e-3)

    mesh_dp = Mesh(devs.reshape(n), ("data",))
    mesh_tp = Mesh(devs.reshape(n // 2, 2), ("data", "model"))
    mesh_sp = Mesh(devs.reshape(n // 2, 2), ("data", "seq"))
    mesh_pp = Mesh(devs.reshape(n // 2, 2), ("data", "pipe"))
    mesh_ep = Mesh(devs.reshape(n // 2, 2), ("data", "expert"))

    def _moe_ep_engine():
        from tiny_deepspeed_tpu import MoEConfig, MoEGPT
        mcfg = MoEConfig(block_size=128, vocab_size=512, n_layer=2,
                         n_head=4, n_embd=64, n_expert=4, expert_top_k=2)
        return Zero2(MoEGPT(mcfg), opt(), mesh=mesh_ep, expert_parallel=2)

    cases = [
        ("ddp", lambda: DDP(GPT2Model(cfg), opt(), mesh=mesh_dp)),
        ("zero1", lambda: Zero1(GPT2Model(cfg), opt(), mesh=mesh_dp)),
        ("zero2", lambda: Zero2(GPT2Model(cfg), opt(), mesh=mesh_dp)),
        ("zero3", lambda: Zero3(GPT2Model(cfg), opt(), mesh=mesh_dp)),
        ("zero3-fp8", lambda: Zero3(
            GPT2Model(GPTConfig(**{**cfg.__dict__, "gather_quant": "fp8"})),
            opt(), mesh=mesh_dp)),
        ("zero3-tp2", lambda: Zero3(GPT2Model(cfg), opt(), mesh=mesh_tp,
                                    tensor_parallel=2)),
        ("zero2-ring-sp2", lambda: Zero2(GPT2Model(cfg), opt(), mesh=mesh_sp,
                                         seq_parallel=2)),
        ("zero1-pipe2-1f1b", lambda: Zero1(
            GPT2Model(cfg), opt(), mesh=mesh_pp, pipeline_parallel=2,
            pipeline_microbatches=4, pipeline_schedule="1f1b")),
        # sharded f32 accumulator across microbatches: each microbatch's
        # grads reduce-scatter into the shard (engine.py round-1 design)
        ("zero2-accum4", lambda: Zero2(GPT2Model(cfg), opt(),
                                       mesh=mesh_dp, accum_steps=4)),
        # expert parallelism: capacity-bucketed dispatch over the "expert"
        # axis — does the TPU partitioner emit real all-to-all?
        ("moe-zero2-ep2", lambda: _moe_ep_engine()),
    ]

    results = []
    for label, make in cases:
        try:
            engine = make()
            res = analyze(engine, b, t, label, dump_dir=args.dump_hlo)
            rs = res["ledger"]["wire_bytes"].get("reduce-scatter", 0)
            ar = res["ledger"]["wire_bytes"].get("all-reduce", 0)
            print(f"{label}: total_wire={res['ledger']['total_wire_bytes']:.3e}"
                  f" (predicted {res['comm_report_total']:.3e})"
                  f" rs={rs:.3e} ar={ar:.3e}"
                  f" starts={res['async_start_pairs']}"
                  f" async_attrs={res['async_attr_collectives']}"
                  f" gathers={res['gather_result_bytes_by_dtype']}",
                  flush=True)
        except Exception as e:  # keep going: one failed case != no report
            res = {"label": label, "error": f"{type(e).__name__}: {e}"[:500]}
            print(f"{label}: ERROR {res['error'][:200]}", flush=True)
        results.append(res)

    out = {"topology": args.topology, "n_devices": n,
           "device_kind": topo.devices[0].device_kind,
           "model": "gpt2 L4/H8/D256/V512", "batch": [b, t],
           "results": results}
    with open(args.json, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {args.json}")


if __name__ == "__main__":
    main()
