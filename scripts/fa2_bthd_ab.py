# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""A/B the heads-last FA2 entry vs transpose + standard FA2, on the chip.

The round-4 profile priced the per-layer (B,T,H,Dh)->(B,H,T,Dh) copies at
~8.4 ms of the 95 ms gpt2-124m step; `fa2_flash_attention_bthd` deletes
them by addressing the head axis in the BlockSpec index maps.  Whether
Mosaic turns those head-strided panel DMAs into something competitive is
exactly what this measures (the round-4 attempt hit the tunnel outage).
Run on a live TPU: prints one JSON line per arm; promote the bthd entry
into the dispatch only if it wins f+b at the 124M shape.
"""

import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from tiny_deepspeed_tpu.ops.flash_fa2 import (
    fa2_flash_attention, fa2_flash_attention_bthd)

B, H, T, Dh = 12, 12, 1024, 64
x = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, Dh), jnp.bfloat16)
k = jax.random.normal(jax.random.PRNGKey(1), (B, T, H, Dh), jnp.bfloat16)
v = jax.random.normal(jax.random.PRNGKey(2), (B, T, H, Dh), jnp.bfloat16)


def loss_transpose(q, k, v):
    o = fa2_flash_attention(q.swapaxes(1, 2), k.swapaxes(1, 2),
                            v.swapaxes(1, 2), 512, 512)
    # back-transpose o so this arm pays ALL 8 per-layer transposes the
    # real model pays (3 inputs + output, fwd and — via autodiff — bwd);
    # consuming o head-major would hide 2 of them and bias the A/B
    o = o.swapaxes(1, 2)
    return jnp.sum(o.astype(jnp.float32) ** 2)


def loss_bthd(q, k, v):
    o = fa2_flash_attention_bthd(q, k, v, 512, 512)
    return jnp.sum(o.astype(jnp.float32) ** 2)


def timeit(f, n=30):
    g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
    t0 = time.time()
    r = g(x, k, v)
    float(jnp.sum(r[0].astype(jnp.float32)))
    compile_s = time.time() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        r = g(x, k, v)
    float(jnp.sum(r[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / n * 1e3, compile_s


for name, fn in [("transpose+fa2", loss_transpose), ("bthd_fa2", loss_bthd)]:
    try:
        ms, compile_s = timeit(fn)
        print(json.dumps({"arm": name, "fb_ms": round(ms, 3),
                          "compile_s": round(compile_s, 1)}), flush=True)
    except Exception as e:  # noqa: BLE001 - report and keep going
        print(json.dumps({"arm": name, "error": repr(e)[:300]}), flush=True)
