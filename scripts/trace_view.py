# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Export a run's metrics JSONL as a Chrome-trace timeline.

    python scripts/trace_view.py RUN.jsonl [-o TRACE.json]

Load TRACE.json in chrome://tracing or https://ui.perfetto.dev.

TRAINING runs show, per step: the whole-step span, the measured host
wall segments (data wait / host->device / device compute+sync —
StepTimer `mark()`), and the compiled step's collective spans from the
HLO ledger (`utils/hlo_comm.py`) instantiated inside the compute window
— widths proportional to wire bytes (schematic), annotations exact:
wire bytes, op count, per-dtype split, loop-resident flag.

SERVING runs (auto-detected from `request`/`tick` records — the
`serve_bench.py` sidecar or any ServingEngine with a logger) show the
scheduler ticks with their measured wall split, a queue track of
request wait windows, and one track per decode slot with each request's
active windows — preemptions, quarantines, and watchdog warm restarts
visible as span boundaries and instant markers.

FLEET serving files (records carrying `replica_id`) lay out one process
per replica, each with the full tick/queue/slot track set; a request
that crossed engines (disagg prefill->decode migration, failover) gets
its windows on every replica it touched, correlated by the `trace_id`
in their span args.  Ambiguous coordinates in such a shared stream
resolve by ONE rule (telemetry/trace.py::serving_chrome_trace): records
carrying an explicit key — replica_id on ticks/flights, per-event
replica stamps on request lifecycles — route by it; records without one
anchor by file order (last matching record written before, else first
after), which is how flight flushes land on the right engine lifetime
when two lifetimes' tick counters both start at 0.

Span assembly lives in `tiny_deepspeed_tpu/telemetry/trace.py`; the
input comes from `examples/* --telemetry --metrics RUN.jsonl` (which
also writes the `trace` span-template record), `bench.py`'s telemetry
sidecar, or `scripts/serve_bench.py`'s sidecar.

Exit codes: 0 ok; 1 parse errors in the JSONL; 2 missing/empty input or
no timed step/tick/request records to lay out.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_module():
    """telemetry/trace.py loaded by file path: the module is pure-python
    (json + typing), but importing it through the package would pull the
    whole jax stack in — a multi-second tax on a viewer that only
    reshuffles JSONL."""
    spec = importlib.util.spec_from_file_location(
        "tiny_deepspeed_tpu_trace_standalone",
        os.path.join(_REPO, "tiny_deepspeed_tpu", "telemetry", "trace.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace = _load_trace_module()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from a training run")
    ap.add_argument("-o", "--out", default=None,
                    help="write the Chrome-trace JSON here "
                         "(default: <input>.trace.json)")
    args = ap.parse_args(argv)
    if not os.path.exists(args.jsonl):
        print(f"{args.jsonl}: no such file", file=sys.stderr)
        return 2
    metas, steps, errs = trace.load_run(args.jsonl)
    for e in errs:
        print(f"warning: {args.jsonl}: {e}", file=sys.stderr)
    if not metas and not steps:
        print(f"{args.jsonl}: no records (empty or fully truncated "
              "metrics file)", file=sys.stderr)
        return 2
    serving = trace.has_serving_records(metas)
    timed_steps = any(
        isinstance(r.get("ts"), (int, float))
        and isinstance(r.get("step_s"), (int, float)) for r in steps
    )
    if serving and not timed_steps:
        doc = trace.serving_chrome_trace(metas, source=args.jsonl)
        laid_out = "tick(s)/request(s)"
        n_laid = (doc["otherData"]["ticks"]
                  + doc["otherData"]["requests"])
    else:
        doc = trace.chrome_trace(metas, steps, source=args.jsonl)
        laid_out = "step(s)"
        n_laid = len(steps)
        if serving:
            # a file carrying BOTH (a combined sidecar): serving tracks
            # join the training timeline as their own process (pid 1)
            doc["traceEvents"].extend(
                trace.serving_chrome_trace(
                    metas, source=args.jsonl)["traceEvents"])
    n_spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    if not n_spans:
        print(f"{args.jsonl}: no timed step records (run with "
              "--telemetry --metrics to record step_s + wall segments) "
              "and no serving tick/request records",
              file=sys.stderr)
        return 2
    out = args.out or (os.path.splitext(args.jsonl)[0] + ".trace.json")
    with open(out, "w") as f:
        json.dump(doc, f)
    reps = doc.get("otherData", {}).get("replicas") or []
    fleet = (f" across {len(reps)} replicas" if len(reps) > 1 else "")
    print(f"wrote {out}: {n_spans} spans over {n_laid} {laid_out}"
          f"{fleet} — open in chrome://tracing or "
          "https://ui.perfetto.dev")
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
