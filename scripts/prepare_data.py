#!/usr/bin/env python3
# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Tokenize a text corpus into the uint16 .bin format TokenLoader consumes.

The training entry points take `--data tokens.bin` / `--val-data val.bin`
(nanoGPT flat-uint16 convention, data/loader.py); this script produces those
files from plain text.  The reference has no data tooling at all — its demo
workload is random tokens (reference example/ddp/train.py:23-24).

Tokenizers:
  * byte (default): raw UTF-8 bytes, vocab 256.  Always available — this
    environment has no network egress, and byte-level LMs train fine at
    small scale.  Pair with a model config whose vocab_size >= 256.
  * gpt2: transformers GPT2TokenizerFast (vocab 50257, pads into the
    models' default 50304).  Works only if the tokenizer files are already
    in the local HF cache; a clear error explains otherwise.

Usage:
  python scripts/prepare_data.py --input corpus.txt --out-dir data/
  # -> data/train.bin + data/val.bin (last --val-fraction held out)
  python examples/ddp/train.py --data data/train.bin --val-data data/val.bin
"""

import argparse
import os
import sys

import numpy as np


def tokenize(text: str, tokenizer: str) -> np.ndarray:
    """Delegates to the shared tokenizer library (data/tokenizer.py —
    also what examples/generate.py encodes --prompt text with), keeping
    CLI-friendly SystemExit error surfacing."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tiny_deepspeed_tpu.data import tokenizer as tok
    try:
        return tok.encode(text, tokenizer)
    except (RuntimeError, ValueError) as e:
        raise SystemExit(str(e))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--input", required=True, metavar="TEXT.txt")
    p.add_argument("--out-dir", default=".", metavar="DIR")
    p.add_argument("--tokenizer", default="byte", choices=("byte", "gpt2"))
    p.add_argument("--val-fraction", type=float, default=0.1,
                   help="trailing fraction held out into val.bin (0 = none)")
    args = p.parse_args()

    with open(args.input, encoding="utf-8") as f:
        text = f.read()
    ids = tokenize(text, args.tokenizer)
    os.makedirs(args.out_dir, exist_ok=True)

    n_val = int(len(ids) * args.val_fraction)
    splits = [("train.bin", ids[: len(ids) - n_val])]
    if n_val:
        splits.append(("val.bin", ids[len(ids) - n_val:]))
    for name, arr in splits:
        path = os.path.join(args.out_dir, name)
        arr.tofile(path)
        print(f"{path}: {len(arr)} tokens "
              f"(max id {int(arr.max()) if len(arr) else 0})")
    if args.tokenizer == "byte":
        print("byte tokenizer: use a model config with vocab_size >= 256 "
              "(e.g. the 'tiny' preset's 512)")


if __name__ == "__main__":
    main()
