# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Render a training run's metrics JSONL into a markdown dashboard — or
validate it against the telemetry schema.

    python scripts/report_run.py RUN.jsonl [-o REPORT.md]
    python scripts/report_run.py --check RUN.jsonl

The JSONL comes from `utils.profiling.MetricsLogger` (examples/common.py
`--telemetry --metrics RUN.jsonl`, or bench.py's telemetry sidecar); the
schema is `tiny_deepspeed_tpu/telemetry/schema.py`.  `--check` exits
non-zero on any drift (unknown fields, wrong types, malformed lines) so CI
catches schema breakage (tests/test_telemetry.py smoke-runs it in tier-1).

The report covers: throughput (p50/p95 step time, tokens/s, MFU when the
meta record carries FLOPs context), the step-time breakdown (data-wait vs
host->device vs device compute), measured (HLO-ledger) collective bytes
next to the `comm_report` ring model, HBM watermarks vs the AOT prediction,
and health flags (non-finite grads, loss spikes, recompiles, anomaly
traces).
"""

from __future__ import annotations

import argparse
import math
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tiny_deepspeed_tpu.telemetry import schema  # noqa: E402
# ONE loader for both views of a metrics file: trace_view.py reads the
# same records through the same function, so the two tools can never
# disagree on record classification
from tiny_deepspeed_tpu.telemetry.trace import load_run  # noqa: E402
from tiny_deepspeed_tpu.utils.profiling import _quantile  # noqa: E402


def _fmt_bytes(n: float) -> str:
    for unit, div in (("GB", 2 ** 30), ("MB", 2 ** 20), ("KB", 2 ** 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n:.0f} B"


def _col(steps: List[dict], key: str) -> List[float]:
    return [
        r[key] for r in steps
        if isinstance(r.get(key), (int, float))
        and not isinstance(r.get(key), bool)
        and math.isfinite(r[key])
    ]


def _meta(metas: List[dict], kind: str) -> Optional[dict]:
    for m in metas:
        if m.get("kind") == kind:
            return m
    return None


def render_report(metas: List[dict], steps: List[dict],
                  source: str = "") -> str:
    run = _meta(metas, "run_meta") or {}
    summary = _meta(metas, "telemetry_summary") or {}
    out: List[str] = []
    title = run.get("model") or os.path.basename(source) or "training run"
    out.append(f"# Run report — {title}\n")
    if source:
        out.append(f"Source: `{source}`\n")

    # -- run identity -------------------------------------------------------
    if run:
        out.append("## Run\n")
        for label, key in (("engine", "engine"), ("devices", "devices"),
                           ("params", "n_params"), ("batch", "batch"),
                           ("seq len", "seq_len"),
                           ("tokens/step", "tokens_per_step")):
            if key in run:
                v = run[key]
                if key == "n_params":
                    v = f"{v / 1e6:.1f}M"
                out.append(f"- {label}: {v}")
        out.append("")

    # -- throughput ---------------------------------------------------------
    times = _col(steps, "step_s")
    # drop the first step once there are more: it pays the compile
    warm = times[1:] if len(times) > 1 else times
    toks = _col(steps, "tokens_per_s")
    out.append("## Throughput\n")
    out.append(f"- steps recorded: {len(steps)}")
    if warm:
        out.append(
            f"- step time: mean {sum(warm) / len(warm) * 1e3:.1f} ms, "
            f"p50 {_quantile(warm, 0.5) * 1e3:.1f} ms, "
            f"p95 {_quantile(warm, 0.95) * 1e3:.1f} ms, "
            f"p99 {_quantile(warm, 0.99) * 1e3:.1f} ms, "
            f"max {max(warm) * 1e3:.1f} ms"
        )
    if toks:
        warm_toks = toks[1:] if len(toks) > 1 else toks
        mean_tps = sum(warm_toks) / len(warm_toks)
        out.append(f"- tokens/s: mean {mean_tps:,.0f}")
        peak = run.get("peak_flops_per_chip")
        n_params = run.get("n_params")
        devices = run.get("devices", 1) or 1
        # MFU accounting preference: HLO-counted (measured numerator,
        # utils/hlo_cost) > analytic matmul (bench's honest formula) >
        # 6N naive (self-flattering: prices embedding gathers as
        # matmul FLOPs) — always labeled with which one was used
        cost = run.get("hlo_cost") or {}
        tok_step = run.get("tokens_per_step")
        fptm = run.get("flops_per_token_matmul")
        if peak and cost.get("total_flops") and tok_step:
            # per-device program FLOPs x steps/s / per-chip peak
            mfu = (float(cost["total_flops"]) * mean_tps
                   / float(tok_step) / peak)
            out.append(f"- MFU (HLO-counted): {mfu:.3f}")
        elif peak and fptm:
            mfu = float(fptm) * mean_tps / devices / peak
            out.append(f"- MFU (matmul accounting): {mfu:.3f}")
        elif peak and n_params:
            mfu = 6 * n_params * mean_tps / devices / peak
            out.append(f"- MFU (6N naive; no measured accounting "
                       f"in file): {mfu:.3f}")
    out.append("")

    # -- step-time breakdown ------------------------------------------------
    seg_keys = [k for k in ("data_s", "h2d_s", "compute_s")
                if _col(steps, k)]
    if seg_keys:
        out.append("## Step-time breakdown (mean, share of step)\n")
        out.append("| segment | mean | share |")
        out.append("|---|---|---|")
        total = sum(
            sum(_col(steps, k)) / max(1, len(_col(steps, k)))
            for k in seg_keys
        )
        names = {"data_s": "data wait", "h2d_s": "host->device",
                 "compute_s": "device compute (+sync)"}
        for k in seg_keys:
            xs = _col(steps, k)
            mean = sum(xs) / len(xs)
            share = mean / total if total else 0.0
            out.append(
                f"| {names[k]} | {mean * 1e3:.2f} ms | {share:.0%} |"
            )
        out.append("")

    # -- communication ------------------------------------------------------
    measured = run.get("comm_measured")
    model_rep = run.get("comm_model")
    if measured or model_rep:
        out.append("## Collective traffic (per device per step)\n")
        if model_rep:
            out.append("ring-model prediction (`comm_report`):\n")
            for k, v in sorted(model_rep.items()):
                if k.endswith("_bytes") and v:
                    out.append(f"- {k}: {_fmt_bytes(v)}")
            out.append("")
        if measured:
            out.append("measured from the compiled step's HLO ledger "
                       "(`utils/hlo_comm.py`):\n")
            out.append("| collective | wire bytes | ops/step |")
            out.append("|---|---|---|")
            counts = measured.get("count", {})
            for op, v in sorted(measured.get("wire_bytes", {}).items()):
                out.append(
                    f"| {op} | {_fmt_bytes(v)} | "
                    f"{counts.get(op, 0):.0f} |"
                )
            out.append(
                f"| **total** | **{_fmt_bytes(measured['total_wire_bytes'])}"
                f"** | |"
            )
            out.append("")
            if "comm_delta" in run:
                out.append(
                    f"measured / modeled = **{run['comm_delta']:.3f}** "
                    "(1.0 = the ring model is exact; >1 = the partitioner "
                    "emitted more wire traffic than the model predicts)\n"
                )
            unresolved = (measured.get("unresolved_loops", 0)
                          + measured.get("unresolved_groups", 0))
            if unresolved:
                out.append(
                    f"WARNING: {unresolved} collective(s)/loop(s) had "
                    "unresolved attribution — totals are a lower bound\n"
                )

    # -- roofline (HLO cost ledger) -----------------------------------------
    cost = run.get("hlo_cost")
    if cost:
        out.append("## Roofline (per device per step, "
                   "`utils/hlo_cost.py`)\n")
        out.append(f"- FLOPs: {cost.get('total_flops', 0.0):.3e} "
                   f"({cost.get('flops_in_loops', 0.0):.3e} in loops)")
        out.append(f"- HBM traffic (modeled): "
                   f"{_fmt_bytes(cost.get('hbm_bytes', 0.0))}")
        if cost.get("wire_bytes"):
            out.append(f"- wire traffic: "
                       f"{_fmt_bytes(cost['wire_bytes'])}")
        ai = cost.get("arithmetic_intensity", 0.0)
        ridge = cost.get("ridge_intensity", 0.0)
        out.append(f"- arithmetic intensity: {ai:.1f} FLOPs/byte "
                   f"(device ridge {ridge:.1f})")
        bound = cost.get("bound", "?")
        out.append(
            f"- bound verdict: **{bound}-bound** "
            f"(t_compute {cost.get('t_compute_s', 0.0) * 1e3:.2f} ms, "
            f"t_hbm {cost.get('t_hbm_s', 0.0) * 1e3:.2f} ms, "
            f"t_wire {cost.get('t_wire_s', 0.0) * 1e3:.2f} ms lower "
            f"bounds)"
        )
        centers = cost.get("top_cost_centers") or []
        if centers:
            out.append("\ntop cost centers:\n")
            out.append("| op (result <- operands) | FLOPs | ops/step "
                       "| share |")
            out.append("|---|---|---|---|")
            for c in centers:
                out.append(
                    f"| `{c.get('sig', '?')}` | "
                    f"{c.get('flops', 0.0):.3e} | "
                    f"{c.get('count', 0.0):.0f} | "
                    f"{c.get('share', 0.0):.0%} |"
                )
        out.append("")

    # -- memory -------------------------------------------------------------
    hbm_peak = _col(steps, "hbm_gb_peak")
    aot = run.get("aot") or {}
    if hbm_peak or aot:
        out.append("## Memory\n")
        if hbm_peak:
            out.append(
                f"- HBM peak watermark: {max(hbm_peak):.3f} GB "
                f"(first step {hbm_peak[0]:.3f} GB)"
            )
            in_use = _col(steps, "hbm_gb_in_use")
            if in_use:
                out.append(f"- HBM in use (last step): {in_use[-1]:.3f} GB")
        if aot.get("temp_bytes") is not None:
            out.append(
                f"- AOT-predicted step temp: "
                f"{_fmt_bytes(aot['temp_bytes'])}"
            )
            if hbm_peak:
                pred_gb = aot["temp_bytes"] / 2 ** 30
                out.append(
                    f"- predicted-vs-measured delta: "
                    f"{max(hbm_peak) - pred_gb:+.3f} GB "
                    "(live state + allocator slack)"
                )
        out.append("")

    # -- health -------------------------------------------------------------
    out.append("## Health\n")
    flags = []
    losses = _col(steps, "loss")
    if losses:
        out.append(
            f"- loss: first {losses[0]:.4f} -> last {losses[-1]:.4f} "
            f"(min {min(losses):.4f})"
        )
        if losses[-1] > losses[0]:
            flags.append("loss ended ABOVE its starting value")
    gn = _col(steps, "grad_norm")
    if gn:
        out.append(f"- grad norm: max {max(gn):.4f}, last {gn[-1]:.4f}")
        p50_gn = _quantile(gn, 0.5)
        if p50_gn and max(gn) > 10 * p50_gn:
            flags.append(
                f"grad-norm spike: max {max(gn):.3g} vs p50 {p50_gn:.3g}"
            )
    nf = [r for r in steps if r.get("nonfinite_grads")]
    if nf:
        flags.append(
            f"{len(nf)} step(s) with NON-FINITE gradients "
            f"(first at step {nf[0].get('step')})"
        )
    else:
        nonf = _col(steps, "nonfinite_grads")
        if nonf:
            out.append("- non-finite grads: none")
    # the first recorded step legitimately pays the first compile; any
    # compiled>0 after it is a shape-driven recompile worth flagging
    recompiles = [r for r in steps[1:] if r.get("compiled")]
    if recompiles:
        flags.append(
            f"{len(recompiles)} RECOMPILE step(s) beyond the first "
            f"(steps {[r.get('step') for r in recompiles][:8]})"
        )
    traces = [r["anomaly_trace"] for r in steps if r.get("anomaly_trace")]
    if traces:
        flags.append(f"anomaly trace captured: `{traces[0]}`")
    flight = _meta(metas, "flight")
    if flight is not None:
        fl = (f"flight record flushed (reason: "
              f"{flight.get('reason', '?')}, "
              f"{len(flight.get('steps') or [])} step(s) of history)")
        fnl = flight.get("first_nonfinite_layer")
        if fnl is not None:
            fl += f"; non-finiteness ORIGINATED at layer {fnl}"
        flags.append(fl)
    if warm:
        p50 = _quantile(warm, 0.5)
        slow = [t for t in warm if p50 and t > 2 * p50]
        if slow:
            flags.append(
                f"{len(slow)} step(s) slower than 2x the p50 step time"
            )
    if flags:
        out.append("\n### Flags\n")
        for fl in flags:
            out.append(f"- [!] {fl}")
    else:
        out.append("- no flags raised")
    out.append("")

    # -- multi-host stragglers ---------------------------------------------
    strag = _meta(metas, "straggler")
    if strag is not None and strag.get("hosts", 1) > 1:
        qty = strag.get("quantity", "step_s")
        out.append(f"## Stragglers (per-host {qty})\n")
        by_host = strag.get("step_s_by_host") or []
        out.append(f"- hosts: {strag['hosts']}")
        out.append(
            f"- slowest host: {strag.get('slowest_host')} "
            f"({max(by_host) * 1e3:.1f} ms vs median "
            f"{_quantile(sorted(by_host), 0.5) * 1e3:.1f} ms)"
        )
        frac = strag.get("straggler_frac", 0.0)
        out.append(
            f"- straggler_frac: {frac:.3f} — the fraction of the slowest "
            "host's time the median host would not have spent (every "
            "SPMD step runs at the slowest host's pace)"
        )
        out.append("")

    # -- serving tier -------------------------------------------------------
    req_recs = [m for m in metas if m.get("kind") == "request"]
    tick_recs = [m for m in metas if m.get("kind") == "tick"]
    if req_recs or tick_recs:
        out.append("## Serving\n")
        by_status = {}
        for r in req_recs:
            s = r.get("status", "?")
            by_status[s] = by_status.get(s, 0) + 1
        if req_recs:
            out.append(f"- requests: {len(req_recs)} (" + ", ".join(
                f"{k} {v}" for k, v in sorted(by_status.items())) + ")")
            ttfts = sorted(
                r["ttft_s"] for r in req_recs
                if isinstance(r.get("ttft_s"), (int, float)))
            if ttfts:
                out.append(
                    f"- TTFT: p50 {_quantile(ttfts, 0.5) * 1e3:.1f} ms, "
                    f"p99 {_quantile(ttfts, 0.99) * 1e3:.1f} ms"
                )
            lats = sorted(
                r["lat_s"] for r in req_recs
                if isinstance(r.get("lat_s"), (int, float))
                and r.get("status") != "shed")
            if lats:
                out.append(
                    f"- latency: p50 {_quantile(lats, 0.5) * 1e3:.1f} ms"
                    f", p99 {_quantile(lats, 0.99) * 1e3:.1f} ms"
                )
        if tick_recs:
            occ = [t["occupancy"] for t in tick_recs
                   if isinstance(t.get("occupancy"), (int, float))]
            if occ:
                out.append(
                    f"- ticks recorded: {len(tick_recs)}, mean "
                    f"occupancy {sum(occ) / len(occ):.2f}"
                )
        out.append(
            "\nFull dashboard (tail attribution, SLO headroom, shed "
            f"audit): `python scripts/serve_report.py "
            f"{source or 'RUN.jsonl'}`\n"
        )

    # -- telemetry registry summary ----------------------------------------
    if summary:
        out.append("## Telemetry registry\n")
        counters = summary.get("counters") or {}
        if counters:
            out.append("counters: " + ", ".join(
                f"{k}={v}" for k, v in sorted(counters.items())
            ) + "\n")
        hists = summary.get("histograms") or {}
        if hists:
            out.append(
                "| histogram | count | mean | p50 | p95 | p99 | max |"
            )
            out.append("|---|---|---|---|---|---|---|")
            for k, h in sorted(hists.items()):
                out.append(
                    f"| {k} | {h.get('count', 0)} | {h.get('mean', 0):.4g} "
                    f"| {h.get('p50', 0):.4g} | {h.get('p95', 0):.4g} "
                    f"| {h.get('p99', h.get('p95', 0)):.4g} "
                    f"| {h.get('max', 0):.4g} |"
                )
            out.append("")
    if _meta(metas, "trace") is not None:
        out.append(
            "Step timeline: `python scripts/trace_view.py "
            f"{source or 'RUN.jsonl'}` -> Chrome-trace JSON "
            "(chrome://tracing / Perfetto).\n"
        )
    return "\n".join(out) + "\n"


def check(path: str) -> int:
    counts, errs = schema.validate_file(path)
    for e in errs:
        print(f"{path}: {e}", file=sys.stderr)
    if errs:
        print(
            f"{path}: SCHEMA DRIFT — {len(errs)} error(s) "
            f"({counts['step']} valid step + {counts['meta']} valid meta "
            "records)",
            file=sys.stderr,
        )
        return 1
    if counts["step"] + counts["meta"] == 0:
        print(f"{path}: no records (empty metrics file)", file=sys.stderr)
        return 2
    metas, _, _ = load_run(path)
    warn = schema.version_warning(metas)
    if warn:
        # advisory only: field validation above is the hard gate
        print(f"{path}: warning: {warn}", file=sys.stderr)
    print(
        f"{path}: ok — {counts['step']} step record(s), "
        f"{counts['meta']} meta record(s)"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from a training run")
    ap.add_argument("-o", "--out", default=None,
                    help="write the markdown report here (default: stdout)")
    ap.add_argument("--check", action="store_true",
                    help="validate the schema instead of rendering; "
                         "exit non-zero on drift")
    args = ap.parse_args(argv)
    if not os.path.exists(args.jsonl):
        print(f"{args.jsonl}: no such file", file=sys.stderr)
        return 2
    if args.check:
        return check(args.jsonl)
    metas, steps, errs = load_run(args.jsonl)
    for e in errs:
        # a truncated final line (crashed writer) is the common case:
        # say so clearly, render what parsed, and exit non-zero below
        print(f"warning: {args.jsonl}: {e}", file=sys.stderr)
    if not metas and not steps:
        print(
            f"{args.jsonl}: no records (empty or fully truncated metrics "
            "file — nothing to report)", file=sys.stderr,
        )
        return 2
    report = render_report(metas, steps, source=args.jsonl)
    if args.out:
        with open(args.out, "w") as f:
            f.write(report)
        print(f"wrote {args.out}")
    else:
        print(report)
    if errs:
        print(
            f"{args.jsonl}: {len(errs)} unparseable line(s) — the report "
            "above covers only the valid records", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
