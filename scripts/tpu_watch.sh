#!/bin/bash
# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

# Probe the TPU tunnel until it answers; exit 0 on success.
# The axon tunnel hangs (not errors) for hours at a time, so each probe runs
# jax.devices() in a killable subprocess via `timeout`.
INTERVAL="${TPU_WATCH_INTERVAL:-180}"
DEADLINE="${TPU_WATCH_DEADLINE:-39600}"  # 11h default
start=$(date +%s)
n=0
while true; do
  n=$((n + 1))
  if timeout 75 python -c "import jax; print(jax.devices())" 2>/dev/null; then
    echo "tpu_watch: tunnel UP after $n probes, $(( $(date +%s) - start ))s"
    # measure IMMEDIATELY while it's up: the full round-3 batch, most
    # important (default bench -> .bench_last_good.json) first
    bash "$(dirname "$0")/tpu_batch.sh"
    exit 0
  fi
  now=$(date +%s)
  if (( now - start > DEADLINE )); then
    echo "tpu_watch: gave up after $n probes, $(( now - start ))s"
    exit 1
  fi
  echo "tpu_watch: probe $n down ($(date -u +%H:%M:%S)), sleeping ${INTERVAL}s"
  sleep "$INTERVAL"
done
