#!/bin/sh
# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

# Repo hygiene: remove python bytecode caches (reference script/clear-pycache.sh).
find "$(dirname "$0")/.." -type d -name __pycache__ -prune -exec rm -rf {} + 2>/dev/null
find "$(dirname "$0")/.." -type f -name '*.pyc' -delete 2>/dev/null
echo "pycache cleared"
