#!/usr/bin/env python3
# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Repo hygiene: prepend a license header to source files that lack one.

Capability parity with the reference's copyright tooling
(reference script/add-copyright.py:1-39, SURVEY §2.21): walk the tree, skip
files that already carry a header, prepend the header comment per file type,
and log files that could not be processed.

Usage:  python scripts/add_license_headers.py [--check] [root]
  --check  only report files missing a header (exit 1 if any); no edits.
"""

from __future__ import annotations

import argparse
import os
import sys

HEADER_LINES = [
    "Copyright 2026 tiny-deepspeed-tpu authors",
    "SPDX-License-Identifier: Apache-2.0",
]

COMMENT_STYLES = {
    ".py": "#", ".sh": "#", ".cmake": "#",
    ".cpp": "//", ".cc": "//", ".h": "//", ".hpp": "//", ".cu": "//",
}

SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "checkpoints", "build"}
MARKER = "SPDX-License-Identifier"


def header_for(ext: str) -> str:
    c = COMMENT_STYLES[ext]
    return "".join(f"{c} {line}\n" for line in HEADER_LINES) + "\n"


def wants_header(path: str) -> bool:
    return os.path.splitext(path)[1] in COMMENT_STYLES


def has_header(text: str) -> bool:
    return MARKER in text[:512]


def process(path: str, check: bool) -> bool:
    """Returns True if the file already had a header (False = it was
    missing; in write mode it has been added by the time we return)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if has_header(text):
        return True
    if check:
        return False
    ext = os.path.splitext(path)[1]
    # keep a shebang and/or a PEP 263 coding cookie on the first lines —
    # Python only honors the cookie on line 1 or 2
    keep = []
    rest = text
    for _ in range(2):
        first, sep, tail = rest.partition("\n")
        if sep and (first.startswith("#!") or "coding" in first[:30]
                    and first.startswith("#")):
            keep.append(first)
            rest = tail
        else:
            break
    prefix = "".join(line + "\n" for line in keep)
    with open(path, "w", encoding="utf-8") as f:
        f.write(prefix + header_for(ext) + rest)
    return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("root", nargs="?",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))))
    ap.add_argument("--check", action="store_true")
    args = ap.parse_args()

    missing, errors = [], []
    for dirpath, dirnames, filenames in os.walk(args.root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for name in filenames:
            path = os.path.join(dirpath, name)
            if not wants_header(path):
                continue
            try:
                if not process(path, args.check):
                    missing.append(os.path.relpath(path, args.root))
            except Exception as e:  # log-and-continue like the reference
                errors.append(f"{path}: {e!r}")
    for line in errors:
        print(f"error: {line}", file=sys.stderr)
    if args.check and missing:
        print("\n".join(missing))
        return 1
    if not args.check:
        print(f"{len(missing)} header(s) added" if missing
              else "all files already carry headers")
    # unprocessable files fail both modes: a passing --check must mean
    # every file was actually verified
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
