# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Tier-1 runtime budgeting: where do the 870 seconds go?

The tier-1 suite (`pytest -m 'not slow'`) runs under a hard timeout on
small CI containers, and the budget is marginal — a timeout truncates the
run and silently sheds coverage from whatever sorts last.  This script
makes the spend visible so trimming is a measured decision, not a guess:

    # run the suite yourself (records per-test durations):
    python scripts/tier1_times.py --run [-- extra pytest args]

    # or analyze an existing log from `pytest --durations=0 -vv`:
    python scripts/tier1_times.py --from-log /tmp/t1.log

Reports:
  * slowest individual tests (the `--top` N),
  * per-module totals (which FILE owns the budget),
  * parametrization fan-out: parametrized test functions ranked by
    (total seconds, case count) — the "most redundant parametrizations"
    are the ones with many cases, high total time, and a cheap slowest
    case; trimming candidates, to be cut only with the coverage argument
    in hand.

Exit code 1 (with `--budget S`) when the summed durations exceed the
budget — a CI early-warning BEFORE the hard timeout starts truncating.
"""

from __future__ import annotations

import argparse
import re
import subprocess
import sys
from collections import defaultdict

# THE tier-1 box budget (seconds): the CI container kills the suite at
# this wall time.  tests/conftest.py's sessionfinish gate imports these
# constants and fails a full `-m "not slow"` run whose WALL time (from
# conftest import, so JAX import + collection are counted) exceeds the
# budget minus the margin — creep fails loudly BEFORE the hard timeout
# starts truncating coverage.  The margin exists because a run killed AT
# the budget never reaches sessionfinish: the gate must trip strictly
# earlier to be heard at all.
TIER1_BUDGET_S = 870.0
TIER1_WALL_MARGIN_S = 30.0
# headroom under this prints a WARNING while still passing: on the
# 2-vCPU box a loaded run drifts tens of seconds past an idle one, so
# a suite that passes with <60s to spare is one noisy neighbor away
# from truncation — new heavy tests should go in slow-marked
TIER1_HEADROOM_WARN_S = 60.0


def budget_check(total_s: float, budget_s: float = TIER1_BUDGET_S):
    """(ok, message) for a measured suite total against the budget —
    the ONE predicate the CLI's --budget exit code and the conftest
    session gate share.  The message always names the remaining
    headroom, and a pass with less than TIER1_HEADROOM_WARN_S of it
    carries a WARNING: the box wall is hard, and load variance on the
    2-vCPU container eats tens of seconds between runs."""
    headroom = budget_s - total_s
    if total_s > budget_s:
        return False, (
            f"tier-1 BUDGET EXCEEDED: {total_s:.1f}s > {budget_s:.0f}s "
            f"(headroom {headroom:.1f}s) "
            f"— demote tests to `slow` (see scripts/tier1_times.py for "
            f"the per-test/per-module spend report) before the box "
            f"timeout starts truncating the suite"
        )
    msg = (
        f"tier-1 within budget: {total_s:.1f}s <= {budget_s:.0f}s "
        f"({100 * total_s / budget_s:.0f}%), headroom {headroom:.1f}s"
    )
    if headroom < TIER1_HEADROOM_WARN_S:
        msg += (
            f" — WARNING: under {TIER1_HEADROOM_WARN_S:.0f}s of "
            "headroom on this box; a loaded run can drift past the "
            "wall — mark new heavy tests `slow` from the start"
        )
    return True, msg


# pytest --durations lines look like:
#   12.34s call     tests/test_x.py::TestY::test_z[case]
_DUR = re.compile(
    r"^\s*(\d+\.\d+)s\s+(call|setup|teardown)\s+(\S+)\s*$"
)


def parse_durations(text: str):
    """[(seconds, phase, nodeid)] from a pytest run with --durations."""
    out = []
    for line in text.splitlines():
        m = _DUR.match(line)
        if m:
            out.append((float(m.group(1)), m.group(2), m.group(3)))
    return out


def split_nodeid(nodeid: str):
    """(module, test function without parametrization, case or None)."""
    module, _, rest = nodeid.partition("::")
    case = None
    fn = rest
    if "[" in rest and rest.endswith("]"):
        fn, _, case = rest[:-1].partition("[")
    return module, fn, case


def report(durations, top: int = 20, budget: float = 0.0) -> int:
    if not durations:
        print("no duration lines found — run pytest with --durations=0 "
              "(or use --run)", file=sys.stderr)
        return 2
    calls = [(s, n) for s, phase, n in durations if phase == "call"]
    total = sum(s for s, _, _ in durations)
    call_total = sum(s for s, _ in calls)
    print(f"recorded {len(calls)} test calls, {call_total:.1f}s in calls, "
          f"{total:.1f}s with setup/teardown\n")

    print(f"slowest {top} tests")
    print("-" * 72)
    for s, n in sorted(calls, reverse=True)[:top]:
        print(f"{s:8.2f}s  {n}")

    by_module = defaultdict(float)
    n_module = defaultdict(int)
    for s, n in calls:
        m, _, _ = split_nodeid(n)
        by_module[m] += s
        n_module[m] += 1
    print(f"\nper-module totals")
    print("-" * 72)
    for m, s in sorted(by_module.items(), key=lambda kv: -kv[1]):
        print(f"{s:8.2f}s  {n_module[m]:4d} tests  {m}")

    groups = defaultdict(list)
    for s, n in calls:
        m, fn, case = split_nodeid(n)
        if case is not None:
            groups[f"{m}::{fn}"].append((s, case))
    print(f"\nparametrization fan-out (trim candidates: many cases, "
          f"big total, cheap max)")
    print("-" * 72)
    ranked = sorted(
        groups.items(), key=lambda kv: -sum(s for s, _ in kv[1])
    )
    for name, cases in ranked[:top]:
        tot = sum(s for s, _ in cases)
        mx = max(s for s, _ in cases)
        print(f"{tot:8.2f}s  {len(cases):3d} cases  max {mx:6.2f}s  {name}")

    if budget:
        ok, msg = budget_check(total, budget)
        if not ok:
            print("\n" + msg, file=sys.stderr)
            return 1
        print("\n" + msg)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--run", action="store_true",
                     help="run the tier-1 suite now with --durations=0 "
                          "and analyze it (pass extra pytest args after "
                          "--)")
    src.add_argument("--from-log", metavar="FILE",
                     help="analyze an existing pytest log that was "
                          "produced with --durations=0")
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--budget", type=float, default=0.0, metavar="S",
                   help="exit 1 when summed durations exceed S seconds "
                        "(tier-1 CI uses 870)")
    p.add_argument("pytest_args", nargs="*",
                   help="extra pytest args after -- (with --run)")
    args = p.parse_args(argv)

    if args.from_log:
        with open(args.from_log, errors="replace") as f:
            text = f.read()
    else:
        cmd = [
            sys.executable, "-m", "pytest", "tests/", "-q", "-m",
            "not slow", "--durations=0", "-p", "no:cacheprovider",
            *args.pytest_args,
        ]
        print("+ " + " ".join(cmd), file=sys.stderr)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        text = proc.stdout + proc.stderr
        sys.stderr.write(text[-2000:])
    return report(parse_durations(text), top=args.top, budget=args.budget)


if __name__ == "__main__":
    sys.exit(main())
