#!/bin/bash
# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

# The TPU measurement batch: run the moment the tunnel answers,
# most-important first, each step tolerant of the tunnel dying again
# mid-batch.  Round-5 ordering: the default bench (outage insurance)
# first, then the ROUND-5 A/Bs (decode, xent kernel, GQA) while the
# window is young — round 4 got ~2.5 h; a shorter window should still
# answer this round's questions.  Everything tees into $OUT.
cd "$(dirname "$0")/.." || exit 1
OUT="${TPU_BATCH_OUT:-/tmp/tpu_batch}"
mkdir -p "$OUT"
log() { echo "[tpu_batch $(date -u +%H:%M:%S)] $*" | tee -a "$OUT/batch.log"; }

log "1. default bench (populates .bench_last_good.json)"
timeout 2400 python bench.py > "$OUT/bench_default.json" 2> "$OUT/bench_default.err"
log "   rc=$? $(cat "$OUT/bench_default.json" 2>/dev/null | head -c 200)"
# commit the measurement IMMEDIATELY: the committed last-good file is the
# round-boundary outage insurance (bench.py replays it, stale-labeled, when
# the tunnel is down for a whole round — the rounds 1-3 failure mode).
# Gate on THIS run's output being a fresh chip measurement (value > 0 and
# not itself a cache replay), and log only if the commit really landed.
if python -c "
import json, sys
try:
    r = json.load(open('$OUT/bench_default.json'))
except Exception:
    sys.exit(1)
sys.exit(0 if r.get('value', 0) > 0
         and not r.get('extra', {}).get('cached_result') else 1)"; then
  if git add .bench_last_good.json && \
     git commit -m "Record measured TPU bench (last-good cache)" \
       --only .bench_last_good.json >> "$OUT/batch.log" 2>&1; then
    log "   committed fresh .bench_last_good.json"
  else
    log "   last-good unchanged; nothing committed"
  fi
fi

log "2. decode throughput (round-5 in-place-cache restructure: vs 4,353 tok/s r4)"
timeout 1800 env BENCH_DECODE=1 python bench.py > "$OUT/bench_decode.json" 2> "$OUT/bench_decode.err"
log "   rc=$? $(cat "$OUT/bench_decode.json" 2>/dev/null | head -c 200)"

log "2b. llama-160m decode (grouped KV cache path, first chip measurement)"
timeout 1800 env BENCH_DECODE=1 BENCH_MODEL=llama-160m python bench.py > "$OUT/bench_decode_llama.json" 2> "$OUT/bench_decode_llama.err"
log "   rc=$? $(cat "$OUT/bench_decode_llama.json" 2>/dev/null | head -c 200)"

log "3. Pallas fused lm_head+xent A/B (round-5 kernel, ops/xent_pallas.py)"
for m in gpt2-124m gpt2-1.5b; do
  timeout 1800 env BENCH_MODEL=$m BENCH_XENT=pallas python bench.py > "$OUT/bench_${m}_xent_pallas.json" 2> "$OUT/bench_${m}_xent_pallas.err"
  log "   $m pallas-xent rc=$? $(cat "$OUT/bench_${m}_xent_pallas.json" 2>/dev/null | head -c 160)"
done

log "4. GQA-native vs repeat A/B (round-5: ops/flash_fa2.py kv-indexed panels)"
for m in llama-160m llama-1b; do
  timeout 1800 env BENCH_MODEL=$m python bench.py > "$OUT/bench_${m}_gqa.json" 2> "$OUT/bench_${m}_gqa.err"
  log "   $m native rc=$? $(cat "$OUT/bench_${m}_gqa.json" 2>/dev/null | head -c 160)"
  timeout 1800 env BENCH_MODEL=$m TINY_DS_GQA=repeat python bench.py > "$OUT/bench_${m}_repeat.json" 2> "$OUT/bench_${m}_repeat.err"
  log "   $m repeat rc=$? $(cat "$OUT/bench_${m}_repeat.json" 2>/dev/null | head -c 160)"
done

log "5. per-op profile of the default step (scripts/profile_step.py)"
timeout 1200 python scripts/profile_step.py --out "$OUT/xplane" > "$OUT/profile_buckets.json" 2> "$OUT/profile_buckets.err"
log "   rc=$? $(cat "$OUT/profile_buckets.json" 2>/dev/null | head -c 300)"

log "6. autotuned bench (guardrail keeps the faster program)"
timeout 3000 env BENCH_AUTOTUNE=1 python bench.py > "$OUT/bench_autotune.json" 2> "$OUT/bench_autotune.err"
log "   rc=$? $(cat "$OUT/bench_autotune.json" 2>/dev/null | head -c 200)"

log "7. 124M b=12 retest"
timeout 2400 env BENCH_BATCH=12 python bench.py > "$OUT/bench_b12.json" 2> "$OUT/bench_b12.err"
log "   rc=$? $(cat "$OUT/bench_b12.json" 2>/dev/null | head -c 200)"

log "8. sweep (350m/774m/1.5b/llama-160m/llama-1b/moe-8x124m rows)"
timeout 6000 python bench.py --sweep > "$OUT/bench_sweep.jsonl" 2> "$OUT/bench_sweep.err"
log "   rc=$? rows=$(wc -l < "$OUT/bench_sweep.jsonl" 2>/dev/null)"

log "9. long context T=4096 (B=2)"
timeout 2400 env BENCH_SEQ=4096 BENCH_BATCH=2 python bench.py > "$OUT/bench_t4096.json" 2> "$OUT/bench_t4096.err"
log "   rc=$? $(cat "$OUT/bench_t4096.json" 2>/dev/null | head -c 200)"

log "10. long context T=8192 (B=1)"
timeout 2400 env BENCH_SEQ=8192 BENCH_BATCH=1 python bench.py > "$OUT/bench_t8192.json" 2> "$OUT/bench_t8192.err"
log "   rc=$? $(cat "$OUT/bench_t8192.json" 2>/dev/null | head -c 200)"

log "11. offload execution test (TPU-gated)"
timeout 1200 python -m pytest tests/test_offload.py -q > "$OUT/offload.log" 2>&1
log "   rc=$? $(tail -1 "$OUT/offload.log")"

log "12. offload bench (1.5b HBM delta; default prefetch window 2)"
timeout 2400 env BENCH_OFFLOAD=1 BENCH_MODEL=gpt2-1.5b python bench.py > "$OUT/bench_offload.json" 2> "$OUT/bench_offload.err"
log "   rc=$? $(cat "$OUT/bench_offload.json" 2>/dev/null | head -c 200)"

log "12b. offload prefetch-window A/B at 774M (w=4 at 1.5B compiles OVER-CHIP"
log "    — 17.25 GB, round-5 AOT study — so the window A/B runs where"
log "    there is headroom)"
timeout 2400 env BENCH_OFFLOAD=1 BENCH_OFFLOAD_PREFETCH=2 BENCH_MODEL=gpt2-774m python bench.py > "$OUT/bench_offload_w2.json" 2> "$OUT/bench_offload_w2.err"
log "   774m w=2 rc=$? $(cat "$OUT/bench_offload_w2.json" 2>/dev/null | head -c 160)"
timeout 2400 env BENCH_OFFLOAD=1 BENCH_OFFLOAD_PREFETCH=4 BENCH_MODEL=gpt2-774m python bench.py > "$OUT/bench_offload_w4.json" 2> "$OUT/bench_offload_w4.err"
log "   774m w=4 rc=$? $(cat "$OUT/bench_offload_w4.json" 2>/dev/null | head -c 160)"

log "12c. offload per-op profile (async-copy bucket attribution)"
timeout 1800 python scripts/profile_step.py --model gpt2-1.5b --offload --out "$OUT/xplane_offload" > "$OUT/profile_offload.json" 2> "$OUT/profile_offload.err"
log "   rc=$? $(cat "$OUT/profile_offload.json" 2>/dev/null | head -c 300)"

log "13. heads-last FA2 A/B (round-4 experiment, see scripts/fa2_bthd_ab.py)"
timeout 1200 python scripts/fa2_bthd_ab.py > "$OUT/fa2_bthd_ab.jsonl" 2> "$OUT/fa2_bthd_ab.err"
log "   rc=$? $(cat "$OUT/fa2_bthd_ab.jsonl" 2>/dev/null | tr '\n' ' ' | head -c 300)"

log "14. MoE sort-dispatch A/B (MoEConfig.moe_dispatch; shard-local under DP since r5)"
timeout 1800 env BENCH_MODEL=moe-8x124m BENCH_MOE_DISPATCH=sort python bench.py > "$OUT/bench_moe_sort.json" 2> "$OUT/bench_moe_sort.err"
log "   rc=$? $(cat "$OUT/bench_moe_sort.json" 2>/dev/null | head -c 200)"

log "15. quantized grad-collective A/B (round-6: grad_comm int8/fp8 error-fed"
log "    reduce-scatter, parallel/comm.py — only meaningful on a multi-chip"
log "    tunnel; on 1 chip the knob records itself inert)"
# the fp32 baseline IS step 1's default bench — reuse it, don't re-burn
# the tunnel window on an identical fingerprint
cp "$OUT/bench_default.json" "$OUT/bench_gradcomm_fp32.json" 2>/dev/null \
  && log "   fp32 baseline = step 1's bench_default.json (copied)"
for gc in int8 fp8; do
  timeout 2400 env BENCH_GRAD_COMM=$gc python bench.py > "$OUT/bench_gradcomm_$gc.json" 2> "$OUT/bench_gradcomm_$gc.err"
  log "   $gc rc=$? $(cat "$OUT/bench_gradcomm_$gc.json" 2>/dev/null | head -c 160)"
done
log "15b. 2-hop hierarchical schedule (inner group 2 on a 2-chip-per-host topology;"
log "     adjust BENCH_GRAD_COMM_GROUPS to the fast-link group size)"
timeout 2400 env BENCH_GRAD_COMM=int8 BENCH_GRAD_COMM_GROUPS=2 python bench.py > "$OUT/bench_gradcomm_int8_hier.json" 2> "$OUT/bench_gradcomm_int8_hier.err"
log "   int8 2-hop rc=$? $(cat "$OUT/bench_gradcomm_int8_hier.json" 2>/dev/null | head -c 160)"

log "16. bucketed backward-overlapped grad release A/B (round-7: grad_buckets="
log "    per-layer-bucket collectives inside the backward scan vs the"
log "    monolithic after-backward sync — only meaningful multi-chip; the"
log "    overlap itself is the latency-hiding scheduler's call, so compare"
log "    step time, not just the ledger)"
for gb in 2 4; do
  timeout 2400 env BENCH_GRAD_COMM=int8 BENCH_GRAD_BUCKETS=$gb python bench.py > "$OUT/bench_gradbuckets_int8_k$gb.json" 2> "$OUT/bench_gradbuckets_int8_k$gb.err"
  log "   int8 K=$gb rc=$? $(cat "$OUT/bench_gradbuckets_int8_k$gb.json" 2>/dev/null | head -c 160)"
done
timeout 2400 env BENCH_GRAD_BUCKETS=4 python bench.py > "$OUT/bench_gradbuckets_fp32_k4.json" 2> "$OUT/bench_gradbuckets_fp32_k4.err"
log "   fp32 K=4 rc=$? $(cat "$OUT/bench_gradbuckets_fp32_k4.json" 2>/dev/null | head -c 160)"

log "17. ZeRO-3 gather-prefetch A/B (round-8: gather_prefetch= layer-ahead"
log "    weight-gather prefetch, parallel/schedule.GatherPrefetchScan — zero3"
log "    1.5B, fp32 vs fp8 gathers x prefetch off(K=1)/on(K=2); the K=1"
log "    runs are the byte-identical on-demand baselines on the SAME"
log "    Zero3 engine.  Only meaningful multi-chip (1 chip = no gathers);"
log "    extra carries the ledger's loop-resident gather wire bytes)"
for gp in 1 2; do
  timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_GATHER_PREFETCH=$gp python bench.py > "$OUT/bench_gatherpf_fp32_k$gp.json" 2> "$OUT/bench_gatherpf_fp32_k$gp.err"
  log "   fp32 K=$gp rc=$? $(cat "$OUT/bench_gatherpf_fp32_k$gp.json" 2>/dev/null | head -c 160)"
  timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_GATHER_PREFETCH=$gp BENCH_GATHER_QUANT=fp8 python bench.py > "$OUT/bench_gatherpf_fp8_k$gp.json" 2> "$OUT/bench_gatherpf_fp8_k$gp.err"
  log "   fp8 K=$gp rc=$? $(cat "$OUT/bench_gatherpf_fp8_k$gp.json" 2>/dev/null | head -c 160)"
done
log "17b. hierarchical 2-hop gather (inner group 2 — fp8 intra, bf16 inter;"
log "     adjust BENCH_GATHER_GROUPS to the fast-link group size)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_GATHER_PREFETCH=2 BENCH_GATHER_QUANT=fp8 BENCH_GATHER_GROUPS=2 python bench.py > "$OUT/bench_gatherpf_fp8_hier.json" 2> "$OUT/bench_gatherpf_fp8_hier.err"
log "   fp8 K=2 2-hop rc=$? $(cat "$OUT/bench_gatherpf_fp8_hier.json" 2>/dev/null | head -c 160)"

log "18. e2e autotune + kernel A/B (round-14: tune_e2e joint knob search,"
log "    Pallas paged-attention serve arms, fp8 matmul train arm at 124M;"
log "    the tuned plan persists in artifacts/autotune_cache.json and the"
log "    spec bench resolves spec_k from it)"
timeout 3000 env BENCH_TUNE_E2E=1 python bench.py > "$OUT/bench_tune_e2e.json" 2> "$OUT/bench_tune_e2e.err"
log "   tune_e2e rc=$? $(cat "$OUT/bench_tune_e2e.json" 2>/dev/null | head -c 240)"
for pk in on off; do
  timeout 2400 env BENCH_SERVE=1 BENCH_PAGED_KERNEL=$pk python bench.py > "$OUT/bench_serve_pk_$pk.json" 2> "$OUT/bench_serve_pk_$pk.err"
  log "   serve paged_kernel=$pk rc=$? $(cat "$OUT/bench_serve_pk_$pk.json" 2>/dev/null | head -c 160)"
done
timeout 2400 env BENCH_SPEC=1 python bench.py > "$OUT/bench_spec_tuned_k.json" 2> "$OUT/bench_spec_tuned_k.err"
log "   spec (plan-resolved spec_k) rc=$? $(cat "$OUT/bench_spec_tuned_k.json" 2>/dev/null | head -c 160)"
timeout 2400 env BENCH_FP8_MATMUL=on python bench.py > "$OUT/bench_fp8_matmul.json" 2> "$OUT/bench_fp8_matmul.err"
log "   fp8 matmul train arm rc=$? $(cat "$OUT/bench_fp8_matmul.json" 2>/dev/null | head -c 160)"
log "18b. refreshed 1.5B row (kernel-era baseline + fp8 arm)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b python bench.py > "$OUT/bench_1.5b_refresh.json" 2> "$OUT/bench_1.5b_refresh.err"
log "   1.5b rc=$? $(cat "$OUT/bench_1.5b_refresh.json" 2>/dev/null | head -c 160)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_FP8_MATMUL=on python bench.py > "$OUT/bench_1.5b_fp8.json" 2> "$OUT/bench_1.5b_fp8.err"
log "   1.5b fp8 rc=$? $(cat "$OUT/bench_1.5b_fp8.json" 2>/dev/null | head -c 160)"

log "19. composed scheduler A/B + hpZ (round-15: parallel/schedule.py —"
log "    legacy single-feature arms (steps 16/17 rows above) vs the"
log "    scheduler-composed FULL STACK in one program: ZeRO-3 +"
log "    gather_prefetch=2 + grad_buckets=2 + int8 grad comm + per-layer"
log "    health; extra.sched carries the merged program's per-slot"
log "    overlap fractions.  The hpZ row records wire_bytes_by_link +"
log "    the in-scan gather link split — before = the plain prefetch row"
log "    from step 17, after = this row (in-scan gather DCN ~0)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_SCHED_COMPOSE=1 python bench.py > "$OUT/bench_sched_compose.json" 2> "$OUT/bench_sched_compose.err"
log "   sched compose rc=$? $(cat "$OUT/bench_sched_compose.json" 2>/dev/null | head -c 200)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_HPZ=1 BENCH_GATHER_PREFETCH=2 python bench.py > "$OUT/bench_hpz.json" 2> "$OUT/bench_hpz.err"
log "   hpz rc=$? $(cat "$OUT/bench_hpz.json" 2>/dev/null | head -c 200)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_HPZ=1 BENCH_SCHED_COMPOSE=1 python bench.py > "$OUT/bench_hpz_compose.json" 2> "$OUT/bench_hpz_compose.err"
log "   hpz+compose rc=$? $(cat "$OUT/bench_hpz_compose.json" 2>/dev/null | head -c 200)"

log "20. wire-agenda close-out (round-17: quantized ZeRO-3 tail release,"
log "    qwZ fp8 hpZ rebuild, DCN-aware 'auto' sizing, and the tune_e2e"
log "    comm phase.  A/B against the step-19 rows: the tail arm's"
log "    extra.sched.zero3_tail_wire_bytes vs the fp32 transpose's, the"
log "    hpz_comm=fp8 arm's hpz_rebuild_dcn_bytes vs the step-19 hpz"
log "    row's (~4x), and the auto arm's resolved plan + measured"
log "    per-link wire vs the best hand-set row.  The re-run tune_e2e"
log "    row now also walks the comm space (multi-chip) and persists"
log "    the comm plan into artifacts/autotune_cache.json)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_SCHED_COMPOSE=1 BENCH_TAIL_QUANT=int8 python bench.py > "$OUT/bench_tail_quant.json" 2> "$OUT/bench_tail_quant.err"
log "   tail int8 rc=$? $(cat "$OUT/bench_tail_quant.json" 2>/dev/null | head -c 200)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_HPZ=1 BENCH_HPZ_COMM=fp8 BENCH_GATHER_PREFETCH=2 python bench.py > "$OUT/bench_hpz_fp8.json" 2> "$OUT/bench_hpz_fp8.err"
log "   hpz fp8 rebuild rc=$? $(cat "$OUT/bench_hpz_fp8.json" 2>/dev/null | head -c 200)"
timeout 2400 env BENCH_MODEL=gpt2-1.5b BENCH_COMM_AUTO=1 python bench.py > "$OUT/bench_comm_auto.json" 2> "$OUT/bench_comm_auto.err"
log "   comm auto rc=$? $(cat "$OUT/bench_comm_auto.json" 2>/dev/null | head -c 200)"
timeout 3000 env BENCH_TUNE_E2E=1 python bench.py > "$OUT/bench_tune_e2e_comm.json" 2> "$OUT/bench_tune_e2e_comm.err"
log "   tune_e2e (comm phase) rc=$? $(cat "$OUT/bench_tune_e2e_comm.json" 2>/dev/null | head -c 240)"

log "21. pipeline schedule A/B (round-19: table-driven interleaved /"
log "    zero-bubble schedules, parallel/pipe_schedule.py — three arms"
log "    at FIXED stages=4 and M=8 so the schedule is the only variable;"
log "    extra.sched.bubble_frac carries the compiled tick program's"
log "    idle fraction (1f1b analytic (S-1)/(M+S-1)=0.273 here) and"
log "    perf_diff sentinels it like the wire keys.  n_layer must divide"
log "    stages*virtual — the 124m default (12 layers) refuses V=2, so"
log "    these arms pin gpt2-350m (24 layers)"
for ps in 1f1b interleaved:2 zbub:2; do
  tag=$(echo "$ps" | tr ':' '_')
  timeout 2400 env BENCH_MODEL=gpt2-350m BENCH_PIPE_SCHED=$ps BENCH_PIPE_STAGES=4 BENCH_PIPE_MB=8 python bench.py > "$OUT/bench_pipe_$tag.json" 2> "$OUT/bench_pipe_$tag.err"
  log "   pipe $ps rc=$? $(cat "$OUT/bench_pipe_$tag.json" 2>/dev/null | head -c 200)"
done

log "batch complete; results in $OUT"
