#!/usr/bin/env python3
# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Serving-tier load driver: synthetic Poisson arrivals through the
continuous-batching engine, reporting aggregate tokens/s at p50/p99
per-token latency — the serving headline the ROADMAP asks for.

    # max-pressure (closed-loop) smoke on the CPU backend:
    python scripts/serve_bench.py --model tiny --cpu --requests 16 \
        --max-active 4 --closed-loop

    # open-loop Poisson at 2 req/s, with the serial generate() baseline:
    python scripts/serve_bench.py --model tiny --cpu --rate 2 --serial

    # quantized KV blocks:
    python scripts/serve_bench.py --model tiny --cpu --kv-quant int8

    # goodput under faults: slot-poison + tick-delay chaos, A/B'd
    # against the same trace fault-free (--chaos runs both passes):
    python scripts/serve_bench.py --model tiny --cpu --requests 12 \
        --closed-loop --chaos "nan@6,nan@7,delay@10" --deadline 30

Prints a human summary plus ONE machine-readable JSON line (the same
shape bench.py's BENCH_SERVE record embeds in `extra`).

Every run writes a telemetry JSONL SIDECAR (default
artifacts/serve_run.jsonl; --jsonl PATH moves it, --jsonl none disables)
the same way bench.py does: a run_meta record carrying the serve config,
per-tick `tick` records, per-request `request` records with lifecycle
events + latency components, flight records on faults, and the
telemetry summary — so every bench run replays in the dashboard
(`scripts/serve_report.py`, `scripts/report_run.py`) and the trace
viewer (`scripts/trace_view.py` -> Perfetto slot/queue tracks).  With
--chaos the faulted pass writes its OWN sidecar next to the clean one
(<path>.chaos.jsonl) with its own telemetry registry, so the A/B is two
replayable files, and the JSON summary carries both passes plus the
terminal-status counts (ok/shed/expired/failed) and p99 TTFT with and
without faults."""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="tiny")
    p.add_argument("--cpu", action="store_true", help="force CPU backend")
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--rate", type=float, default=None, metavar="RPS",
                   help="Poisson arrival rate (default: closed loop — "
                        "all requests arrive at t=0)")
    p.add_argument("--closed-loop", action="store_true",
                   help="ignore arrival times; keep the engine saturated")
    p.add_argument("--prompt-lens", default="8,16,32",
                   help="comma list the trace samples prompts from")
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--max-active", type=int, default=4)
    p.add_argument("--num-blocks", type=int, default=64)
    p.add_argument("--block-tokens", type=int, default=16)
    p.add_argument("--max-seq-tokens", type=int, default=0,
                   help="per-request length ceiling sizing the compiled "
                        "decode panel (0 = auto: max prompt + max new, "
                        "rounded to a block)")
    p.add_argument("--kv-quant", default=None, choices=("int8", "fp8"))
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--deadline", type=float, default=None, metavar="S",
                   help="per-request completion SLO in seconds; the "
                        "engine sheds unmeetable queued requests and "
                        "expires active ones that blow it")
    p.add_argument("--max-queue", type=int, default=None,
                   help="admission watermark: submissions beyond this "
                        "queue depth are shed at the door")
    p.add_argument("--shed-pool-util", type=float, default=None,
                   help="pool-pressure watermark in [0,1]: shed "
                        "submissions while the paged pool is this full "
                        "with a backlog")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="tick-fault spec, e.g. 'nan@6,delay@10,nan%%0.02'"
                        " (kinds: nan, delay, prefill, journal_kill); "
                        "runs the SAME trace fault-free first and "
                        "reports the goodput A/B")
    p.add_argument("--chaos-delay-s", type=float, default=0.25,
                   help="tick-delay fault duration")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="append the crash-recovery request journal here "
                        "(fleet mode derives per-replica paths "
                        "PATH.rN from it; default for --replicas: "
                        "artifacts/fleet_journal.jsonl)")
    p.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="fleet mode: N engine replicas behind the "
                        "SLO-aware FleetRouter (fleet/router.py); each "
                        "replica gets its own journal so a chaos "
                        "engine_kill@T (which kills replica 0) fails "
                        "over onto a sibling mid-trace")
    p.add_argument("--disagg", action="store_true",
                   help="disaggregated mode: prefill and decode on "
                        "separate engines with priced paged-KV "
                        "migration between their pools "
                        "(fleet/disagg.py); per-request migration "
                        "bytes/link land on the request records")
    p.add_argument("--spec-draft", default=None, metavar="DRAFTER",
                   help="speculative decoding drafter: 'ngram' "
                        "(model-free prompt lookup), 'model:self', or "
                        "'model:<preset>' (serving/drafter.py); greedy "
                        "output stays token-exact, committed tokens/s "
                        "is the number to compare")
    p.add_argument("--spec-k", type=int, default=4,
                   help="draft span width: up to this many tokens "
                        "proposed+verified per slot per tick")
    p.add_argument("--prefix-cache", action="store_true",
                   help="shared-prefix KV reuse: radix tree over the "
                        "refcounted pool — matched prompt blocks alias "
                        "copy-on-write, only the suffix prefills "
                        "(serving/prefix.py)")
    p.add_argument("--prefix-pool", type=int, default=0, metavar="P",
                   help="shared-prefix TRACE: draw each prompt's "
                        "leading --prefix-len tokens from P distinct "
                        "system prompts, Zipf-weighted (0 = plain "
                        "uniform trace)")
    p.add_argument("--prefix-len", type=int, default=32,
                   help="system-prompt length for --prefix-pool traces")
    p.add_argument("--zipf-a", type=float, default=1.2,
                   help="Zipf exponent over the --prefix-pool prompts")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="multi-tenant mode: comma list of "
                        "name[:weight[:tokens_per_tick[:max_queue]]] "
                        "policies (serving/tenancy.py); arrivals are "
                        "tagged by weight-proportional draw and "
                        "admission turns weighted-fair")
    p.add_argument("--tenant-weights", default=None, metavar="W",
                   help="override the ARRIVAL mix only: comma weights "
                        "aligned with --tenants order (default: the "
                        "tenants' scheduling weights)")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="SLO objective 'target=0.99,ttft=0.5,latency=5' "
                        "(telemetry/slo.py grammar; keys optional): "
                        "terminal requests feed multi-window error-"
                        "budget burn accounting, the summary gains the "
                        "budget snapshot, and a fast-burn alert flushes "
                        "the flight recorder")
    p.add_argument("--live-port", type=int, default=None, metavar="PORT",
                   help="start the live observability exporter on this "
                        "port (0 = OS-assigned; printed to stderr): "
                        "/metrics Prometheus text, /healthz per-replica "
                        "state, /slo budget JSON — host-side only, "
                        "scrape while the bench runs")
    p.add_argument("--serial", action="store_true",
                   help="also run the one-at-a-time generate() baseline "
                        "on the same trace and report the ratio")
    p.add_argument("--jsonl", default=None, metavar="PATH",
                   help="telemetry JSONL sidecar (run_meta + tick + "
                        "request records + flight/telemetry summary; "
                        "default: artifacts/serve_run.jsonl beside the "
                        "repo, 'none' disables)")
    args = p.parse_args(argv)

    jsonl_path = args.jsonl
    if jsonl_path is None:
        jsonl_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..",
            "artifacts", "serve_run.jsonl")
    elif jsonl_path.lower() == "none":
        jsonl_path = None

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from tiny_deepspeed_tpu.models import ALL_PRESETS, build_model
    from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine
    from tiny_deepspeed_tpu.serving.driver import poisson_trace, run_trace
    from tiny_deepspeed_tpu.telemetry import Telemetry

    model = build_model(args.model)
    cfg = model.config
    params = model.init(jax.random.PRNGKey(args.seed))
    prompt_lens = [int(x) for x in args.prompt_lens.split(",")]

    tenants = None
    tenant_mix = None
    if args.tenants:
        from tiny_deepspeed_tpu.serving import parse_tenant_spec
        tenants = parse_tenant_spec(args.tenants)
        tenant_mix = {n: pol.weight for n, pol in tenants.items()}
        if args.tenant_weights:
            ws = [float(x) for x in args.tenant_weights.split(",")]
            names = [e.split(":")[0] for e in args.tenants.split(",")
                     if e.strip()]
            if len(ws) != len(names):
                p.error("--tenant-weights must match --tenants count")
            tenant_mix = dict(zip(names, ws))

    if args.prefix_pool:
        from tiny_deepspeed_tpu.serving.driver import shared_prefix_trace
        suffix_lens = [max(1, pl - args.prefix_len)
                       for pl in prompt_lens]
        trace = shared_prefix_trace(
            args.requests, rate_rps=args.rate,
            prefix_pool=args.prefix_pool, prefix_len=args.prefix_len,
            suffix_lens=suffix_lens, zipf_a=args.zipf_a,
            max_new_tokens=args.max_new_tokens,
            vocab_size=cfg.vocab_size, seed=args.seed,
            deadline_s=args.deadline, tenants=tenant_mix,
        )
        prompt_lens = sorted({args.prefix_len + s for s in suffix_lens})
    else:
        trace = poisson_trace(
            args.requests, rate_rps=args.rate,
            prompt_lens=prompt_lens,
            max_new_tokens=args.max_new_tokens,
            vocab_size=cfg.vocab_size,
            seed=args.seed, deadline_s=args.deadline,
        )
        if tenant_mix:
            import numpy as _np
            trng = _np.random.default_rng(args.seed + 1)
            names = sorted(tenant_mix)
            tw = _np.asarray([tenant_mix[n] for n in names])
            tw = tw / tw.sum()
            trace = [a._replace(tenant=str(trng.choice(names, p=tw)))
                     for a in trace]

    tel = Telemetry()

    bt = args.block_tokens
    max_seq = args.max_seq_tokens or min(
        cfg.block_size,
        -(-(max(prompt_lens) + args.max_new_tokens) // bt) * bt,
    )

    serve_cfg = ServeConfig(
        max_active=args.max_active, num_blocks=args.num_blocks,
        block_tokens=bt, quant=args.kv_quant,
        temperature=args.temperature, top_k=args.top_k,
        seed=args.seed, max_seq_tokens=max_seq,
        max_queue=args.max_queue, shed_pool_util=args.shed_pool_util,
        spec_draft=args.spec_draft, spec_k=args.spec_k,
        prefix_cache=args.prefix_cache, tenants=tenants,
    )
    realtime = not args.closed_loop and args.rate is not None

    def make_logger(path):
        """Sidecar writer: run_meta first (schema stamp + the serve
        geometry trace_view.py lays slot tracks out from), the engine
        streams tick/request/flight records behind it."""
        if not path:
            return None
        from tiny_deepspeed_tpu.telemetry.schema import SCHEMA_VERSION
        from tiny_deepspeed_tpu.utils.profiling import MetricsLogger
        if os.path.exists(path):
            os.remove(path)
        lg = MetricsLogger(path, stdout=False)
        lg.log_meta(schema_version=SCHEMA_VERSION,
                    engine=f"serve:{args.model}",
                    model=args.model, devices=jax.device_count(),
                    serve=dict(
                        max_active=args.max_active,
                        num_blocks=args.num_blocks, block_tokens=bt,
                        max_seq_tokens=max_seq,
                        quant=args.kv_quant or "off",
                        spec_draft=args.spec_draft or "off",
                        spec_k=args.spec_k,
                        replicas=args.replicas,
                        disagg=bool(args.disagg),
                        prefix_cache=bool(args.prefix_cache),
                        tenants={n: {"weight": pol.weight,
                                     "tokens_per_tick":
                                         pol.tokens_per_tick,
                                     "max_queue": pol.max_queue}
                                 for n, pol in (tenants or {}).items()},
                    ))
        return lg

    # CLI validation BEFORE the sidecar writer truncates anything: an
    # invalid invocation must not destroy the previous run's records
    if args.replicas < 1:
        p.error("--replicas must be >= 1")
    if args.disagg and args.replicas > 1:
        p.error("--disagg and --replicas are separate modes (a fleet "
                "of disagg pairs is not wired yet)")
    if args.disagg and args.chaos:
        p.error("--chaos targets a single engine or fleet replica 0; "
                "not supported with --disagg")
    if args.disagg and args.spec_draft:
        p.error("--disagg does not compose with --spec-draft (drafter "
                "state only rebuilds through the prefill admission "
                "path)")
    if args.prefix_cache and args.spec_draft:
        p.error("--prefix-cache does not compose with --spec-draft "
                "(the suffix prefill and the draft span both own the "
                "span program)")
    if (args.chaos and "journal_kill" in args.chaos
            and not args.journal and args.replicas == 1):
        p.error("--chaos journal_kill@N needs --journal PATH (the kill "
                "fires inside the journal's commit, and recovery "
                "replays it); fleet mode auto-assigns journals")
    slo_obj = None
    if args.slo:
        from tiny_deepspeed_tpu.telemetry.slo import SLOObjective
        try:
            slo_obj = SLOObjective.parse(args.slo)
        except ValueError as e:
            p.error(f"--slo: {e}")

    logger = make_logger(jsonl_path)

    # the live plane attaches to the MEASURED pass only (warm requests
    # pollute neither the aggregator nor the SLO budget, same contract
    # as telemetry/logger); the exporter is a loopback daemon thread —
    # strictly host-side, so serving HLO and tick cadence are untouched
    slo_tracker = None
    live_agg = None
    exporter = None
    if args.slo or args.live_port is not None:
        from tiny_deepspeed_tpu.telemetry.slo import SLOTracker
        from tiny_deepspeed_tpu.telemetry.slo import SLOObjective as _Obj
        slo_tracker = SLOTracker(default=slo_obj or _Obj())
    if args.live_port is not None:
        from tiny_deepspeed_tpu.telemetry.live import (
            LiveAggregator, LiveExporter,
        )
        live_agg = LiveAggregator()
        exporter = LiveExporter(live_agg, slo=slo_tracker,
                                port=args.live_port)
        port = exporter.start()
        print(f"live exporter -> http://127.0.0.1:{port}/metrics "
              "(also /healthz, /slo)", file=sys.stderr)

    # warm run on the SAME engine (each engine owns fresh jit closures,
    # so warming a throwaway one buys nothing): one request per DISTINCT
    # prompt length covers every power-of-two prefill bucket, closed-loop
    # covers the decode step — the measured pass then reports serving
    # throughput, not XLA compile time.  Telemetry/logger/journal attach
    # after, so warm requests pollute neither counters, the JSONL, nor
    # the crash-recovery write-ahead log.
    from tiny_deepspeed_tpu.serving import RequestJournal
    from tiny_deepspeed_tpu.serving.driver import Arrival

    warm_trace = [
        Arrival(0.0, [0] * plen, min(2, args.max_new_tokens))
        for plen in sorted(set(prompt_lens))
    ]
    if args.prefix_cache:
        # a SECOND identical-prompt request per length hits the tree
        # and compiles the suffix-prefill bucket — without it the
        # measured pass pays that XLA compile on its first cache hit
        warm_trace = [a for a in warm_trace for _ in range(2)]

    def warmed_engine(journal_path=None, replica_id=None):
        e = ServingEngine(model, params, serve_cfg,
                          replica_id=replica_id)
        run_trace(e, warm_trace, realtime=False)
        if e._prefix is not None:
            # warm requests compiled the suffix program (and may sit
            # warm in the tree), but the measured pass's hit-rate
            # stats must price the TRACE only
            e._prefix.reset_stats()
        if journal_path:
            e.journal = RequestJournal(journal_path)
        return e

    def replica_journal(i, tag=""):
        base = args.journal or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..",
            "artifacts", "fleet_journal.jsonl")
        root, ext = os.path.splitext(base)
        path = f"{root}{tag}.r{i}{ext or '.jsonl'}"
        # per-run scratch, like the sidecar: journals open in APPEND
        # mode (recovery continues one file), so a stale file from the
        # previous invocation would resurrect ITS interrupted requests
        # at this run's first failover — and pin this run to its
        # geometry stamp
        if os.path.exists(path):
            os.remove(path)
        return path

    def build_target(telemetry, logger, chaos=None, tag=""):
        """The measured object for this pass: a single engine, a fleet
        router over N warmed replicas, or a disaggregated pair —
        telemetry/logger attached AFTER warm in every mode, so warm
        requests pollute neither counters nor the sidecar."""
        if args.disagg:
            from tiny_deepspeed_tpu.fleet import DisaggEngine
            dis = DisaggEngine(model, params, serve_cfg)
            run_trace(dis, warm_trace, realtime=False)
            # warm requests migrated too — zero the counters so the
            # summary prices the MEASURED trace only (their records
            # never reached the sidecar: logger AND journal attach
            # after warm, same as the other modes — warm requests
            # must not enter the crash-recovery WAL either)
            dis.migrations = 0
            dis.migrated_bytes = 0
            dis.bytes_by_link = {}
            j = RequestJournal(args.journal) if args.journal else None
            for e in (dis.prefill, dis.decode):
                e.telemetry, e.logger = telemetry, logger
                e.journal = j  # shared WAL (geometry stamped per attach)
            dis.telemetry = telemetry
            return dis
        if args.replicas > 1:
            from tiny_deepspeed_tpu.fleet import FleetRouter
            from tiny_deepspeed_tpu.resilience import ChaosServingEngine
            engines = []
            for i in range(args.replicas):
                e = warmed_engine(replica_journal(i, tag), replica_id=i)
                e.telemetry, e.logger = telemetry, logger
                engines.append(e)
            if chaos is not None:
                # chaos faults target replica 0 — an engine_kill there
                # exercises the failover path while siblings keep
                # serving
                engines[0] = ChaosServingEngine(engines[0], chaos)
            # parallel ticks: replicas are independent engines and XLA
            # releases the GIL mid-program — on a multi-core host this
            # is where replica-count scaling comes from
            return FleetRouter(engines, telemetry=telemetry,
                               logger=logger, parallel=True)
        e = warmed_engine(args.journal)
        e.telemetry, e.logger = telemetry, logger
        return e

    eng = build_target(tel, logger)
    res = run_trace(eng, trace, realtime=realtime,
                    slo=slo_tracker, live=live_agg)
    res.pop("outputs")
    res.pop("requests")
    if slo_tracker is not None and logger is not None:
        # final budget snapshot as an `slo` record: the engine only
        # emits one when an alert fires, but serve_report's "SLO
        # budgets" section needs the end-of-run state on clean runs too
        slo_tracker.record(logger)

    summary = {
        "model": args.model,
        "requests": args.requests,
        "rate_rps": args.rate,
        "max_active": args.max_active,
        "kv_quant": args.kv_quant,
        "deadline_s": args.deadline,
        "tokens_per_s": res["tokens_per_s"],
        "ok_tokens_per_s": res["ok_tokens_per_s"],
        "status_counts": res["status_counts"],
        "restarts": res["restarts"],
        "token_latency": res["token_latency"],
        "ttft": res["ttft"],
        "latency_components_s": res["latency_components_s"],
        "mean_occupancy": res["mean_occupancy"],
        "mean_pool_utilization": res["mean_pool_utilization"],
        "evictions": res["evictions"],
        "preemptions": res["preemptions"],
        "pool": eng.pool.kv_bytes(),
    }
    if "spec" in res:
        summary["spec"] = dict(res["spec"], drafter=args.spec_draft,
                               k=args.spec_k)
    if args.replicas > 1:
        summary["fleet"] = {
            "replicas": args.replicas,
            "replicas_live": len(eng._live()),
            "failovers": eng.failovers,
            "dispatch": {str(k): v
                         for k, v in eng.dispatch_counts().items()},
        }
    if args.disagg:
        summary["disagg"] = eng.migration_summary()
    if "prefix_cache" in res:
        summary["prefix_cache"] = res["prefix_cache"]
    if "tenants" in res:
        summary["tenants"] = res["tenants"]
    if "slo" in res:
        # the budget snapshot rides the machine-readable line, so
        # bench.py's BENCH_SERVE extra carries slo.attainment — the
        # higher-is-better key perf_diff.py's sentinel watches
        summary["slo"] = res["slo"]

    if args.chaos:
        # goodput under faults, A/B on the SAME trace: the clean pass
        # above is the baseline; this pass injects the tick faults
        from tiny_deepspeed_tpu.resilience import (
            ChaosServingEngine, parse_serving_chaos,
        )
        from tiny_deepspeed_tpu.serving import ServingKilled
        chaos = parse_serving_chaos(args.chaos, seed=args.seed,
                                    delay_s=args.chaos_delay_s)
        # the faulted pass gets its OWN sidecar + telemetry registry:
        # two replayable files (clean vs chaos) make the A/B a pair of
        # serve_report.py dashboards instead of one entangled stream
        chaos_jsonl = None
        if jsonl_path:
            root, ext = os.path.splitext(jsonl_path)
            chaos_jsonl = f"{root}.chaos{ext or '.jsonl'}"
        tel2 = Telemetry()
        logger2 = make_logger(chaos_jsonl)
        if args.replicas > 1:
            # fleet: the router ITSELF absorbs replica death (incl.
            # engine_kill / journal_kill on replica 0) by journal-replay
            # failover — the A/B shows the goodput cost of losing and
            # recovering a whole engine mid-trace
            ceng = build_target(tel2, logger2, chaos=chaos,
                                tag=".chaos")
        else:
            ceng = ChaosServingEngine(build_target(tel2, logger2),
                                      chaos)
        try:
            cres = run_trace(ceng, trace, realtime=realtime)
        except ServingKilled:
            # In fleet mode the router absorbs replica deaths by
            # failover; a ServingKilled escaping run_trace means the
            # LAST live replica died — total fleet loss is a real
            # outcome, and a FleetRouter has no recover() to pretend
            # otherwise with
            if args.replicas > 1:
                raise
            # the journal_kill fault "killed" the engine mid-commit;
            # demonstrate the recovery recipe end-to-end: a fresh
            # engine replays the journal and finishes the in-flight
            # requests (arrivals not yet submitted died with the
            # process, exactly as a real crash loses them)
            reng = build_target(tel2, logger2)
            rec = reng.recover()
            reng.drain()
            cres = None
            summary["chaos"] = {
                "spec": args.chaos,
                "journal_killed": True,
                "recovered": len(rec),
                "recovered_ok": sum(1 for r in rec
                                    if r.status == "ok"),
            }
        n_faults = len(chaos.injected)
        if logger2 is not None:
            chaos.log_faults(logger2)
            tel2.flush(logger2)
            logger2.close()
            print(f"chaos-pass records -> {chaos_jsonl}",
                  file=sys.stderr)
        if cres is not None:
            summary["chaos"] = {
                "spec": args.chaos,
                "faults_injected": n_faults,
                "tokens_per_s": cres["tokens_per_s"],
                "ok_tokens_per_s": cres["ok_tokens_per_s"],
                "status_counts": cres["status_counts"],
                "restarts": cres["restarts"],
                "ttft_p99_ms": cres["ttft"]["p99_ms"],
                "ttft_p99_ms_clean": res["ttft"]["p99_ms"],
                "goodput_frac": round(
                    cres["ok_tokens_per_s"]
                    / max(res["ok_tokens_per_s"], 1e-9), 3),
            }
            if args.replicas > 1:
                summary["chaos"]["failovers"] = ceng.failovers
                summary["chaos"]["replicas_live"] = len(ceng._live())
    if args.serial:
        from tiny_deepspeed_tpu.serving.driver import run_serial
        ser = run_serial(model, params, trace,
                         temperature=args.temperature, top_k=args.top_k)
        summary["serial_tokens_per_s"] = ser["tokens_per_s"]
        summary["vs_serial"] = round(
            res["tokens_per_s"] / max(ser["tokens_per_s"], 1e-9), 3)

    sc = res["status_counts"]
    print(f"served {args.requests} requests, {res['tokens']} tokens in "
          f"{res['wall_s']}s -> {res['tokens_per_s']} tok/s "
          f"(occupancy {res['mean_occupancy']:.2f}, "
          f"p50 {res['token_latency']['p50_ms']}ms / "
          f"p99 {res['token_latency']['p99_ms']}ms per token)")
    print(f"outcomes: ok {sc['ok']} / shed {sc['shed']} / "
          f"expired {sc['expired']} / failed {sc['failed']} "
          f"(goodput {res['ok_tokens_per_s']} tok/s)")
    if args.replicas > 1:
        fl = summary["fleet"]
        print(f"fleet: {fl['replicas_live']}/{fl['replicas']} replicas "
              f"live, dispatch {fl['dispatch']}, "
              f"failovers {fl['failovers']}")
    if args.disagg:
        dg = summary["disagg"]
        print(f"disagg: {dg['migrations']} prefill->decode migrations, "
              f"{dg['migrated_bytes'] / 1024:.1f} KiB KV moved "
              f"({dg['bytes_by_link']})")
    if "spec" in summary:
        sp = summary["spec"]
        print(f"speculation [{sp['drafter']} k={sp['k']}]: "
              f"accept rate {sp['accept_rate']} "
              f"({sp['accepted']}/{sp['proposed']} drafts)")
    if "prefix_cache" in summary:
        pc = summary["prefix_cache"]
        print(f"prefix cache: hit rate {pc['hit_rate']} "
              f"({pc['blocks_aliased']} blocks aliased, "
              f"{pc['prefill_tokens_avoided']} prefill tokens avoided, "
              f"{pc['cached_blocks']} warm, "
              f"{pc['tree_evictions']} tree evictions)")
    if "tenants" in summary:
        for name, td in sorted(summary["tenants"].items()):
            sc_t = td["status_counts"]
            bu = td.get("scheduler", {}).get("budget_utilization")
            print(f"tenant {name}: {td['requests']} req "
                  f"(ok {sc_t['ok']} / shed {sc_t['shed']} / expired "
                  f"{sc_t['expired']}), goodput "
                  f"{td['ok_tokens_per_s']} tok/s, p99 TTFT "
                  f"{td['ttft']['p99_ms']}ms"
                  + (f", budget util {bu}" if bu is not None else ""))
    if args.chaos:
        ch = summary["chaos"]
        if ch.get("journal_killed"):
            print(f"chaos [{ch['spec']}]: engine killed between "
                  f"journal append and commit; recovered "
                  f"{ch['recovered']} in-flight request(s) from "
                  f"{args.journal} -> {ch['recovered_ok']} ok")
        else:
            cc = ch["status_counts"]
            fo = (f", {ch['failovers']} failover(s) "
                  f"({ch['replicas_live']}/{args.replicas} replicas "
                  "left)" if "failovers" in ch else "")
            print(f"chaos [{ch['spec']}]: {ch['faults_injected']} "
                  f"faults, {ch['restarts']} restarts{fo} -> ok "
                  f"{cc['ok']} "
                  f"/ shed {cc['shed']} / expired {cc['expired']} / "
                  f"failed {cc['failed']}; goodput "
                  f"{ch['ok_tokens_per_s']} tok/s "
                  f"({ch['goodput_frac']}x clean), p99 TTFT "
                  f"{ch['ttft_p99_ms']}ms vs {ch['ttft_p99_ms_clean']}"
                  "ms clean")
    if args.serial:
        print(f"serial generate() baseline: "
              f"{summary['serial_tokens_per_s']} tok/s -> "
              f"{summary['vs_serial']}x")
    if "slo" in summary:
        sl = summary["slo"]
        print(f"slo: attainment {sl['attainment']}, "
              f"{len(sl['alerts'])} alert(s) "
              f"(windows {sl['windows_s']}s)")
    if exporter is not None:
        agg_snap = live_agg.snapshot()
        print(f"live exporter served {live_agg.scrapes} scrape(s), "
              f"aggregated {sum(agg_snap['ticks'].values())} tick "
              f"snapshot(s) across {len(agg_snap['ticks'])} replica "
              "stream(s)", file=sys.stderr)
        exporter.stop()
    print(json.dumps(summary))

    if logger is not None:
        tel.flush(logger)
        logger.close()
        print(
            f"sidecar -> {jsonl_path}  (dashboard: python "
            f"scripts/serve_report.py {jsonl_path}; timeline: python "
            f"scripts/trace_view.py {jsonl_path})", file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
