# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Render a serving run's metrics JSONL into a markdown dashboard:
tail-latency ATTRIBUTION, not just percentiles.

    python scripts/serve_report.py RUN.jsonl [-o REPORT.md]

The JSONL comes from `scripts/serve_bench.py`'s sidecar (or any
`ServingEngine` run with a MetricsLogger attached); the record schema is
`tiny_deepspeed_tpu/telemetry/schema.py` (v6: per-request latency
components + per-tick time series).  The dashboard answers the
operational questions the percentile headline cannot:

  * p50/p95/p99 TTFT and end-to-end latency — and, for the requests in
    the p99 latency tail, WHICH component they paid (queue-wait /
    prefill / decode-active / preempted-wait / restart-overhead): a
    quarantine-induced p99 names restart-overhead, an overload-induced
    one names queue-wait.
  * SLO headroom histogram (deadline - latency, served requests only):
    how close the tier ran to its promises, violations included.
  * shed-reason audit: watermark refusals vs deadline-overdue vs
    deadline-unmeetable — the three mean different capacity actions
    (raise the pool / fix arrival bursts / fix the SLO).
  * goodput over a rolling window (tokens of "ok" requests per second),
    min/mean/max — a restart shows up as the min-window dip even when
    the whole-run average looks fine.
  * per-tick time series summary: tick-wall split (host scheduling vs
    prefill vs decode dispatch vs token fetch), occupancy / pool /
    queue-depth ranges, and the fault counters.
  * fleet section (schema v8, fleet/disagg runs): per-replica request/
    goodput/p99 breakdown keyed on `replica_id`, the router's failover
    fault records, and the disaggregated prefill->decode KV-migration
    totals (measured bytes, by ICI/DCN link class).
  * tenancy table (schema v9, tenant-tagged runs): per-tenant goodput,
    p99 TTFT/latency, shed-by-reason, and budget utilization (from the
    run_meta tenant policies x tick count).
  * prefix-cache section (schema v9, prefix-cache runs): blocks
    aliased, prefill tokens avoided / hit rate from the request
    records, and the refcount-measured pool bytes saved from the
    telemetry summary gauges.
  * SLO budgets section (schema v15, SLO-configured runs): per-tenant
    attainment, error-budget spend and multi-window burn rates from the
    engine's `slo` records, plus every burn alert fired over the run —
    the tail table above names the component, this section names the
    tenant whose budget paid for it.  Cross-engine tails split too:
    `comp_migrate_s` is the prefill->decode handoff wait, a re-prefill
    on the decode engine lands in prefill/restart-overhead.
  * fleet runs additionally get a per-replica gauge table from the
    `name{replica=N}` labeled gauge keys (schema v15) — parallel
    replicas no longer overwrite each other's last-tick state.

Exit codes: 0 ok; 1 parse errors in the JSONL (partial report rendered);
2 missing/empty input or no serving records at all.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# latency-component record fields -> dashboard labels, in partition
# order; comp_migrate_s (schema v15) appears only on disagg-migrated
# requests — a cross-engine tail names migration-wait when the request
# queued at the prefill->decode handoff, prefill when it re-prefilled
# on the decode engine after a preemption/restart there
COMPONENTS = (
    ("comp_queue_s", "queue-wait"),
    ("comp_prefill_s", "prefill"),
    ("comp_decode_s", "decode-active"),
    ("comp_preempt_s", "preempted-wait"),
    ("comp_restart_s", "restart-overhead"),
    ("comp_migrate_s", "migration-wait"),
)


def _load_trace_module():
    """telemetry/trace.py by file path (same trick as trace_view.py):
    the loader is pure-python and the dashboard must not pay a jax
    import to reshuffle JSONL."""
    spec = importlib.util.spec_from_file_location(
        "tiny_deepspeed_tpu_trace_for_serve_report",
        os.path.join(_REPO, "tiny_deepspeed_tpu", "telemetry", "trace.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_live_module():
    """telemetry/live.py by file path (same trick): pure stdlib, and
    the dashboard needs its parse_gauge_key to split label-qualified
    gauge keys (schema v15) back into (base, {replica: N})."""
    spec = importlib.util.spec_from_file_location(
        "tiny_deepspeed_tpu_live_for_serve_report",
        os.path.join(_REPO, "tiny_deepspeed_tpu", "telemetry", "live.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace = _load_trace_module()
live = _load_live_module()
# ONE quantile implementation for the jax-free scripts (the loaded
# trace module's copy) — report_run.py's percentiles come from the same
# formula via utils/profiling._quantile
_quantile = trace._quantile


def _ms(s: float) -> str:
    return f"{s * 1e3:.1f} ms"


def _pcts(xs: List[float]) -> str:
    return (f"p50 {_ms(_quantile(xs, 0.5))}, "
            f"p95 {_ms(_quantile(xs, 0.95))}, "
            f"p99 {_ms(_quantile(xs, 0.99))}, "
            f"max {_ms(max(xs))}")


def _histogram_ascii(xs: List[float], bins: int = 8,
                     width: int = 24) -> List[str]:
    """Small fixed-width ASCII histogram (markdown code block lines)."""
    lo, hi = min(xs), max(xs)
    if hi <= lo:
        hi = lo + 1e-9
    counts = [0] * bins
    for x in xs:
        i = min(bins - 1, int((x - lo) / (hi - lo) * bins))
        counts[i] += 1
    peak = max(counts) or 1
    out = []
    for i, c in enumerate(counts):
        b0 = lo + (hi - lo) * i / bins
        b1 = lo + (hi - lo) * (i + 1) / bins
        bar = "#" * max(1 if c else 0, round(c / peak * width))
        out.append(f"[{b0 * 1e3:+9.1f}, {b1 * 1e3:+9.1f}) ms "
                   f"{bar:<{width}} {c}")
    return out


def render_serve_report(metas: List[dict], source: str = "") -> str:
    run = next((m for m in metas if m.get("kind") == "run_meta"), {})
    reqs = [m for m in metas if m.get("kind") == "request"]
    ticks = [m for m in metas if m.get("kind") == "tick"]
    out: List[str] = []
    title = run.get("model") or run.get("engine") \
        or os.path.basename(source) or "serving run"
    out.append(f"# Serving report — {title}\n")
    if source:
        out.append(f"Source: `{source}`\n")

    if run:
        out.append("## Run\n")
        for label, key in (("engine", "engine"), ("model", "model"),
                           ("devices", "devices")):
            if key in run:
                out.append(f"- {label}: {run[key]}")
        serve = run.get("serve") or {}
        if serve:
            out.append("- serve config: " + ", ".join(
                f"{k}={v}" for k, v in sorted(serve.items())))
        # roofline verdict from the HLO cost ledger (utils/hlo_cost),
        # when the run captured one — decode programs are the textbook
        # hbm-bound case (weights re-read every token), so the verdict
        # + top cost center name WHAT dominates the token loop
        cost = run.get("hlo_cost") or {}
        if cost.get("bound"):
            out.append(
                f"- roofline: **{cost['bound']}-bound** "
                f"(AI {cost.get('arithmetic_intensity', 0.0):.1f} "
                f"FLOPs/byte vs ridge "
                f"{cost.get('ridge_intensity', 0.0):.1f}; "
                f"{cost.get('total_flops', 0.0):.2e} FLOPs, "
                f"{cost.get('hbm_bytes', 0.0):.2e} HBM bytes per "
                f"program)"
            )
            centers = cost.get("top_cost_centers") or []
            for c in centers[:3]:
                out.append(
                    f"  - {c.get('share', 0.0):.0%} `{c.get('sig', '?')}`"
                )
        out.append("")

    # -- outcomes -----------------------------------------------------------
    by_status: Dict[str, int] = {}
    for r in reqs:
        by_status[r.get("status", "?")] = \
            by_status.get(r.get("status", "?"), 0) + 1
    out.append("## Requests\n")
    out.append(f"- terminal records: {len(reqs)} (" + ", ".join(
        f"{k} {v}" for k, v in sorted(by_status.items())) + ")")
    toks = sum(r.get("new_tokens", 0) for r in reqs)
    ok_toks = sum(r.get("new_tokens", 0) for r in reqs
                  if r.get("status") == "ok")
    out.append(f"- tokens produced: {toks} ({ok_toks} to requests that "
               "finished ok)")
    preempts = sum(r.get("preemptions", 0) for r in reqs)
    if preempts:
        out.append(f"- preemptions: {preempts}")
    out.append("")

    # -- latency + tail attribution ----------------------------------------
    served = [r for r in reqs if r.get("status") != "shed"
              and isinstance(r.get("lat_s"), (int, float))]
    ttfts = [r["ttft_s"] for r in reqs
             if isinstance(r.get("ttft_s"), (int, float))]
    if ttfts:
        out.append("## Latency\n")
        out.append(f"- TTFT: {_pcts(ttfts)}")
    if served:
        lats = [r["lat_s"] for r in served]
        out.append(f"- end-to-end latency (served requests): "
                   f"{_pcts(lats)}")
        out.append("")
        p99 = _quantile(lats, 0.99)
        tail = [r for r in served if r["lat_s"] >= p99] or \
            [max(served, key=lambda r: r["lat_s"])]
        out.append("### Tail attribution (p99 and above, "
                   f"{len(tail)} request(s))\n")
        out.append("What the slowest requests actually paid for — the "
                   "components partition each request's latency "
                   "(engine-pinned: they sum to lat_s), so the biggest "
                   "share IS the cause:\n")
        out.append("| component | tail mean | tail share | all-request "
                   "p99 |")
        out.append("|---|---|---|---|")
        tail_lat = sum(r["lat_s"] for r in tail) or 1e-9
        shares = []
        for key, label in COMPONENTS:
            tot = sum(float(r.get(key, 0.0)) for r in tail)
            all_p99 = _quantile(
                [float(r.get(key, 0.0)) for r in served], 0.99)
            shares.append((tot / tail_lat, label, tot, all_p99))
        for share, label, tot, all_p99 in sorted(shares, reverse=True):
            out.append(f"| {label} | {_ms(tot / len(tail))} | "
                       f"{share:.0%} | {_ms(all_p99)} |")
        top = max(shares)
        out.append(
            f"\np99 verdict: **{top[1]}** dominates the tail "
            f"({top[0]:.0%} of tail latency).\n"
        )

    # -- speculation --------------------------------------------------------
    spec_reqs = [r for r in reqs
                 if isinstance(r.get("spec_proposed"), int)]
    if spec_reqs:
        proposed = sum(r["spec_proposed"] for r in spec_reqs)
        accepted = sum(r.get("spec_accepted", 0) for r in spec_reqs)
        out.append("## Speculation\n")
        out.append(f"- drafts proposed {proposed}, accepted {accepted} "
                   f"(accept rate "
                   f"{accepted / max(1, proposed):.2f}) — the committed "
                   "sequences are target-exact regardless; the rate "
                   "decides whether the draft+verify walls pay")
        rates = sorted(
            r.get("spec_accepted", 0) / max(1, r["spec_proposed"])
            for r in spec_reqs if r["spec_proposed"])
        if rates:
            out.append(
                f"- per-request accept rate: min {rates[0]:.2f}, "
                f"median {_quantile(rates, 0.5):.2f}, "
                f"max {rates[-1]:.2f}")
        draft = sum(float(t.get("draft_s", 0.0)) for t in ticks)
        verify = sum(float(t.get("decode_s", 0.0))
                     + float(t.get("fetch_s", 0.0)) for t in ticks)
        if draft or verify:
            out.append(f"- draft vs verify wall: {draft:.3f} s vs "
                       f"{verify:.3f} s "
                       f"({draft / max(draft + verify, 1e-9):.0%} of "
                       "decode time spent drafting)")
        out.append("")

    # -- tenancy ------------------------------------------------------------
    by_tenant: Dict[str, List[dict]] = {}
    for r in reqs:
        if isinstance(r.get("tenant"), str):
            by_tenant.setdefault(r["tenant"], []).append(r)
    if by_tenant:
        run_tenants = (run.get("serve") or {}).get("tenants") or {}
        # tick records are SAMPLED — the highest tick INDEX (+1) is the
        # real tick count the budget accrued over, not the record count
        tick_idx = [t["tick"] for t in ticks
                    if isinstance(t.get("tick"), int)]
        n_ticks = max(tick_idx) + 1 if tick_idx else None
        out.append("## Tenancy\n")
        out.append("| tenant | requests | ok | goodput tokens | "
                   "p99 TTFT | p99 latency | shed by reason | "
                   "budget util (est.) |")
        out.append("|---|---|---|---|---|---|---|---|")
        for name in sorted(by_tenant):
            rs = by_tenant[name]
            oks = [r for r in rs if r.get("status") == "ok"]
            ttfts = [r["ttft_s"] for r in rs
                     if isinstance(r.get("ttft_s"), (int, float))]
            lats = [r["lat_s"] for r in rs
                    if isinstance(r.get("lat_s"), (int, float))
                    and r.get("status") != "shed"]
            sheds: Dict[str, int] = {}
            for r in rs:
                fin = str(r.get("finish", ""))
                if r.get("status") == "shed" and fin.startswith("shed:"):
                    key = fin.split(":", 1)[1]
                    sheds[key] = sheds.get(key, 0) + 1
            shed_s = ", ".join(f"{k} {v}"
                               for k, v in sorted(sheds.items())) or "-"
            # budget utilization: admitted token cost over the budget
            # the run's tick count granted (run_meta carries the
            # policy; only computable when a budget is configured)
            pol = run_tenants.get(name) or {}
            rate = pol.get("tokens_per_tick")
            util = "-"
            if rate and n_ticks:
                admitted = sum(
                    r.get("prompt_tokens", 0) + r.get("new_tokens", 0)
                    for r in rs if r.get("status") != "shed")
                util = f"{admitted / (rate * n_ticks):.0%}"
            out.append(
                f"| {name} | {len(rs)} | {len(oks)} | "
                f"{sum(r.get('new_tokens', 0) for r in oks)} | "
                f"{_ms(_quantile(ttfts, 0.99)) if ttfts else '-'} | "
                f"{_ms(_quantile(lats, 0.99)) if lats else '-'} | "
                f"{shed_s} | {util} |")
        out.append("")
        if any((run_tenants.get(n) or {}).get("tokens_per_tick")
               for n in by_tenant):
            out.append(
                "Budget util here is an ESTIMATE from delivered "
                "tokens over rate x ticks — the scheduler's measured "
                "number (admission cost = prompt + max_new per "
                "admission, resumes included) is the "
                "`budget_utilization` in the bench JSON's per-tenant "
                "scheduler stats.\n")

    # -- prefix cache -------------------------------------------------------
    pc_reqs = [r for r in reqs
               if isinstance(r.get("prefix_blocks"), int)]
    gauges = {}
    for m in metas:
        if m.get("kind") == "telemetry_summary" \
                and isinstance(m.get("gauges"), dict):
            gauges.update(m["gauges"])
    if pc_reqs or any(k.startswith("serve_prefix_") for k in gauges):
        aliased = sum(r.get("prefix_blocks", 0) for r in pc_reqs)
        avoided = sum(r.get("prefix_tokens", 0) for r in pc_reqs)
        prompts = sum(r.get("prompt_tokens", 0) for r in pc_reqs
                      if r.get("status") != "shed")
        # the engine's own gauge uses per-ADMISSION prompt tokens in
        # the denominator; the record-derived fallback counts each
        # request's prompt once while prefix_tokens accumulates over
        # re-admissions, so it is clamped (a preempted-and-rehit
        # request could otherwise push it past 100%)
        rate = gauges.get("serve_prefix_hit_rate")
        if rate is None:
            rate = min(1.0, avoided / max(1, prompts))
        out.append("## Prefix cache\n")
        out.append(f"- blocks aliased: {aliased}, prefill tokens "
                   f"avoided: {avoided} (hit rate {rate:.0%} of "
                   "admitted prompt tokens)")
        hits = sum(1 for r in pc_reqs if r.get("prefix_blocks", 0) > 0)
        out.append(f"- requests that hit: {hits}/{len(pc_reqs)}")
        saved = gauges.get("serve_prefix_pool_saved_bytes")
        if saved:
            out.append(
                f"- pool bytes saved by sharing at last tick: "
                f"{saved / 1024:.1f} KiB — measured from block "
                "refcounts (each holder beyond a block's first), not "
                "modeled")
        warm = gauges.get("serve_prefix_cached_blocks")
        if warm is not None:
            out.append(f"- warm blocks held by the radix tree at last "
                       f"tick: {warm:.0f}")
        out.append("")

    # -- fleet --------------------------------------------------------------
    by_rep: Dict[int, List[dict]] = {}
    for r in reqs:
        if isinstance(r.get("replica_id"), int):
            by_rep.setdefault(r["replica_id"], []).append(r)
    failovers = [m for m in metas if m.get("kind") == "fault"
                 and m.get("fault") == "fleet_failover"]
    migrated = [r for r in reqs
                if isinstance(r.get("kv_migration_bytes"), int)]
    if by_rep or failovers or migrated:
        out.append("## Fleet\n")
        if by_rep:
            out.append("| replica | requests | ok | tokens | "
                       "p99 latency |")
            out.append("|---|---|---|---|---|")
            for rid in sorted(by_rep):
                rs = by_rep[rid]
                oks = [r for r in rs if r.get("status") == "ok"]
                lats = [r["lat_s"] for r in rs
                        if isinstance(r.get("lat_s"), (int, float))]
                out.append(
                    f"| {rid} | {len(rs)} | {len(oks)} | "
                    f"{sum(r.get('new_tokens', 0) for r in rs)} | "
                    f"{_ms(_quantile(lats, 0.99)) if lats else '-'} |")
            out.append("")
        # per-replica labeled gauges (schema v15): each replica writes
        # `name{replica=N}` into the SHARED registry, so the fleet's
        # last telemetry_summary carries every replica's last-tick
        # state side by side instead of last-writer-wins
        rep_gauges: Dict[str, Dict[str, float]] = {}
        for key, v in gauges.items():
            base, labels = live.parse_gauge_key(key)
            if "replica" in labels and isinstance(v, (int, float)):
                rep_gauges.setdefault(
                    labels["replica"], {})[base] = float(v)
        if rep_gauges:
            cols = (("serve_queue_depth", "queue"),
                    ("serve_batch_occupancy", "occupancy"),
                    ("serve_pool_utilization", "pool util"),
                    ("serve_restarts", "restarts"),
                    ("serve_quarantined", "quarantined"))
            out.append("Per-replica gauges at last tick:\n")
            out.append("| replica | " + " | ".join(
                label for _, label in cols) + " |")
            out.append("|" + "---|" * (len(cols) + 1))
            for rid in sorted(rep_gauges):
                g = rep_gauges[rid]
                out.append(f"| {rid} | " + " | ".join(
                    (f"{g[k]:g}" if k in g else "-")
                    for k, _ in cols) + " |")
            out.append("")
        for f in failovers:
            out.append(f"- failover at tick {f.get('at_step', '?')}: "
                       f"{f.get('action', '?')}")
        if failovers:
            out.append("")
        if migrated:
            total = sum(r["kv_migration_bytes"] for r in migrated)
            by_link: Dict[str, int] = {}
            for r in migrated:
                link = str(r.get("kv_migration_link", "?"))
                by_link[link] = by_link.get(link, 0) \
                    + r["kv_migration_bytes"]
            out.append(
                f"- disaggregated KV migration: {len(migrated)} "
                f"request(s), {total / 1024:.1f} KiB moved "
                "prefill -> decode (" + ", ".join(
                    f"{k} {v / 1024:.1f} KiB"
                    for k, v in sorted(by_link.items()))
                + ") — per-request bytes are measured from the payload "
                  "arrays, the link from the wire_link_split granule "
                  "logic")
            out.append("")

    # -- SLO headroom -------------------------------------------------------
    slo = [(float(r["deadline_s"]) - float(r["lat_s"])) for r in served
           if isinstance(r.get("deadline_s"), (int, float))]
    if slo:
        viol = sum(1 for h in slo if h < 0)
        out.append("## SLO headroom (deadline − latency, served "
                   "requests)\n")
        out.append(f"- requests with deadlines: {len(slo)}, violations "
                   f"(negative headroom): {viol}")
        out.append(f"- headroom: {_pcts(sorted(slo))}")
        out.append("\n```")
        out.extend(_histogram_ascii(slo))
        out.append("```\n")

    # -- SLO error budgets (schema v15 `slo` records) -----------------------
    slo_recs = [m for m in metas if m.get("kind") == "slo"]
    if slo_recs:
        last = slo_recs[-1]
        ws = (last.get("windows") or {}).get("s") or []
        out.append("## SLO budgets\n")
        att = last.get("attainment")
        out.append(
            f"- attainment {att:.2%}" if isinstance(att, (int, float))
            else "- attainment -")
        out[-1] += (f" across all tenants, burn windows {ws}s "
                    f"({len(slo_recs)} snapshot(s) in the run)")
        tenants = last.get("tenants") or {}
        if tenants:
            out.append("\n| tenant | target | requests | good | "
                       "attainment | budget spent | burn rates |")
            out.append("|---|---|---|---|---|---|---|")
            for name in sorted(tenants):
                td = tenants[name] or {}
                obj = td.get("objective") or {}
                burn = td.get("burn") or {}
                burn_s = ", ".join(
                    f"{k} {float(v):.1f}x"
                    for k, v in sorted(burn.items())) or "-"
                spent = td.get("budget_spent_frac")
                out.append(
                    f"| {name} | {obj.get('target', '-')} | "
                    f"{td.get('requests', 0)} | {td.get('good', 0)} | "
                    f"{float(td.get('attainment', 1.0)):.2%} | "
                    + (f"{float(spent):.0%}"
                       if isinstance(spent, (int, float)) else "-")
                    + f" | {burn_s} |")
            out.append("")
        # every alert over the run, not just the ones still burning at
        # the last snapshot — this is the postmortem ledger
        seen_alerts = []
        for rec in slo_recs:
            for a in rec.get("alerts") or []:
                key = (a.get("tenant"), a.get("kind"), a.get("t"))
                if key not in {(x.get("tenant"), x.get("kind"),
                                x.get("t")) for x in seen_alerts}:
                    seen_alerts.append(a)
        for a in seen_alerts:
            out.append(
                f"- alert: **{a.get('kind', '?')}** for tenant "
                f"`{a.get('tenant', '?')}` — burn "
                f"{float(a.get('burn', 0.0)):.1f}x over "
                f"{a.get('window_s', '?')}s (threshold "
                f"{a.get('threshold', '?')}x); fast-burn alerts also "
                "flushed the flight ring (`slo_fast_burn` below)")
        if seen_alerts:
            out.append("")

    # -- shed audit ---------------------------------------------------------
    sheds: Dict[str, int] = {}
    for r in reqs:
        fin = str(r.get("finish", ""))
        if r.get("status") == "shed" and fin.startswith("shed:"):
            sheds[fin.split(":", 1)[1]] = \
                sheds.get(fin.split(":", 1)[1], 0) + 1
    if sheds:
        out.append("## Shed audit\n")
        out.append("| reason | count | what it means |")
        out.append("|---|---|---|")
        meaning = {
            "queue_watermark": "admission refused at max_queue — "
                               "sustained overload, add capacity",
            "pool_watermark": "admission refused at pool pressure — "
                              "KV pool too small for the traffic",
            "deadline_overdue": "already past its deadline in queue — "
                                "arrival bursts outran the SLO",
            "deadline_unmeetable": "priced as unmeetable from the "
                                   "measured decode tick — the SLO "
                                   "asks more than the engine serves",
        }
        for reason, n in sorted(sheds.items(), key=lambda kv: -kv[1]):
            out.append(f"| {reason} | {n} | "
                       f"{meaning.get(reason, '?')} |")
        out.append("")

    # -- rolling goodput ----------------------------------------------------
    done = sorted(
        (float(r["ts"]), int(r.get("new_tokens", 0)))
        for r in reqs if r.get("status") == "ok"
        and isinstance(r.get("ts"), (int, float))
    )
    if len(done) >= 2 and done[-1][0] > done[0][0]:
        span = done[-1][0] - done[0][0]
        win = max(span / 8.0, 1e-6)
        rates = []
        t = done[0][0]
        while t < done[-1][0]:
            rates.append(sum(n for ts, n in done
                             if t <= ts < t + win) / win)
            t += win
        out.append("## Goodput (ok-request tokens/s, rolling "
                   f"{win:.2f}s windows)\n")
        out.append(
            f"- mean {sum(rates) / len(rates):.1f}, "
            f"min {min(rates):.1f}, max {max(rates):.1f} tok/s "
            "(a restart or shed burst shows as the min-window dip)"
        )
        out.append("")

    # -- per-tick time series -----------------------------------------------
    if ticks:
        out.append("## Scheduler ticks\n")
        out.append(f"- tick records: {len(ticks)} "
                   f"({sum(1 for t in ticks if t.get('emit') == 'event')}"
                   " eventful, rest sampled)")
        walls = [t["wall_s"] for t in ticks
                 if isinstance(t.get("wall_s"), (int, float))]
        if walls:
            out.append(f"- tick wall: {_pcts(walls)}")
        segs = [("sched_s", "host scheduling"),
                ("prefill_s", "prefill"),
                ("decode_s", "decode dispatch"),
                ("fetch_s", "token fetch")]
        if any(isinstance(t.get("draft_s"), (int, float))
               for t in ticks):
            # spec runs split decode into draft vs verify walls
            segs.insert(1, ("draft_s", "draft propose"))
        tot = sum(sum(float(t.get(k, 0.0)) for t in ticks)
                  for k, _ in segs) or 1e-9
        out.append("\n| tick segment | total | share |")
        out.append("|---|---|---|")
        for k, label in segs:
            s = sum(float(t.get(k, 0.0)) for t in ticks)
            out.append(f"| {label} | {s:.3f} s | {s / tot:.0%} |")
        occ = [t["occupancy"] for t in ticks
               if isinstance(t.get("occupancy"), (int, float))]
        qd = [t["queue_depth"] for t in ticks
              if isinstance(t.get("queue_depth"), int)]
        out.append("")
        if occ:
            out.append(f"- occupancy: mean {sum(occ) / len(occ):.2f}, "
                       f"min {min(occ):.2f}, max {max(occ):.2f}")
        if qd:
            out.append(f"- queue depth: max {max(qd)}")
        faults = {k: sum(int(t.get(k, 0)) for t in ticks)
                  for k in ("shed", "expired", "quarantined",
                            "restarted")}
        if any(faults.values()):
            out.append("- fault counters: " + ", ".join(
                f"{k} {v}" for k, v in faults.items() if v))
        out.append("")

    flights = [m for m in metas if m.get("kind") == "flight"
               and str(m.get("reason", "")).startswith(
                   ("serve_", "slo_"))]
    if flights:
        out.append("## Flight records\n")
        for fl in flights:
            out.append(
                f"- `{fl.get('reason')}` at tick "
                f"{fl.get('at_step', '?')}: "
                f"{len(fl.get('steps') or [])} tick(s) of lead-up in "
                "the ring"
            )
        out.append("")

    out.append(
        "Request timeline: `python scripts/trace_view.py "
        f"{source or 'RUN.jsonl'}` -> Chrome-trace JSON "
        "(chrome://tracing / Perfetto).\n"
    )
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from a serving run")
    ap.add_argument("-o", "--out", default=None,
                    help="write the markdown report here "
                         "(default: stdout)")
    args = ap.parse_args(argv)
    if not os.path.exists(args.jsonl):
        print(f"{args.jsonl}: no such file", file=sys.stderr)
        return 2
    metas, _steps, errs = trace.load_run(args.jsonl)
    for e in errs:
        print(f"warning: {args.jsonl}: {e}", file=sys.stderr)
    if not any(m.get("kind") in ("request", "tick") for m in metas):
        print(
            f"{args.jsonl}: no serving records (run serve_bench.py "
            "with its sidecar, or attach a MetricsLogger to the "
            "ServingEngine)", file=sys.stderr,
        )
        return 2
    report = render_serve_report(metas, source=args.jsonl)
    if args.out:
        with open(args.out, "w") as f:
            f.write(report)
        print(f"wrote {args.out}")
    else:
        print(report)
    if errs:
        print(
            f"{args.jsonl}: {len(errs)} unparseable line(s) — the "
            "report covers only the valid records", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
