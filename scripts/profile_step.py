# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Capture a per-op TPU profile of the default bench step and bucket it.

Automates the round-4 analysis behind PROFILE.md "chip profile": traces 5
steps of the default single-chip config with `jax.profiler.trace`, parses
the XPlane with `jax.profiler.ProfileData` (no TensorBoard needed), and
prints a JSON bucket table (ms/step by op family).  Run on a live TPU:

    python scripts/profile_step.py [--model gpt2-124m] [--out DIR]

The buckets are the ceiling-analysis vocabulary: attention kernels, vocab
head (50304-shaped), MLP (4d-shaped), QKV (3d-shaped), scan stash
slices, copies, other.  Sum of buckets reproduces the device step time
(the `%while` wrappers are skipped; their children are counted).
"""

import argparse
import dataclasses
import glob
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = 5


def bucket_for(name: str, d_model: int, vocab: int) -> str:
    head = name.split(" = ")[0]
    if head.startswith("%while"):
        return "SKIP"
    if "_xent_" in name:
        return "fused xent kernels"  # ops/xent_pallas.py (BENCH_XENT=pallas)
    if ("flash" in name or "_fwd_kernel" in name or "_bwd_dkv" in name
            or "_bwd_dq" in name):
        return "attention kernels"
    if str(vocab) in name:
        return "vocab head/xent/embed"
    if str(4 * d_model) in name:
        return "MLP fusions"
    if str(3 * d_model) in name:
        return "QKV fusions"
    if "dynamic-update-slice" in name or "dynamic-slice" in name:
        return "scan stash/slices"
    if "copy-start" in head or "copy-done" in head:
        # the offload stream's async host<->HBM transfers (and any other
        # async copies) — the bucket VERDICT r4 #5 asked for: on an
        # offload_opt_state run this is the moments traffic, and its
        # size vs the update/other buckets says what the streaming hides
        return "async copies (offload stream)"
    if "copy" in head:
        return "copies"
    return "other"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2-124m")
    ap.add_argument("--out", default="/tmp/profile_step")
    ap.add_argument("--offload", action="store_true",
                    help="profile the offload_opt_state step (adds the "
                         "async-copy bucket attribution for the moments "
                         "stream)")
    ap.add_argument("--offload-prefetch", type=int, default=4)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from bench import _bench_config
    from tiny_deepspeed_tpu import AdamW, SingleDevice, make_mesh
    from tiny_deepspeed_tpu.models import ALL_PRESETS, build_model

    bc = _bench_config(args.model)
    cfg = dataclasses.replace(ALL_PRESETS[args.model], **bc["overrides"])
    model = build_model(cfg)
    opt = AdamW(lr=1e-5, weight_decay=0.1,
                state_dtype=bc["state_dtype"] or jnp.float32)
    ek = {}
    if args.offload:
        ek = dict(offload_opt_state=True,
                  offload_prefetch=args.offload_prefetch)
    engine = SingleDevice(model, opt, mesh=make_mesh(), **ek)
    state = engine.init(jax.random.PRNGKey(0))
    b, t = bc["batch"], 1024
    idx = jax.random.randint(jax.random.PRNGKey(1), (b, t), 0,
                             cfg.vocab_size, jnp.int32)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (b, t), 0,
                             cfg.vocab_size, jnp.int32)
    for _ in range(5):
        state, loss = engine.step(state, (idx, tgt))
    float(loss)
    with jax.profiler.trace(args.out):
        for _ in range(STEPS):
            state, loss = engine.step(state, (idx, tgt))
        float(loss)

    from jax.profiler import ProfileData
    xplane = sorted(glob.glob(
        os.path.join(args.out, "plugins/profile/*/*.xplane.pb")))[-1]
    p = ProfileData.from_file(xplane)
    tpu = next((pl for pl in p.planes if "TPU" in pl.name), None)
    if tpu is None:
        raise SystemExit(
            f"no TPU plane in {xplane} (planes: "
            f"{[pl.name for pl in p.planes]}) — this script needs the "
            "real chip; the CPU backend records no per-op device line")
    ops = next(ln for ln in tpu.lines if ln.name == "XLA Ops")
    tot = defaultdict(float)
    for e in ops.events:
        bk = bucket_for(e.name, cfg.n_embd, cfg.vocab_size)
        if bk != "SKIP":
            tot[bk] += e.duration_ns / 1e6 / STEPS
    print(json.dumps({
        "model": args.model, "batch": b, "offload": bool(args.offload),
        "xplane": xplane,
        "step_ms": round(sum(tot.values()), 2),
        "buckets_ms": {k: round(v, 2) for k, v in
                       sorted(tot.items(), key=lambda x: -x[1])},
    }))


if __name__ == "__main__":
    main()
