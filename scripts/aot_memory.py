# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""AOT memory + roofline analysis of the bench configs on a v5e topology.

Compiles each BASELINE.md single-chip bench configuration (bench.py
_bench_config: model preset + dtype/remat/batch knobs) against a
compile-only single-chip v5e topology — no hardware, libtpu compiles
locally — and reports, per config:

  * compiled peak HBM: live TrainState bytes + XLA temp allocation
    (the same accounting bench.py reports from the real chip);
  * ANALYTIC roofline floors — compute: matmul FLOPs (bench.py's honest
    MFU accounting) / 197 bf16 TF/s; memory: a weight/optimizer traffic
    LOWER bound (weights read 3x per step [fwd + dx + dw passes], moments
    read+written, params written) / 819 GB/s.  Deliberately NOT
    `compiled.cost_analysis()`: XLA's flops/bytes counters count a
    while-loop body ONCE, so remat scans understate true work L-fold
    (the same trip-count trap utils/hlo_comm.py handles for collectives).

The floors are the CEILING ANALYSIS for the throughput numbers: measured
step time can approach but not beat max(compute_floor, hbm_floor); the
gap between measured step time and the binding floor is the optimization
headroom (round-4 verdict #3 for gpt2-124m).

Usage: python scripts/aot_memory.py [--topology v5e:1x1] [--json OUT]
"""

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the tunnel

import jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import Mesh

from tiny_deepspeed_tpu.ops.dispatch import kernel_target_forced

V5E_PEAK_FLOPS = 197e12  # bf16
V5E_HBM_BW = 819e9       # bytes/s
V5E_HBM_GB = 16.0


def _matmul_flops_per_token(model, cfg, t):
    """bench.py's honest MFU accounting: 6 x non-embedding (active) params
    + 12*L*T*d attention FLOPs per token (wte/wpe gathers excluded)."""
    from tiny_deepspeed_tpu.models.llama import LlamaConfig
    from tiny_deepspeed_tpu.models.moe import MoEConfig
    import math

    n_params = model.num_params()
    embed = cfg.vocab_size * cfg.n_embd + (
        0 if isinstance(cfg, LlamaConfig) else cfg.block_size * cfg.n_embd
    )
    n_active = n_params
    if isinstance(cfg, MoEConfig):
        expert = sum(
            int(math.prod(s.shape))
            for n, s in model.param_shapes().items()
            if ".moe." in n and "router" not in n
        )
        n_active = (n_params - expert
                    + expert * cfg.expert_top_k // cfg.n_expert)
    return 6 * (n_active - embed) + 12 * cfg.n_layer * t * cfg.n_embd


def _traffic_floor_bytes(state):
    """Per-step HBM traffic LOWER bound from the live state alone:
    params read 3x (fwd, dx pass, dw pass) + written once; optimizer
    state read + written.  Ignores activations, logits, and grads — a
    true floor, so the implied tokens/s is an upper bound."""
    params_b = opt_b = 0
    for path, x in jax.tree_util.tree_flatten_with_path(state)[0]:
        b = int(np.prod(x.shape)) * x.dtype.itemsize
        if any(getattr(p, "name", None) == "params"
               or getattr(p, "key", None) == "params" for p in path):
            params_b += b
        else:
            opt_b += b
    return 4 * params_b + 2 * opt_b


def _bench_engine(model_name: str, mesh, t=1024, offload=False):
    """Mirror bench.py run_one's single-chip engine construction."""
    import bench
    from tiny_deepspeed_tpu import AdamW, SingleDevice
    from tiny_deepspeed_tpu.models import ALL_PRESETS, build_model

    bc = bench._bench_config(model_name)
    cfg = dataclasses.replace(ALL_PRESETS[model_name], **bc["overrides"])
    if t > cfg.block_size:
        cfg = dataclasses.replace(cfg, block_size=t, remat=True,
                                  fused_xent=True)
    model = build_model(cfg)
    opt = AdamW(lr=1e-5, weight_decay=0.1,
                state_dtype=bc["state_dtype"] or jnp.float32)
    eng = SingleDevice(model, opt, mesh=mesh,
                       offload_opt_state=offload)
    return eng, bc["batch"], cfg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x2",
                    help="smallest v5e topology libtpu accepts is 2x2; the "
                         "single-chip engines compile on a 1-device mesh "
                         "carved from it")
    ap.add_argument("--json", default="/tmp/aot_memory.json")
    ap.add_argument("--seq", type=int, default=0,
                    help="override T for every config (long-context rows)")
    args = ap.parse_args()

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=args.topology)
    devs = np.array(topo.devices)
    mesh = Mesh(devs[:1], ("data",))  # single-chip bench configs
    print(f"topology {args.topology}: {devs.size}x "
          f"{topo.devices[0].device_kind} (using 1 device)", flush=True)

    # import the sibling script for the shared abstract-state builders
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "aot_topology_script",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "aot_topology.py"),
    )
    aot = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(aot)

    cases = [
        ("gpt2-124m", {}),
        ("gpt2-350m", {}),
        ("gpt2-774m", {}),
        ("gpt2-1.5b", {}),
        ("moe-8x124m", {}),
        ("llama-160m", {}),
        ("gpt2-124m", {"t": 4096, "b": 2}),
        ("gpt2-124m", {"t": 8192, "b": 1}),
        ("gpt2-1.5b", {"offload": True}),
        ("llama-1b", {"b": 4}),
    ]
    results = []
    for model_name, kw in cases:
        t = kw.get("t", args.seq or 1024)
        label = model_name + (f"-t{t}" if t != 1024 else "") \
            + ("-offload" if kw.get("offload") else "")
        try:
            eng, b_dflt, cfg = _bench_engine(
                model_name, mesh, t=t, offload=kw.get("offload", False)
            )
            b = kw.get("b", b_dflt)
            state = aot._state_structs(eng)
            compiled = None
            while True:
                try:
                    with kernel_target_forced("tpu"):
                        compiled = eng._step.lower(
                            state, aot._batch_structs(eng, b, t)
                        ).compile()
                    break
                except Exception as e:
                    # compile-time HBM OOM: step the batch down and label
                    # it — the fitting envelope is itself a result
                    if "RESOURCE_EXHAUSTED" in repr(e) and b > 1:
                        b -= 1
                        continue
                    raise
            mem = compiled.memory_analysis()
            state_bytes = sum(
                int(np.prod(x.shape)) * x.dtype.itemsize
                for x in jax.tree.leaves(state)
                if getattr(x.sharding, "memory_kind", None) != "pinned_host"
            )
            temp = int(mem.temp_size_in_bytes)
            hbm_gb = (state_bytes + temp) / 2**30
            toks = b * t
            flops = _matmul_flops_per_token(eng.model, cfg, t) * toks
            traffic = _traffic_floor_bytes(state)
            compute_floor_ms = flops / V5E_PEAK_FLOPS * 1e3
            hbm_floor_ms = traffic / V5E_HBM_BW * 1e3
            floor_ms = max(compute_floor_ms, hbm_floor_ms)
            rec = {
                "label": label, "batch": b, "seq": t,
                "batch_reduced_from": (None if b == kw.get("b", b_dflt)
                                       else kw.get("b", b_dflt)),
                "state_gb": round(state_bytes / 2**30, 3),
                "temp_gb": round(temp / 2**30, 3),
                "peak_hbm_gb": round(hbm_gb, 3),
                "fits_16gb": hbm_gb < V5E_HBM_GB,
                "matmul_flops_per_step": flops,
                "traffic_floor_bytes": traffic,
                "compute_floor_ms": round(compute_floor_ms, 3),
                "hbm_floor_ms": round(hbm_floor_ms, 3),
                "bound": ("compute" if compute_floor_ms >= hbm_floor_ms
                          else "hbm"),
                "roofline_tokens_per_sec": (
                    round(toks / (floor_ms / 1e3), 1) if floor_ms else None
                ),
            }
            note = (f" (b {rec['batch_reduced_from']}->{b})"
                    if rec["batch_reduced_from"] else "")
            print(f"{label}{note}: peak_hbm={rec['peak_hbm_gb']:.2f}GB "
                  f"floors(compute={compute_floor_ms:.1f}ms, "
                  f"hbm={hbm_floor_ms:.1f}ms) -> {rec['bound']}-bound, "
                  f"roofline {rec['roofline_tokens_per_sec']:.0f} tok/s",
                  flush=True)
        except Exception as e:
            rec = {"label": label,
                   "error": f"{type(e).__name__}: {e}"[:400]}
            print(f"{label}: ERROR {rec['error'][:160]}", flush=True)
        results.append(rec)

    # ---- north-star shape (BASELINE.md): 1.5B ZeRO-2/3 on a 16-chip v5e
    # pod slice — per-chip compiled memory of the REAL-size multi-chip
    # program (the dryrun covers tiny shapes only; this is the full model)
    import dataclasses as _dc
    from tiny_deepspeed_tpu import AdamW, Zero2, Zero3
    from tiny_deepspeed_tpu.models import ALL_PRESETS, build_model

    # ---- long context at real scale: ring attention over a seq=8 mesh,
    # GPT-2 124M widened to T=32k/64k — per-chip compiled memory is the
    # O(T/n) claim at sizes one chip cannot hold (round-3 CPU evidence
    # stopped at T=16k)
    for t_long in (32768, 65536):
        label = f"ring-sp8-124m-t{t_long}"
        try:
            topo8 = topologies.get_topology_desc(platform="tpu",
                                                 topology_name="v5e:4x2")
            d8 = np.array(topo8.devices)
            mesh8 = Mesh(d8.reshape(1, 8), ("data", "seq"))
            cfgL = _dc.replace(
                ALL_PRESETS["gpt2-124m"], block_size=t_long,
                param_dtype=jnp.bfloat16, remat=True,
            )
            eng = Zero2(build_model(cfgL), AdamW(lr=1e-5), mesh=mesh8,
                        seq_parallel=8)
            state = aot._state_structs(eng)
            with kernel_target_forced("tpu"):
                compiled = eng._step.lower(
                    state, aot._batch_structs(eng, 1, t_long)
                ).compile()
            mem = compiled.memory_analysis()
            state_b = sum(
                int(np.prod(x.sharding.shard_shape(x.shape)))
                * x.dtype.itemsize
                for x in jax.tree.leaves(state)
            )
            temp = int(mem.temp_size_in_bytes)
            rec = {"label": label, "devices": 8, "batch": 1, "seq": t_long,
                   "state_gb_per_chip": round(state_b / 2**30, 3),
                   "temp_gb_per_chip": round(temp / 2**30, 3),
                   "peak_hbm_gb_per_chip": round(
                       (state_b + temp) / 2**30, 3)}
            print(f"{label}: per-chip state={rec['state_gb_per_chip']}GB "
                  f"temp={rec['temp_gb_per_chip']}GB "
                  f"peak={rec['peak_hbm_gb_per_chip']}GB", flush=True)
        except Exception as e:
            rec = {"label": label,
                   "error": f"{type(e).__name__}: {e}"[:400]}
            print(f"{label}: ERROR {repr(e)[:200]}", flush=True)
        results.append(rec)

    for label, eng_cls in (("northstar-zero2-1.5b-dp16", Zero2),
                           ("northstar-zero3-1.5b-dp16", Zero3)):
        try:
            topo16 = topologies.get_topology_desc(
                platform="tpu", topology_name="v5e:4x4"
            )
            d16 = np.array(topo16.devices)
            mesh16 = Mesh(d16.reshape(d16.size), ("data",))
            cfg15 = _dc.replace(
                ALL_PRESETS["gpt2-1.5b"],
                param_dtype=jnp.bfloat16, fused_xent=True,
            )  # f32 moments SHARDED across chips replace the single-chip
            #    bf16-moment squeeze (BASELINE.md fitting note)
            eng = eng_cls(build_model(cfg15),
                          AdamW(lr=1e-5, weight_decay=0.1), mesh=mesh16)
            state = aot._state_structs(eng)
            b16 = 4 * d16.size  # per-chip batch 4, the bench 1.5b setting
            while True:
                try:
                    with kernel_target_forced("tpu"):
                        compiled = eng._step.lower(
                            state, aot._batch_structs(eng, b16, 1024)
                        ).compile()
                    break
                except Exception as e:
                    if "RESOURCE_EXHAUSTED" in repr(e) and \
                            b16 > d16.size:
                        b16 -= d16.size
                        continue
                    raise
            mem = compiled.memory_analysis()
            # per-chip: sharded leaves already count 1/N via shard_shape
            state_b = sum(
                int(np.prod(x.sharding.shard_shape(x.shape)))
                * x.dtype.itemsize
                for x in jax.tree.leaves(state)
            )
            temp = int(mem.temp_size_in_bytes)  # per device
            rec = {
                "label": label, "devices": int(d16.size),
                "batch_global": b16, "seq": 1024,
                "state_gb_per_chip": round(state_b / 2**30, 3),
                "temp_gb_per_chip": round(temp / 2**30, 3),
                "peak_hbm_gb_per_chip": round(
                    (state_b + temp) / 2**30, 3),
            }
            print(f"{label}: per-chip state={rec['state_gb_per_chip']}GB "
                  f"temp={rec['temp_gb_per_chip']}GB "
                  f"peak={rec['peak_hbm_gb_per_chip']}GB", flush=True)
        except Exception as e:
            rec = {"label": label,
                   "error": f"{type(e).__name__}: {e}"[:400]}
            print(f"{label}: ERROR {repr(e)[:200]}", flush=True)
        results.append(rec)

    out = {"topology": args.topology,
           "device_kind": topo.devices[0].device_kind,
           "assumptions": {"peak_flops": V5E_PEAK_FLOPS,
                           "hbm_bw": V5E_HBM_BW},
           "results": results}
    with open(args.json, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {args.json}")


if __name__ == "__main__":
    main()
