#!/usr/bin/env python3
# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Cross-run perf regression sentinel over BENCH_*.json rounds.

The repo commits one BENCH_rNN.json per growth round but nothing ever
COMPARED them — a silent 10% throughput loss would ride along forever.
This script diffs two or more rounds per fingerprint key and exits
nonzero for CI when something regressed:

  * **throughput regression** — the newest fresh value of a fingerprint
    vs the best of its (up to 3) most recent prior fresh values, flagged
    only beyond a noise threshold = max(--noise-floor, the relative
    spread of those prior values).  Best-of-3 spread IS the measured
    noise: a delta inside it proves nothing.
  * **modeled-vs-measured MFU drift** — bench stamps both the analytic
    `matmul_mfu` (hand formula) and `hlo_cost.mfu_hlo` (FLOPs counted
    from the compiled HLO, utils/hlo_cost.py).  When they diverge beyond
    --drift-tol the FORMULA rotted (a model change the hand accounting
    missed — exactly how the MoE dispatch einsums went uncounted for ten
    rounds).
  * **program growth** (informational) — when telemetry sidecars are
    reachable, a >2% jump in HLO-counted FLOPs for the same fingerprint
    is printed as a NOTE: the program changed, whether or not the clock
    noticed yet.
  * **SLO-attainment regression** — serve records stamp
    `extra.slo.attainment` (higher is better); a drop vs the best prior
    round beyond the noise floor flags a service regression that raw
    tokens/s can mask (tail latency traded for batch occupancy).

Records are usable only when fresh: value > 0 and not replayed from the
last-good cache (`extra.cached_result` — BENCH_r04/r05 replay a round-3
measurement and must never be diffed as five independent rounds).  With
zero usable fingerprints the verdict is OK (nothing to compare), exit 0
— the committed trajectory's dead-tunnel rounds stay green.

Pure python (no jax): runs anywhere, including tier-1 CI
(tests/test_repo_hygiene.py wires `perf_diff --check BENCH_*.json`).

Usage:
    python scripts/perf_diff.py BENCH_r04.json BENCH_r05.json
    python scripts/perf_diff.py --check BENCH_*.json     # CI gate
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# extra keys that define a comparable measurement — same metric at a
# different chip count or sequence length is a different experiment,
# not a regression
_FINGERPRINT_KEYS = ("chips", "seq_len")

# metric-name substrings meaning lower-is-better; everything else in the
# bench vocabulary (tokens/s, requests/s, speedup) is higher-is-better
_LOWER_IS_BETTER = ("time", "latency", "_ms", "_s_", "ttft")

# per-fingerprint ledger wire-byte fields (extra.sched, stamped by the
# scheduler bench arms): dotted path -> short label.  Wire bytes are
# measured from the compiled HLO and deterministic per program, so a
# newest-vs-best increase beyond the noise floor is a COMM regression —
# the program started moving more bytes — even when the clock (step
# time on a CPU mesh) never noticed
_WIRE_KEYS = (
    ("sched.gather_wire_bytes_in_loops", "loop gather wire"),
    ("sched.reduce_wire_bytes_in_loops", "loop reduce wire"),
    ("sched.zero3_tail_wire_bytes", "zero3 tail wire"),
    ("sched.hpz_rebuild_dcn_bytes", "hpz rebuild DCN wire"),
    ("sched.wire_bytes_by_link.ici_wire_bytes", "ICI wire"),
    ("sched.wire_bytes_by_link.dcn_wire_bytes", "DCN wire"),
    # not wire, but the same deterministic-per-program contract: the
    # compiled pipeline tick program's idle fraction (pipe-schedule
    # arms) — a bubble creeping back up is a schedule regression the
    # clock on a CPU mesh never notices
    ("sched.bubble_frac", "pipeline bubble frac"),
)

# per-fingerprint HIGHER-is-better extras (the wire keys above are all
# lower-is-better): serve records stamp extra.slo.attainment (fraction
# of requests meeting the default SLO objective, telemetry/slo.py) —
# a drop vs the best prior round beyond the noise floor is a SERVICE
# regression even when tokens/s held (tail latency traded away for
# throughput).  Rounds that predate the stamp simply don't participate.
_ATTAIN_KEYS = (
    ("slo.attainment", "SLO attainment"),
)


def _wire_of(rec: dict, dotted: str) -> Optional[float]:
    """Numeric field at a dotted path under extra, or None."""
    node = rec.get("extra") or {}
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    try:
        return float(node)
    except (TypeError, ValueError):
        return None


def _records_of(obj) -> List[dict]:
    """Bench records inside one loaded JSON value: a driver wrapper
    {"n","cmd","rc","tail","parsed"} yields its parsed record, a bare
    record yields itself, a list flattens recursively."""
    if isinstance(obj, list):
        return [r for o in obj for r in _records_of(o)]
    if not isinstance(obj, dict):
        return []
    if "parsed" in obj and "rc" in obj:
        return _records_of(obj["parsed"]) if obj["parsed"] else []
    if "metric" in obj and "value" in obj:
        return [obj]
    return []


def load_round(path: str) -> List[dict]:
    """All bench records in one round file (JSON value or JSONL)."""
    with open(path) as f:
        text = f.read()
    try:
        return _records_of(json.loads(text))
    except ValueError:
        recs: List[dict] = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                recs.extend(_records_of(json.loads(line)))
            except ValueError:
                pass
        return recs


def is_fresh(rec: dict) -> bool:
    """Usable for comparison: a positive live measurement, not an error
    record and not a last-good-cache replay of an older round."""
    try:
        v = float(rec.get("value", 0.0))
    except (TypeError, ValueError):
        return False
    if v <= 0.0:
        return False
    if rec.get("stale"):
        return False
    extra = rec.get("extra") or {}
    if extra.get("cached_result") or extra.get("stale_cached_result"):
        return False
    if extra.get("error"):
        return False
    return True


def fingerprint(rec: dict) -> str:
    extra = rec.get("extra") or {}
    parts = [str(rec.get("metric", "?"))]
    for k in _FINGERPRINT_KEYS:
        if k in extra:
            parts.append(f"{k}={extra[k]}")
    return " ".join(parts)


def _higher_is_better(metric: str) -> bool:
    m = metric.lower()
    return not any(s in m for s in _LOWER_IS_BETTER)


def _sidecar_flops(rec: dict, round_dir: str) -> Optional[float]:
    """HLO-counted FLOPs for a record: from extra.hlo_cost directly, else
    from the telemetry sidecar's run_meta (best effort — sidecars are
    working-tree artifacts and are usually gone for old rounds)."""
    extra = rec.get("extra") or {}
    cost = extra.get("hlo_cost") or {}
    if isinstance(cost, dict) and cost.get("total_flops"):
        return float(cost["total_flops"])
    path = extra.get("telemetry_jsonl")
    if not path:
        return None
    if not os.path.isabs(path):
        path = os.path.join(round_dir, path)
    try:
        with open(path) as f:
            for line in f:
                try:
                    m = json.loads(line)
                except ValueError:
                    continue
                if isinstance(m, dict) and m.get("kind") == "run_meta":
                    c = m.get("hlo_cost") or {}
                    if c.get("total_flops"):
                        return float(c["total_flops"])
    except OSError:
        return None
    return None


def diff_rounds(rounds: List[Tuple[str, List[dict]]],
                noise_floor: float = 0.03,
                drift_tol: float = 0.15) -> Dict[str, object]:
    """Compare rounds (in given order; last = newest).  Returns
    {"regressions": [...], "drifts": [...], "notes": [...],
     "compared": n, "usable": n} — each flag a printable string naming
    the metric + fingerprint."""
    regressions: List[str] = []
    drifts: List[str] = []
    notes: List[str] = []

    # fingerprint -> [(round_name, rec)] in round order, fresh only
    series: Dict[str, List[Tuple[str, dict]]] = {}
    usable = 0
    for rname, recs in rounds:
        for rec in recs:
            if not is_fresh(rec):
                continue
            usable += 1
            series.setdefault(fingerprint(rec), []).append((rname, rec))

    compared = 0
    for fp, entries in sorted(series.items()):
        # modeled-vs-measured drift: every fresh record that carries both
        for rname, rec in entries:
            extra = rec.get("extra") or {}
            cost = extra.get("hlo_cost") or {}
            mm = extra.get("matmul_mfu")
            mh = cost.get("mfu_hlo") if isinstance(cost, dict) else None
            if mm and mh:
                rel = abs(float(mm) - float(mh)) / max(float(mh), 1e-12)
                if rel > drift_tol:
                    drifts.append(
                        f"DRIFT {fp} [{rname}]: analytic matmul_mfu "
                        f"{float(mm):.3f} vs HLO-counted mfu_hlo "
                        f"{float(mh):.3f} ({rel:.0%} apart > "
                        f"{drift_tol:.0%}) — the hand formula and the "
                        f"compiled program disagree"
                    )
        if len(entries) < 2:
            continue
        compared += 1
        newest_name, newest = entries[-1]
        prior = entries[:-1][-3:]  # up to the 3 most recent prior rounds
        vals = [float(r["value"]) for _, r in prior]
        newest_v = float(newest["value"])
        higher = _higher_is_better(str(newest.get("metric", "")))
        best = max(vals) if higher else min(vals)
        spread = (max(vals) - min(vals)) / max(abs(best), 1e-12)
        threshold = max(noise_floor, spread)
        delta = ((best - newest_v) if higher else (newest_v - best)) \
            / max(abs(best), 1e-12)
        if delta > threshold:
            regressions.append(
                f"REGRESSION {fp} [{newest_name}]: {newest_v:,.1f} vs "
                f"best-of-{len(vals)} {best:,.1f} "
                f"({-delta:+.1%} > noise {threshold:.1%} = "
                f"max(floor {noise_floor:.1%}, spread {spread:.1%}))"
            )
        # comm regression: per-fingerprint ledger wire bytes — newest vs
        # the best (lowest) prior value carrying the same field.  Both
        # sides must stamp the field: a round that predates the
        # scheduler arms (no extra.sched) simply does not participate,
        # so the committed trajectory stays comparable
        for dotted, label in _WIRE_KEYS:
            w_new = _wire_of(newest, dotted)
            w_prior = [w for w in (_wire_of(r, dotted) for _, r in prior)
                       if w is not None]
            if w_new is None or not w_prior:
                continue
            best_w = min(w_prior)
            if best_w <= 0.0:
                continue
            rel = (w_new - best_w) / best_w
            if rel > noise_floor:
                regressions.append(
                    f"REGRESSION {fp} [{newest_name}]: {label} "
                    f"{w_new:,.0f} B vs best-of-{len(w_prior)} "
                    f"{best_w:,.0f} B ({rel:+.1%} > {noise_floor:.1%}) "
                    f"— the compiled step moves more bytes"
                )
        # service regression: SLO attainment (higher is better) —
        # newest vs the best (highest) prior value carrying the field
        for dotted, label in _ATTAIN_KEYS:
            a_new = _wire_of(newest, dotted)
            a_prior = [a for a in (_wire_of(r, dotted) for _, r in prior)
                       if a is not None]
            if a_new is None or not a_prior:
                continue
            best_a = max(a_prior)
            if best_a <= 0.0:
                continue
            rel = (best_a - a_new) / best_a
            if rel > noise_floor:
                regressions.append(
                    f"REGRESSION {fp} [{newest_name}]: {label} "
                    f"{a_new:.3f} vs best-of-{len(a_prior)} "
                    f"{best_a:.3f} ({-rel:+.1%} > {noise_floor:.1%}) "
                    f"— fewer requests met their SLO objective"
                )
        # program growth: HLO-counted FLOPs for the same fingerprint
        f_old = _sidecar_flops(prior[-1][1],
                               os.path.dirname(prior[-1][0]) or ".")
        f_new = _sidecar_flops(newest,
                               os.path.dirname(newest_name) or ".")
        if f_old and f_new:
            rel = (f_new - f_old) / f_old
            if abs(rel) > 0.02:
                notes.append(
                    f"NOTE {fp}: HLO-counted FLOPs changed {rel:+.1%} "
                    f"({f_old:.3e} -> {f_new:.3e}) — the compiled "
                    f"program itself changed"
                )

    return {"regressions": regressions, "drifts": drifts, "notes": notes,
            "compared": compared, "usable": usable,
            "fingerprints": len(series)}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="noise-aware cross-round bench diff (see module "
                    "docstring)")
    ap.add_argument("files", nargs="+",
                    help="BENCH_*.json round files, oldest first "
                         "(sorted by name unless --no-sort)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: same comparison, documented gate — "
                         "exit 1 on any REGRESSION/DRIFT flag, 0 "
                         "otherwise (including nothing-to-compare)")
    ap.add_argument("--no-sort", action="store_true",
                    help="take files in the order given instead of "
                         "sorting by name")
    ap.add_argument("--noise-floor", type=float, default=0.03,
                    help="minimum relative delta to flag (default 3%%)")
    ap.add_argument("--drift-tol", type=float, default=0.15,
                    help="modeled-vs-measured MFU divergence to flag "
                         "(default 15%%)")
    args = ap.parse_args(argv)

    files = list(args.files) if args.no_sort else sorted(args.files)
    rounds = [(f, load_round(f)) for f in files]
    out = diff_rounds(rounds, noise_floor=args.noise_floor,
                      drift_tol=args.drift_tol)

    print(f"perf_diff: {len(rounds)} round(s), {out['usable']} fresh "
          f"record(s), {out['fingerprints']} fingerprint(s), "
          f"{out['compared']} compared")
    for line in out["notes"]:
        print(line)
    for line in out["drifts"]:
        print(line)
    for line in out["regressions"]:
        print(line)
    flags = len(out["regressions"]) + len(out["drifts"])
    if flags:
        print(f"FAIL: {flags} flag(s)")
        return 1
    if not out["compared"] and not out["usable"]:
        print("OK (no fresh records to compare — cached/error rounds "
              "are excluded)")
    else:
        print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
