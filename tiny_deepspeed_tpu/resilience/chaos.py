# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Fault-injection harness: deterministic, seed-driven chaos.

Every recovery path in this package is tested by actually breaking
things — a NaN really reaches the gradients, a checkpoint writer really
dies between tmp-write and commit, a host really stalls — not by mocking
the failure's observers.  Faults fire deterministically: either from an
explicit step set, or from a per-(seed, kind, step) counter-mode RNG, so
a failing chaos test replays bit-identically from its seed.

    chaos = Chaos(seed=7, nan_steps=(3,), ckpt_write_failures=2)
    chaos.install()                       # checkpoint I/O hook
    eng = ChaosEngine(engine, chaos)      # step-level faults
    ...
    chaos.uninstall()

Fault kinds (training, via ChaosEngine):
  * "nan"    — poison one parameter with NaN AFTER the injected step:
               the next forward/backward produces non-finite loss and
               gradients everywhere (exactly how real overflow spreads),
               driving the telemetry non-finite detector end-to-end.
  * "delay"  — sleep `delay_s` before the step (a straggling host;
               exercises the straggler gauges and the rebalancer).
  * "sigterm"— raise SIGTERM in-process at the injected step (the
               preemption notice; exercises PreemptionGuard's drain).
  * checkpoint I/O — `ckpt_write_failures` transient OSErrors on save
               attempts (exercises retry/backoff) and `kill_next_commit`
               a CheckpointKilled between tmp-write and commit
               (exercises the uncommitted-dir skip on restore).

Fault kinds (serving, via ChaosServingEngine — tick-scoped, the tick
counter counts `tick()` calls on the wrapper):
  * "tick_nan"     — NaN-poison ONE active slot's decode logits this
                     tick (deterministic slot rotation over the active
                     set); drives the decode-health guard's quarantine
                     and, when consecutive, its warm-restart watchdog.
  * "tick_delay"   — sleep `delay_s` before the tick (a stalled device
                     or host; inflates TTFT/deadline pressure — what
                     the SLO shedding reacts to).
  * "prefill_raise"— raise inside the next admission's prefill
                     (exercises the tick-exception warm restart with
                     the half-admitted request re-queued).
  * "journal_kill" — ServingKilled between the request journal's
                     line-append and its per-tick fsync commit: the
                     buffered tick is lost exactly as a SIGKILL at the
                     worst moment would lose it (exercises
                     ServingEngine.recover's re-decode of the tail).
  * "engine_kill"  — EngineKilled raised OUT of the wrapped engine's
                     tick: the whole replica dies (its host went away),
                     which no warm restart may catch — the FLEET router
                     catches it one level up and replays the dead
                     replica's journal onto a sibling
                     (fleet/failover.py).
  * "tenant_flood" — ONE abusive tenant submits `flood_requests`
                     requests in a burst before the tick (deterministic
                     prompts from the (seed, kind, tick) rng, tagged
                     `flood_tenant`): the multi-tenant isolation
                     machinery (per-tenant watermarks, token budgets,
                     weighted-fair admission — serving/tenancy.py) must
                     absorb it without moving a well-behaved tenant's
                     p99 (the ROADMAP isolation pin,
                     tests/test_serving_prefix.py).
"""

from __future__ import annotations

import signal
import time
from typing import Dict, Iterable, List, Optional

import numpy as np

import jax.numpy as jnp

from ..utils.checkpoint import CheckpointKilled, set_io_hook

_KIND_CODE = {"nan": 1, "delay": 2, "sigterm": 3,
              "tick_nan": 4, "tick_delay": 5, "prefill_raise": 6,
              "journal_kill": 7, "engine_kill": 8, "tenant_flood": 9}


class Chaos:
    """Deterministic fault schedule + checkpoint I/O fault hook."""

    def __init__(self, seed: int = 0, *,
                 nan_steps: Iterable[int] = (),
                 nan_prob: float = 0.0,
                 delay_steps: Iterable[int] = (),
                 delay_prob: float = 0.0,
                 delay_s: float = 0.25,
                 sigterm_step: Optional[int] = None,
                 ckpt_write_failures: int = 0,
                 tick_nan_steps: Iterable[int] = (),
                 tick_nan_prob: float = 0.0,
                 tick_delay_steps: Iterable[int] = (),
                 tick_delay_prob: float = 0.0,
                 prefill_raise_steps: Iterable[int] = (),
                 journal_kill_step: Optional[int] = None,
                 engine_kill_step: Optional[int] = None,
                 tenant_flood_steps: Iterable[int] = (),
                 tenant_flood_prob: float = 0.0,
                 flood_tenant: str = "abuser",
                 flood_requests: int = 8,
                 flood_prompt_len: int = 8,
                 flood_new_tokens: int = 8):
        self.seed = int(seed)
        self.delay_s = float(delay_s)
        self.flood_tenant = str(flood_tenant)
        self.flood_requests = int(flood_requests)
        self.flood_prompt_len = int(flood_prompt_len)
        self.flood_new_tokens = int(flood_new_tokens)
        self._steps = {
            "nan": frozenset(int(s) for s in nan_steps),
            "delay": frozenset(int(s) for s in delay_steps),
            "sigterm": frozenset(
                () if sigterm_step is None else (int(sigterm_step),)
            ),
            "tick_nan": frozenset(int(s) for s in tick_nan_steps),
            "tick_delay": frozenset(int(s) for s in tick_delay_steps),
            "prefill_raise": frozenset(
                int(s) for s in prefill_raise_steps),
            "journal_kill": frozenset(
                () if journal_kill_step is None
                else (int(journal_kill_step),)
            ),
            "engine_kill": frozenset(
                () if engine_kill_step is None
                else (int(engine_kill_step),)
            ),
            "tenant_flood": frozenset(
                int(s) for s in tenant_flood_steps),
        }
        self._prob = {"nan": float(nan_prob), "delay": float(delay_prob),
                      "sigterm": 0.0,
                      "tick_nan": float(tick_nan_prob),
                      "tick_delay": float(tick_delay_prob),
                      "prefill_raise": 0.0, "journal_kill": 0.0,
                      "engine_kill": 0.0,
                      "tenant_flood": float(tenant_flood_prob)}
        self._write_fails_left = int(ckpt_write_failures)
        self._kill_commit = False
        self.injected: List[Dict] = []  # JSON-safe fault log

    # -- schedule ----------------------------------------------------------

    def fires(self, kind: str, step: int) -> bool:
        """True when fault `kind` fires at `step` — explicit step set
        first, then the seeded probability draw (counter-mode: the
        decision for (seed, kind, step) never depends on call order)."""
        hit = step in self._steps[kind]
        p = self._prob[kind]
        if not hit and p > 0.0:
            rng = np.random.default_rng(
                (self.seed, _KIND_CODE[kind], int(step))
            )
            hit = bool(rng.random() < p)
        if hit:
            self.record(kind, at_step=step)
        return hit

    def record(self, fault: str, **fields) -> Dict:
        rec = {"fault": fault, **fields}
        self.injected.append(rec)
        return rec

    def log_faults(self, logger) -> None:
        """Write every injected fault as a `kind="fault"` JSONL record
        (telemetry/schema.py) and clear the log."""
        for rec in self.injected:
            logger.log_meta(kind="fault", **rec)
        self.injected = []

    # -- checkpoint I/O faults ---------------------------------------------

    def fail_next_writes(self, n: int) -> None:
        """Arm `n` transient write failures (each save ATTEMPT consumes
        one; the retry loop in utils/checkpoint.py rides them out)."""
        self._write_fails_left = int(n)

    def kill_next_commit(self) -> None:
        """Arm ONE simulated writer death between tmp-write and commit:
        the next save raises CheckpointKilled after the payload is fully
        written but before the rename+marker — on disk it looks exactly
        like a SIGKILL'd process."""
        self._kill_commit = True

    def checkpoint_hook(self, phase: str, path: str, attempt: int) -> None:
        if phase == "write" and self._write_fails_left > 0:
            self._write_fails_left -= 1
            self.record("ckpt_write_failure", path=path, attempts=attempt)
            raise OSError(
                f"chaos: injected transient checkpoint write failure "
                f"(attempt {attempt})"
            )
        if phase == "commit" and self._kill_commit:
            self._kill_commit = False
            self.record("ckpt_kill", path=path, attempts=attempt)
            raise CheckpointKilled(
                "chaos: writer killed between tmp-write and commit"
            )

    def install(self) -> "Chaos":
        set_io_hook(self.checkpoint_hook)
        return self

    def uninstall(self) -> None:
        set_io_hook(None)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


def poison_params(state):
    """NaN the [0, 0, ...] element of the first float parameter (sorted
    name order): the next step's forward sees a non-finite weight, so its
    loss AND every gradient leaf go non-finite — the honest propagation
    path, not a synthetic health vector."""
    import dataclasses
    for name in sorted(state.params):
        leaf = state.params[name]
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            new = dict(state.params)
            new[name] = leaf.at[(0,) * leaf.ndim].set(jnp.nan)
            return dataclasses.replace(state, params=new)
    raise ValueError("no float parameter leaf to poison")


class ChaosEngine:
    """Engine proxy that injects step-level faults: delays before the
    step, NaN poisoning after it, SIGTERM at it.  Tracks its own step
    counter (0-based, counting `step()` calls); everything else
    delegates to the wrapped engine."""

    def __init__(self, engine, chaos: Chaos):
        self.engine = engine
        self.chaos = chaos
        self.steps_run = 0

    def __getattr__(self, name):
        return getattr(self.engine, name)

    def step(self, state, batch):
        it = self.steps_run
        self.steps_run += 1
        if self.chaos.fires("delay", it):
            time.sleep(self.chaos.delay_s)
        if self.chaos.fires("sigterm", it):
            signal.raise_signal(signal.SIGTERM)
        state, loss = self.engine.step(state, batch)
        if self.chaos.fires("nan", it):
            state = poison_params(state)
        return state, loss


class ChaosServingEngine:
    """Serving-engine proxy injecting tick-scoped faults (module
    docstring, "serving" kinds).  Tracks its own tick counter (0-based,
    counting `tick()` calls on the wrapper); everything else delegates
    to the wrapped `serving.ServingEngine` — which is why `drain` is
    re-implemented here: the engine's own drain would call the engine's
    tick and sail straight past the faults."""

    def __init__(self, engine, chaos: Chaos):
        self.engine = engine
        self.chaos = chaos
        self.ticks_run = 0

    def __getattr__(self, name):
        return getattr(self.engine, name)

    def tick(self, **kw) -> int:
        t = self.ticks_run
        self.ticks_run += 1
        if self.chaos.fires("engine_kill", t):
            from ..fleet.failover import EngineKilled
            raise EngineKilled(
                f"chaos: replica killed whole at tick {t}"
            )
        if self.chaos.fires("tick_delay", t):
            time.sleep(self.chaos.delay_s)
        if self.chaos.fires("tenant_flood", t):
            # one abusive tenant bursts N requests through the real
            # submit() door: watermark sheds, budget throttling, and
            # weighted-fair admission all see honest traffic.  Prompts
            # are deterministic from the (seed, kind, tick) rng.
            ch = self.chaos
            rng = np.random.default_rng(
                (ch.seed, _KIND_CODE["tenant_flood"], int(t)))
            vocab = self.engine.model.config.vocab_size
            outcomes = []
            for _ in range(ch.flood_requests):
                r = self.engine.submit(
                    rng.integers(0, vocab,
                                 ch.flood_prompt_len).tolist(),
                    ch.flood_new_tokens, tenant=ch.flood_tenant)
                outcomes.append(r.status or "queued")
            ch.injected[-1]["action"] = (
                f"tenant {ch.flood_tenant} x{ch.flood_requests}: "
                + ",".join(outcomes))
        if self.chaos.fires("tick_nan", t):
            active = self.engine.active_slots()
            if active:
                slot = active[t % len(active)]
                self.engine.poison_slot(slot)
                self.chaos.injected[-1]["slot"] = slot
            else:
                # recorded by fires() but nothing to poison this tick
                self.chaos.injected[-1]["slot"] = -1
        if self.chaos.fires("prefill_raise", t):
            self.engine.arm_prefill_exception(
                RuntimeError(f"chaos: injected prefill failure at "
                             f"tick {t}")
            )
        if self.chaos.fires("journal_kill", t):
            if self.engine.journal is None:
                raise ValueError(
                    "chaos journal_kill armed but the engine has no "
                    "journal — construct ServingEngine(journal=...)"
                )
            from ..serving.journal import ServingKilled

            def _kill():
                raise ServingKilled(
                    "chaos: killed between journal append and commit"
                )

            self.engine.journal.arm_commit_hook(_kill)
        return self.engine.tick(**kw)

    def drain(self, max_ticks: Optional[int] = None) -> int:
        total = 0
        ticks = 0
        while self.engine.queue_depth or self.engine.n_active:
            total += self.tick()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(
                    f"drain exceeded {max_ticks} ticks with "
                    f"{self.engine.queue_depth} queued"
                )
        return total


def parse_serving_chaos(spec: str, *, seed: int = 0,
                        delay_s: float = 0.25) -> Chaos:
    """Build a serving Chaos schedule from a CLI spec string
    (scripts/serve_bench.py --chaos).  Comma-separated entries:

        kind@tick     fire `kind` at that tick       nan@5,delay@7
        kind%prob     seeded per-tick probability    nan%0.02
        journal_kill@tick                            journal_kill@9
        engine_kill@tick (fleet: kills the whole     engine_kill@12
        wrapped replica; the router fails it over)
        flood@tick (one abusive tenant bursts        flood@4
        requests through submit; needs a tenants-
        configured engine for the isolation to bite)

    Kinds: nan (slot-poison), delay (tick delay), prefill (prefill
    raise), journal_kill, engine_kill, flood (tenant_flood).  The
    schedule is deterministic from (spec, seed) — the same A/B replays
    bit-identically."""
    kinds = {"nan": "tick_nan", "delay": "tick_delay",
             "prefill": "prefill_raise", "journal_kill": "journal_kill",
             "engine_kill": "engine_kill", "flood": "tenant_flood"}
    steps: Dict[str, List[int]] = {k: [] for k in kinds.values()}
    probs: Dict[str, float] = {}
    journal_kill = None
    engine_kill = None
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        for sep in ("@", "%"):
            if sep in entry:
                kind, val = entry.split(sep, 1)
                break
        else:
            raise ValueError(
                f"chaos entry {entry!r}: expected kind@tick or kind%prob"
            )
        if kind not in kinds:
            raise ValueError(
                f"unknown chaos kind {kind!r} (one of {sorted(kinds)})"
            )
        if sep == "%":
            if kinds[kind] in ("prefill_raise", "journal_kill",
                               "engine_kill"):
                raise ValueError(f"{kind} only supports kind@tick")
            probs[kinds[kind]] = float(val)
        elif kinds[kind] == "journal_kill":
            journal_kill = int(val)
        elif kinds[kind] == "engine_kill":
            engine_kill = int(val)
        else:
            steps[kinds[kind]].append(int(val))
    return Chaos(
        seed=seed, delay_s=delay_s,
        tick_nan_steps=steps["tick_nan"],
        tick_nan_prob=probs.get("tick_nan", 0.0),
        tick_delay_steps=steps["tick_delay"],
        tick_delay_prob=probs.get("tick_delay", 0.0),
        prefill_raise_steps=steps["prefill_raise"],
        journal_kill_step=journal_kill,
        engine_kill_step=engine_kill,
        tenant_flood_steps=steps["tenant_flood"],
        tenant_flood_prob=probs.get("tenant_flood", 0.0),
    )
