# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Fault-injection harness: deterministic, seed-driven chaos.

Every recovery path in this package is tested by actually breaking
things — a NaN really reaches the gradients, a checkpoint writer really
dies between tmp-write and commit, a host really stalls — not by mocking
the failure's observers.  Faults fire deterministically: either from an
explicit step set, or from a per-(seed, kind, step) counter-mode RNG, so
a failing chaos test replays bit-identically from its seed.

    chaos = Chaos(seed=7, nan_steps=(3,), ckpt_write_failures=2)
    chaos.install()                       # checkpoint I/O hook
    eng = ChaosEngine(engine, chaos)      # step-level faults
    ...
    chaos.uninstall()

Fault kinds:
  * "nan"    — poison one parameter with NaN AFTER the injected step:
               the next forward/backward produces non-finite loss and
               gradients everywhere (exactly how real overflow spreads),
               driving the telemetry non-finite detector end-to-end.
  * "delay"  — sleep `delay_s` before the step (a straggling host;
               exercises the straggler gauges and the rebalancer).
  * "sigterm"— raise SIGTERM in-process at the injected step (the
               preemption notice; exercises PreemptionGuard's drain).
  * checkpoint I/O — `ckpt_write_failures` transient OSErrors on save
               attempts (exercises retry/backoff) and `kill_next_commit`
               a CheckpointKilled between tmp-write and commit
               (exercises the uncommitted-dir skip on restore).
"""

from __future__ import annotations

import signal
import time
from typing import Dict, Iterable, List, Optional

import numpy as np

import jax.numpy as jnp

from ..utils.checkpoint import CheckpointKilled, set_io_hook

_KIND_CODE = {"nan": 1, "delay": 2, "sigterm": 3}


class Chaos:
    """Deterministic fault schedule + checkpoint I/O fault hook."""

    def __init__(self, seed: int = 0, *,
                 nan_steps: Iterable[int] = (),
                 nan_prob: float = 0.0,
                 delay_steps: Iterable[int] = (),
                 delay_prob: float = 0.0,
                 delay_s: float = 0.25,
                 sigterm_step: Optional[int] = None,
                 ckpt_write_failures: int = 0):
        self.seed = int(seed)
        self.delay_s = float(delay_s)
        self._steps = {
            "nan": frozenset(int(s) for s in nan_steps),
            "delay": frozenset(int(s) for s in delay_steps),
            "sigterm": frozenset(
                () if sigterm_step is None else (int(sigterm_step),)
            ),
        }
        self._prob = {"nan": float(nan_prob), "delay": float(delay_prob),
                      "sigterm": 0.0}
        self._write_fails_left = int(ckpt_write_failures)
        self._kill_commit = False
        self.injected: List[Dict] = []  # JSON-safe fault log

    # -- schedule ----------------------------------------------------------

    def fires(self, kind: str, step: int) -> bool:
        """True when fault `kind` fires at `step` — explicit step set
        first, then the seeded probability draw (counter-mode: the
        decision for (seed, kind, step) never depends on call order)."""
        hit = step in self._steps[kind]
        p = self._prob[kind]
        if not hit and p > 0.0:
            rng = np.random.default_rng(
                (self.seed, _KIND_CODE[kind], int(step))
            )
            hit = bool(rng.random() < p)
        if hit:
            self.record(kind, at_step=step)
        return hit

    def record(self, fault: str, **fields) -> Dict:
        rec = {"fault": fault, **fields}
        self.injected.append(rec)
        return rec

    def log_faults(self, logger) -> None:
        """Write every injected fault as a `kind="fault"` JSONL record
        (telemetry/schema.py) and clear the log."""
        for rec in self.injected:
            logger.log_meta(kind="fault", **rec)
        self.injected = []

    # -- checkpoint I/O faults ---------------------------------------------

    def fail_next_writes(self, n: int) -> None:
        """Arm `n` transient write failures (each save ATTEMPT consumes
        one; the retry loop in utils/checkpoint.py rides them out)."""
        self._write_fails_left = int(n)

    def kill_next_commit(self) -> None:
        """Arm ONE simulated writer death between tmp-write and commit:
        the next save raises CheckpointKilled after the payload is fully
        written but before the rename+marker — on disk it looks exactly
        like a SIGKILL'd process."""
        self._kill_commit = True

    def checkpoint_hook(self, phase: str, path: str, attempt: int) -> None:
        if phase == "write" and self._write_fails_left > 0:
            self._write_fails_left -= 1
            self.record("ckpt_write_failure", path=path, attempts=attempt)
            raise OSError(
                f"chaos: injected transient checkpoint write failure "
                f"(attempt {attempt})"
            )
        if phase == "commit" and self._kill_commit:
            self._kill_commit = False
            self.record("ckpt_kill", path=path, attempts=attempt)
            raise CheckpointKilled(
                "chaos: writer killed between tmp-write and commit"
            )

    def install(self) -> "Chaos":
        set_io_hook(self.checkpoint_hook)
        return self

    def uninstall(self) -> None:
        set_io_hook(None)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


def poison_params(state):
    """NaN the [0, 0, ...] element of the first float parameter (sorted
    name order): the next step's forward sees a non-finite weight, so its
    loss AND every gradient leaf go non-finite — the honest propagation
    path, not a synthetic health vector."""
    import dataclasses
    for name in sorted(state.params):
        leaf = state.params[name]
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            new = dict(state.params)
            new[name] = leaf.at[(0,) * leaf.ndim].set(jnp.nan)
            return dataclasses.replace(state, params=new)
    raise ValueError("no float parameter leaf to poison")


class ChaosEngine:
    """Engine proxy that injects step-level faults: delays before the
    step, NaN poisoning after it, SIGTERM at it.  Tracks its own step
    counter (0-based, counting `step()` calls); everything else
    delegates to the wrapped engine."""

    def __init__(self, engine, chaos: Chaos):
        self.engine = engine
        self.chaos = chaos
        self.steps_run = 0

    def __getattr__(self, name):
        return getattr(self.engine, name)

    def step(self, state, batch):
        it = self.steps_run
        self.steps_run += 1
        if self.chaos.fires("delay", it):
            time.sleep(self.chaos.delay_s)
        if self.chaos.fires("sigterm", it):
            signal.raise_signal(signal.SIGTERM)
        state, loss = self.engine.step(state, batch)
        if self.chaos.fires("nan", it):
            state = poison_params(state)
        return state, loss
