# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Elastic, preemption-tolerant training.

Real TPU fleets run on preemptible capacity: a slice can vanish mid-step,
come back smaller, and the run is expected to continue — the reference
(and this repo until now) treated fault tolerance as a documented
non-goal.  This package makes the three missing pieces first-class:

  * `checkpoint` — CheckpointManager: async atomic saves that overlap
    Orbax I/O with the next training steps, adaptive cadence (checkpoint
    immediately when the telemetry anomaly detector fires, postmortem
    snapshots on non-finite health), bounded retry, and PreemptionGuard —
    a SIGTERM handler that drains one final committed checkpoint before
    the process dies.  Rides the atomic tmp-dir + rename + COMMITTED
    marker contract in utils/checkpoint.py.
  * `elastic` — restore a checkpoint saved on N devices onto an M-device
    mesh: the engine re-derives its ZeRO partition tables and
    NamedShardings for the new topology, Orbax reshards the global
    arrays on read, topology-shaped leaves (the quantized-grad-comm
    error-feedback residual) are re-derived, and the data loader resumes
    at the exact global sample offset.  Configurations that cannot
    reshape (pipeline stage slabs, MoE expert placement, TP/SP layouts)
    are refused loudly with both mesh shapes in the message.
  * `chaos` — deterministic, seed-driven fault injection: NaN'd
    parameters (poisoning the next step's gradients), delayed hosts
    (exercising the straggler gauges), checkpoint write failures and
    simulated writer kills between tmp-write and commit, and an injected
    SIGTERM — so every recovery path is tested by actually breaking
    things, not by mocks.
  * `straggler` — the first straggler MITIGATION: rebalance per-host
    data-shard sizes when the PR-5 `straggler_frac` gauge stays high.
"""

from .checkpoint import CheckpointManager, PreemptionGuard
from .elastic import (
    check_reshapeable, data_offset_batches, elastic_load,
)
from .chaos import (Chaos, ChaosEngine, ChaosServingEngine,
                    parse_serving_chaos)
from .straggler import ShardRebalancer, rebalance_shares

__all__ = [
    "CheckpointManager",
    "PreemptionGuard",
    "elastic_load",
    "check_reshapeable",
    "data_offset_batches",
    "Chaos",
    "ChaosEngine",
    "ChaosServingEngine",
    "parse_serving_chaos",
    "ShardRebalancer",
    "rebalance_shares",
]
