# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Preemption-safe checkpoint cadence: async atomic saves, anomaly-driven
cadence, and the SIGTERM drain.

`CheckpointManager` owns a run's checkpoint lifecycle:

  * async save — `save()` snapshots the TrainState to host memory
    synchronously (cheap: one D2H copy, and REQUIRED for correctness —
    the engine's jitted step donates the state buffers, so a background
    thread must never read device arrays the next step may invalidate),
    then a writer thread runs the Orbax serialization + atomic commit
    while training continues.  One save in flight at a time; a second
    request waits for the first (backpressure, never a dropped commit).
    `overlap_steps` counts training steps that ran while a save was in
    flight — the measured "steps hidden behind I/O" number (PROFILE.md).
  * adaptive cadence — `maybe_save(state, step, anomaly=...)` saves on
    the fixed interval AND immediately when the telemetry anomaly
    detector fires (the PR-5 flight-recorder signal: step-time spike or
    non-finite health).  A non-finite anomaly routes to a POSTMORTEM
    checkpoint under `<dir>/postmortem/` — preserved for debugging but
    invisible to `latest_step`, so the resume chain can never land on a
    NaN state.
  * preemption drain — pair with `PreemptionGuard`: the signal handler
    only sets a flag; the training loop observes it between steps and
    calls `maybe_save(..., force=True)` + `close()`, draining one final
    COMMITTED checkpoint before exit (a handler that saved inline could
    fire mid-step with the state donated).

Multi-host note: the async host-snapshot path requires fully-addressable
arrays (single-process meshes); on a multi-host run `save()` falls back
to a synchronous device-array save, where Orbax writes each host's
shards (utils/checkpoint.py handles the cross-host commit barrier).
"""

from __future__ import annotations

import os
import signal
import threading
import time
import warnings
from typing import Any, Dict, Optional

import numpy as np

import jax

from ..utils.checkpoint import _is_committed, _step_dir, save_checkpoint


class PreemptionGuard:
    """SIGTERM/preemption flag: installs handlers that record the signal
    and return — the training loop polls `triggered` between steps and
    drains a final checkpoint on its own schedule.  Restores the previous
    handlers on `uninstall()` / context exit.  Inert (with a warning)
    when not on the main thread, where CPython forbids signal handlers.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self.triggered = False
        self.signum: Optional[int] = None
        self._orig: Dict[int, Any] = {}
        self.active = False
        try:
            for s in signals:
                self._orig[s] = signal.signal(s, self._handler)
            self.active = True
        except ValueError:  # not the main thread
            warnings.warn(
                "PreemptionGuard inactive: signal handlers can only be "
                "installed from the main thread",
                stacklevel=2,
            )

    def _handler(self, signum, frame):
        self.triggered = True
        self.signum = signum

    def agreed(self, allgather=None) -> bool:
        """Host-agreed drain decision.  `triggered` is RANK-LOCAL —
        hosts can observe the preemption notice at different iterations,
        and a final save only some hosts enter mismatches
        save_checkpoint's collective barriers against the others' next
        training step (the same hazard that disables multi-host anomaly
        cadence in `CheckpointManager.maybe_save`).  On multi-host this
        ORs the flag across hosts, so every host calling at the same
        loop point drains at the same step; single-process returns the
        local flag directly.  `allgather` is injectable for tests
        (defaults to multihost_utils.process_allgather)."""
        if jax.process_count() == 1 and allgather is None:
            return self.triggered
        if allgather is None:
            from jax.experimental import multihost_utils
            allgather = multihost_utils.process_allgather
        flags = allgather(np.asarray(self.triggered, dtype=np.bool_))
        return bool(np.any(flags))

    def uninstall(self) -> None:
        for s, h in self._orig.items():
            signal.signal(s, h)
        self._orig = {}
        self.active = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.uninstall()
        return False


class CheckpointManager:
    """Async atomic checkpointing with interval + anomaly cadence.

        mgr = CheckpointManager(dir, every=50, engine=engine,
                                telemetry=telem)
        for it in range(iters):
            state, loss = engine.step(state, batch)
            mgr.note_step()
            mgr.maybe_save(state, it + 1, anomaly=flush_reason,
                           data_meta={...})
        mgr.close()

    Telemetry wiring (when a Telemetry is passed): counters
    `checkpoint_saves` / `checkpoint_postmortems` (+ `checkpoint_retries`
    from utils/checkpoint.py), gauges `checkpoint_save_s` /
    `checkpoint_last_step` / `checkpoint_overlap_steps`.
    """

    def __init__(self, directory: str, *, every: int = 0, engine=None,
                 telemetry=None, retries: int = 3, backoff: float = 0.5,
                 async_save: bool = True):
        self.directory = directory
        self.every = int(every)
        self.engine = engine
        self.telemetry = telemetry
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.async_save = bool(async_save)
        self.saves = 0
        self.postmortems = 0
        self.overlap_steps = 0          # steps run while a save was in flight
        self.last_saved_step: Optional[int] = None
        self.last_reason: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._pending_exc: Optional[BaseException] = None
        self._mh_anomaly_warned = False
        self._last_postmortem_step: Optional[int] = None

    # -- cadence -----------------------------------------------------------

    def maybe_save(self, state, step: int, *, anomaly: Optional[str] = None,
                   data_meta: Optional[dict] = None,
                   force: bool = False) -> Optional[str]:
        """Save when due; returns the reason ("interval" / "anomaly:<r>" /
        "final") or None.  `anomaly` is the flight-flush reason the caller
        observed this step (examples/common.py passes
        `telem.maybe_flush_flight`'s return); when no caller-side signal
        exists the manager CONSUMES `telemetry.flight_pending` itself —
        a non-None latch here means no flusher ran before us this step
        (the examples flush first when a metrics logger is on), and
        clearing it re-arms the registry's edge trigger for the next
        anomaly episode.  Non-finite anomalies snapshot to the postmortem
        dir — the state is already poisoned, so committing it into the
        resume chain would make `latest_step` restore a NaN; the same
        guard checks `telemetry.last_health` on EVERY due save, because a
        NaN episode outlives its one edge-triggered anomaly and the next
        interval/final save must not commit the poisoned state either."""
        failed_prev = False
        if force:
            # drain priority: a PREVIOUS background failure must not
            # abort the final save — warn, remember not to trust that
            # save as a commit below, and drain a fresh one
            try:
                self._raise_pending()
            except RuntimeError as e:
                failed_prev = True
                warnings.warn(
                    f"previous background checkpoint save failed "
                    f"({e.__cause__!r}); draining a fresh final save",
                    stacklevel=2,
                )
        else:
            self._raise_pending()
        single = jax.process_count() == 1
        if anomaly is not None and not single:
            # the anomaly signal is RANK-LOCAL (telemetry instruments
            # rank 0 only) but save_checkpoint is a collective with
            # multihost barriers: a save only one host enters deadlocks
            # against the others' next step.  Multi-host anomaly cadence
            # needs a host-agreed signal first — until then, interval /
            # final cadence only (deterministic on every host).
            if not self._mh_anomaly_warned:
                self._mh_anomaly_warned = True
                warnings.warn(
                    "anomaly-driven checkpoint cadence is disabled on "
                    "multi-host runs (rank-local signal cannot drive a "
                    "collective save); interval/final cadence still "
                    "applies", stacklevel=2,
                )
            anomaly = None
        if anomaly is None and self.telemetry is not None and single:
            pending = getattr(self.telemetry, "flight_pending", None)
            if pending is not None:
                anomaly = pending
                self.telemetry.flight_pending = None
        reason = None
        if force:
            reason = "final"
        elif anomaly is not None:
            reason = f"anomaly:{anomaly}"
        elif self.every and step % self.every == 0:
            reason = "interval"
        if reason is None:
            return None
        postmortem = anomaly is not None and "nonfinite" in str(anomaly)
        if not postmortem and self.telemetry is not None and single:
            # rank-local for the same reason as above: on multi-host the
            # hosts would route the collective save to different paths
            h = getattr(self.telemetry, "last_health", None)
            if h is not None and (
                h.get("nonfinite_grads")
                or not np.isfinite(h.get("loss", 0.0))
            ):
                postmortem = True
                # the returned reason must not sound resumable — this
                # save is invisible to latest_step, and the caller's
                # "saved checkpoint" log would otherwise promise a
                # restore point that does not exist
                reason = f"postmortem:{reason}"
        if postmortem and self._last_postmortem_step == step:
            # anomaly + interval/drain coinciding on a poisoned step:
            # the postmortem dir is already committed — a second save of
            # the same step would die on the already-committed check
            return None
        if postmortem:
            # the dedup above is process-local, but a resumed
            # deterministic run replays the same trajectory and re-hits
            # the same NaN step — the PREVIOUS process's postmortem for
            # this step is already committed on disk, and save_checkpoint
            # would die on its already-committed check (in the writer
            # thread, surfacing as an opaque background-save failure)
            pm = _step_dir(os.path.join(self.directory, "postmortem"), step)
            if _is_committed(pm):
                self._last_postmortem_step = step
                warnings.warn(
                    f"postmortem for step {step} already committed at "
                    f"{pm} (anomaly replayed after resume); skipping the "
                    f"duplicate save", stacklevel=2,
                )
                return None
        if not postmortem and self.last_saved_step == step:
            if not force:
                return None  # interval+anomaly coinciding: one commit is enough
            # preemption drain: last_saved_step records an ASYNC save at
            # enqueue time, not commit time — skipping on an in-flight
            # (possibly failing) save would drain nothing and lose the
            # state that was in hand.  Only a confirmed commit skips.
            if not failed_prev:
                try:
                    self.wait()
                    return None
                except RuntimeError:
                    pass  # the enqueued save failed: drain a fresh one
        if force and self._thread is not None:
            # drain priority once more: a still-IN-FLIGHT failing save
            # (for an earlier step) would otherwise surface inside
            # save()'s backpressure wait() and abort the final save
            try:
                self.wait()
            except RuntimeError as e:
                warnings.warn(
                    f"in-flight background checkpoint save failed "
                    f"({e.__cause__!r}); draining the final save anyway",
                    stacklevel=2,
                )
        self.save(state, step, data_meta=data_meta,
                  extra_meta={"reason": reason}, postmortem=postmortem)
        self.last_reason = reason
        return reason

    # -- the save itself ---------------------------------------------------

    def _meta(self, step: int, data_meta, extra_meta) -> dict:
        meta: Dict[str, Any] = {"step": int(step), "wall_ts": time.time()}
        if self.engine is not None:
            meta["elastic"] = self.engine.elastic_descriptor()
        if data_meta:
            meta["data"] = dict(data_meta)
        if extra_meta:
            meta.update(extra_meta)
        return meta

    def _host_snapshot(self, state):
        """TrainState copied to host numpy arrays, or None when any leaf
        is not fully addressable (multi-host) — the async path's defence
        against the step's buffer donation.  Only the addressability
        check gates the fallback: a real snapshot failure must raise,
        not silently degrade every save to the synchronous path."""
        if any(
            getattr(x, "is_fully_addressable", True) is False
            for x in jax.tree.leaves(state)
        ):
            return None
        # copy=True, not asarray: on CPU backends np.asarray can be a
        # ZERO-COPY view of the device buffer, which donation would
        # reuse under the writer thread — committing garbage
        return jax.tree.map(lambda x: np.array(x, copy=True), state)

    def save(self, state, step: int, *, data_meta: Optional[dict] = None,
             extra_meta: Optional[dict] = None,
             postmortem: bool = False, sync: bool = False) -> None:
        """Kick one checkpoint of `state` at `step` (async unless `sync`
        or the manager was built with async_save=False)."""
        self.wait()  # one in-flight save; also surfaces a prior failure
        directory = self.directory
        if postmortem:
            directory = os.path.join(self.directory, "postmortem")
            self.postmortems += 1
            self._last_postmortem_step = step
            if self.telemetry is not None:
                self.telemetry.counter("checkpoint_postmortems").inc()
        meta = self._meta(step, data_meta, extra_meta)
        snapshot = None
        if self.async_save and not sync:
            snapshot = self._host_snapshot(state)
        if snapshot is None:
            self._write(directory, state, step, meta, background=False,
                        postmortem=postmortem)
        else:
            self._thread = threading.Thread(
                target=self._write,
                args=(directory, snapshot, step, meta),
                kwargs={"postmortem": postmortem},
                name=f"ckpt-save-{step}", daemon=True,
            )
            self._thread.start()
        if not postmortem:
            self.last_saved_step = step

    def _write(self, directory, tree, step, meta, background=True,
               postmortem=False):
        t0 = time.perf_counter()
        try:
            save_checkpoint(
                directory, tree, step, meta=meta, retries=self.retries,
                backoff=self.backoff, telemetry=self.telemetry,
            )
        except BaseException as e:
            if not background:
                raise
            # background writer: stash the failure for the training
            # thread — wait()/the next maybe_save re-raises it there
            self._pending_exc = e
            return
        finally:
            dt = time.perf_counter() - t0
            if self.telemetry is not None:
                self.telemetry.gauge("checkpoint_save_s", dt)
        if postmortem:
            return  # postmortem counter already bumped in save(); the
            # saves counter and checkpoint_last_step gauge advertise the
            # RESUME chain (schema: "last COMMITTED checkpoint") and a
            # postmortem step is invisible to latest_step by design
        self.saves += 1
        if self.telemetry is not None:
            self.telemetry.counter("checkpoint_saves").inc()
            self.telemetry.gauge("checkpoint_last_step", step)

    # -- lifecycle ---------------------------------------------------------

    def note_step(self) -> None:
        """Call once per training step: counts steps whose compute ran
        while a save was in flight (the async-overlap measurement)."""
        if self._thread is not None and self._thread.is_alive():
            self.overlap_steps += 1
            if self.telemetry is not None:
                self.telemetry.gauge(
                    "checkpoint_overlap_steps", self.overlap_steps
                )

    def _raise_pending(self) -> None:
        if self._pending_exc is not None:
            exc, self._pending_exc = self._pending_exc, None
            raise RuntimeError(
                "background checkpoint save failed"
            ) from exc

    def wait(self) -> None:
        """Join any in-flight save; re-raises its failure here (the
        thread's exception must not die silently — a run that believes
        it is checkpointed when it is not loses everything at the next
        preemption)."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._raise_pending()

    def close(self) -> None:
        self.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # drain the writer even when the loop raised — but do not let a
        # background-save failure mask the original exception
        try:
            self.close()
        except RuntimeError:
            if exc == (None, None, None):
                raise
        return False
