# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Mesh-shape-changing resume: restore a checkpoint saved on N devices
onto an M-device mesh.

Why this works at all: every engine derives its ZeRO partition tables and
NamedShardings from the mesh it is constructed on (parallel/engine.py),
and Orbax stores GLOBAL arrays — so restoring into a fresh engine's
`state_target()` on the new mesh IS the reshard: each device reads only
the slices its new NamedSharding assigns it, zero extra copies, and
uneven tails are exact because the global shapes never changed.  What
does NOT carry over is topology-shaped state:

  * `TrainState.grad_residual` — the quantized-grad-comm error-feedback
    residual has global shape (n_devices, padded_elems): on a topology
    change it is re-derived (zeroed) and one step's quantization error
    goes uncompensated (the same contract as restoring a checkpoint
    saved without error feedback).
  * the data stream — the checkpoint meta records the global SAMPLE
    offset.  An unchanged global batch replays the per-batch stream
    bit-exactly from that offset (`data_offset_batches` +
    TokenLoader.seek_samples); a CHANGED global batch has no per-batch
    continuation (that stream is keyed by batch counter and size), so
    the examples switch to the per-sample indexed stream
    (TokenLoader(indexed=True)) at the saved offset — batch-size
    invariant from there on.
  * configurations that pin state to mesh positions — pipeline stage
    slabs, MoE expert placement, tensor/sequence-parallel layouts —
    cannot reshape and are REFUSED with both mesh shapes in the message
    (check_reshapeable).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..parallel.mesh import describe_mesh
from ..utils.checkpoint import (
    _resolve_step, _restore, _step_dir, _fill_legacy_leaves, read_meta,
)

# non-data mesh axes whose placement is semantic, not just layout: a
# pipeline stage owns a contiguous layer slab, an expert axis owns
# specific experts, TP/SP reserve tensor dims — none of these reshape by
# re-slicing global arrays alone
_PINNED_AXES = ("pipe", "expert", "model", "seq")


def check_reshapeable(saved: Optional[Dict[str, Any]], engine,
                      new: Optional[Dict[str, Any]] = None) -> bool:
    """Validate that `engine` can accept a checkpoint written by the
    engine described by `saved` (an `elastic_descriptor()` dict from the
    checkpoint meta).  Returns True when the topology CHANGED (elastic
    handling needed), False when it matches exactly.  Raises ValueError —
    naming both mesh shapes — for configs that cannot reshape.  `new`
    lets a caller that already built `engine.elastic_descriptor()` pass
    it in instead of deriving it twice.
    """
    if new is None:
        new = engine.elastic_descriptor()
    if saved is None:
        # pre-resilience checkpoint: no descriptor to compare — assume
        # same-topology (the plain load_checkpoint contract) but say so
        warnings.warn(
            "checkpoint has no elastic descriptor (pre-resilience meta); "
            "assuming it was saved on an identical mesh — a device-count "
            "mismatch will surface as an Orbax sharding error",
            stacklevel=3,
        )
        return False
    same_mesh = saved.get("mesh") == new["mesh"]
    if same_mesh:
        return False
    blockers = sorted(
        {
            ax
            for desc in (saved.get("mesh") or {}, new["mesh"])
            for ax, size in (desc.get("axes") or {}).items()
            if ax in _PINNED_AXES and size > 1
        }
    )
    if blockers:
        raise ValueError(
            f"cannot elastically resume: checkpoint was saved on mesh "
            f"{describe_mesh(saved.get('mesh'))} and this engine runs on "
            f"{describe_mesh(new['mesh'])}, but the {blockers} ax"
            f"{'es' if len(blockers) > 1 else 'is'} pin"
            f"{'' if len(blockers) > 1 else 's'} state to mesh positions "
            f"(pipeline stage slabs / MoE expert placement / TP+SP tensor "
            f"layouts) — only the 'data' axis supports shape-changing "
            f"resume; restore on a matching mesh or re-shard offline"
        )
    return True


def elastic_load(
    directory: str,
    engine,
    step: Optional[int] = None,
    retries: int = 3,
    backoff: float = 0.5,
    telemetry=None,
) -> Tuple[Any, Dict[str, Any]]:
    """Restore the latest (or `step`) COMMITTED checkpoint into `engine`,
    tolerating a different device count than it was saved on.

    Returns `(state, info)`: `state` lands in the engine's shardings on
    the CURRENT mesh; `info` is a JSON-safe resume report (feeds the
    `resume` telemetry record) carrying the old/new mesh descriptors, the
    data offset from the meta sidecar, what happened to topology-shaped
    leaves, and how many params changed greedy owner in the re-derived
    partition table.
    """
    step = _resolve_step(directory, step)
    path = _step_dir(directory, step)
    meta = read_meta(directory, step) or {}
    saved = meta.get("elastic")
    new_desc = engine.elastic_descriptor()
    changed = check_reshapeable(saved, engine, new=new_desc)

    target = engine.state_target()
    saved_res = (saved or {}).get("residual_shape")
    eng_res = new_desc["residual_shape"]
    residual_action = "kept"
    drop_residual = False
    if saved_res != eng_res:
        # topology-shaped leaf: (n_devices, padded_elems) cannot be
        # re-sliced meaningfully — restore the saved tensor as-is (its
        # global shape, replicated) only to satisfy the tree structure,
        # then re-derive.  When the checkpoint predates the meta sidecar
        # (saved_res None from a no-meta save) the engine-shaped target
        # either matches (same topology) or the restore surfaces it.
        if saved_res is not None:
            # numpy target -> Orbax restores this doomed leaf to HOST
            # memory: the residual is ~a full fp32 gradient, and a
            # replicated device restore would transiently occupy every
            # device exactly on the near-HBM-limit runs that
            # restore-instead-of-init exists for — it is discarded
            # right below
            target = dataclasses.replace(
                target,
                grad_residual=np.zeros(tuple(saved_res), np.float32),
            )
            drop_residual = True
            residual_action = (
                "rederived" if eng_res is not None else "dropped"
            )
        elif eng_res is not None and saved:
            # meta present and says: saved WITHOUT a residual; the
            # engine-target would ask Orbax for a leaf that isn't there
            # (handled by the legacy zero-fill below)
            target = dataclasses.replace(target, grad_residual=None)
            drop_residual = True
            residual_action = "zero_filled"

    state = _restore(path, target, retries=retries, backoff=backoff,
                     telemetry=telemetry)
    if drop_residual:
        state = dataclasses.replace(state, grad_residual=None)
        if eng_res is not None:
            warnings.warn(
                f"grad_residual re-derived for the new topology "
                f"(saved {saved_res} -> engine {eng_res}): one step's "
                f"quantization error goes uncompensated",
                stacklevel=2,
            )
    state = _fill_legacy_leaves(state, engine)

    moved = 0
    if changed and saved and saved.get("n_shard"):
        from ..parallel.partition import repartition_delta
        moved = len(repartition_delta(
            engine.model.param_shapes(),
            int(saved["n_shard"]), engine.n_shard,
        ))
    info = {
        "resumed_step": int(step),
        "elastic": bool(changed),
        "old_mesh": (saved or {}).get("mesh"),
        "new_mesh": new_desc["mesh"],
        "residual_action": residual_action,
        "moved_params": int(moved),
    }
    if "data" in meta:
        info["data"] = meta["data"]
    return state, info


def data_offset_batches(info_or_meta: Dict[str, Any],
                        global_batch: int) -> Optional[int]:
    """How many batches of the CURRENT run's `global_batch` the loader
    must skip so the resumed stream continues at the checkpoint's global
    sample offset — None when the checkpoint carries no data meta (the
    caller falls back to step-count replay).  Raises when the offset is
    not batch-aligned for the new geometry (a half-consumed batch cannot
    be resumed without sample-indexed loading — use
    TokenLoader(indexed=True), whose seek_samples accepts any offset).
    """
    data = info_or_meta.get("data") or {}
    samples = data.get("samples_seen")
    if samples is None:
        return None
    samples = int(samples)
    if samples % int(global_batch):
        raise ValueError(
            f"checkpoint data offset {samples} samples is not divisible "
            f"by the current global batch {global_batch} (saved with "
            f"global batch {data.get('global_batch')}); use an indexed "
            f"loader (TokenLoader(indexed=True).seek_samples) or pick a "
            f"batch size that divides the offset"
        )
    return samples // int(global_batch)
