# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Straggler mitigation: rebalance per-host data-shard sizes.

PR 5 built the ATTRIBUTION — `Telemetry.sample_stragglers` gathers each
host's uncoupled host-side prep wall and gauges `straggler_frac`.  This
module acts on it: when the fraction stays above a threshold for
`patience` consecutive samples (hysteresis — one GC pause must not
re-shard the fleet), per-host batch shares are recomputed
inverse-proportionally to the measured walls, so the slow host prepares
fewer samples per step and the others absorb the difference.  The GLOBAL
batch is preserved exactly (the optimizer semantics must not drift), and
every host keeps at least `min_share` samples (a host with zero share
would drop out of the data-parallel collective's expectations).

The rebalance applies to HOST-side data preparation only — the device
mesh and its sharding stay fixed.  A host feeding fewer samples pads its
per-device shard usage unevenly only when shares are not divisible by the
host's device count; callers that need device-exact sharding round
`min_share` up to local device multiples.
"""

from __future__ import annotations

from typing import List, Optional, Sequence


def rebalance_shares(walls: Sequence[float], global_batch: int,
                     min_share: int = 1) -> List[int]:
    """Integer per-host batch shares ∝ measured speed (1/wall), summing
    EXACTLY to `global_batch`, each >= `min_share` (largest-remainder
    rounding).  Hosts reporting no wall (<= 0) are treated as fastest."""
    n = len(walls)
    if n == 0:
        raise ValueError("no hosts to rebalance")
    if global_batch < n * min_share:
        raise ValueError(
            f"global batch {global_batch} cannot give {n} hosts "
            f"min_share={min_share} each"
        )
    floor = max(1e-9, min((w for w in walls if w > 0), default=1e-9))
    speed = [1.0 / max(w, floor) for w in walls]
    total = sum(speed)
    spendable = global_batch - n * min_share
    ideal = [min_share + spendable * s / total for s in speed]
    shares = [int(x) for x in ideal]
    rema = sorted(
        range(n), key=lambda i: ideal[i] - shares[i], reverse=True
    )
    for i in range(global_batch - sum(shares)):
        shares[rema[i % n]] += 1
    return shares


class ShardRebalancer:
    """Hysteresis wrapper: feed each straggler sample's per-host walls to
    `observe`; after `patience` consecutive samples with
    straggler_frac >= threshold it returns the new per-host shares (and
    re-arms), else None.

        reb = ShardRebalancer(global_batch=64, threshold=0.3, patience=3)
        shares = reb.observe(record["step_s_by_host"],
                             frac=record["straggler_frac"])
        if shares is not None:
            loader.set_host_share(shares[jax.process_index()])  # caller's
    """

    def __init__(self, global_batch: int, *, threshold: float = 0.25,
                 patience: int = 3, min_share: int = 1, telemetry=None):
        self.global_batch = int(global_batch)
        self.threshold = float(threshold)
        self.patience = int(patience)
        self.min_share = int(min_share)
        self.telemetry = telemetry
        self.streak = 0
        self.events = 0
        self.last_shares: Optional[List[int]] = None

    @staticmethod
    def straggler_frac(walls: Sequence[float]) -> float:
        """(slowest - median) / slowest — the PR-5 attribution formula."""
        if not walls:
            return 0.0
        s = sorted(walls)
        n = len(s)
        med = s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])
        worst = s[-1]
        return (worst - med) / worst if worst > 0 else 0.0

    def observe(self, walls: Sequence[float],
                frac: Optional[float] = None) -> Optional[List[int]]:
        """`frac`: pass the straggler record's already-gauged
        `straggler_frac` so the rebalance triggers on EXACTLY the value
        telemetry logged (the local fallback's plain median can differ
        from the record's _quantile interpolation on even host counts);
        computed from `walls` when omitted."""
        if frac is None:
            frac = self.straggler_frac(walls)
        if len(walls) > 1 and frac >= self.threshold:
            self.streak += 1
        else:
            self.streak = 0
        if self.streak < self.patience:
            return None
        self.streak = 0
        self.events += 1
        self.last_shares = rebalance_shares(
            walls, self.global_batch, self.min_share
        )
        if self.telemetry is not None:
            self.telemetry.counter("straggler_rebalances").inc()
        return self.last_shares
