// Copyright 2026 tiny-deepspeed-tpu authors
// SPDX-License-Identifier: Apache-2.0

// tds_dataloader: native prefetching token-batch pipeline.
//
// The reference has NO native components (SURVEY 2.9: 100% Python; its
// examples build batches with torch.randint on the host each iteration,
// reference example/ddp/train.py:23-24).  This is the TPU framework's
// native runtime piece: a C++ producer that keeps (B, T) next-token batches
// ready ahead of the device, so host batch assembly never sits on the step
// critical path.
//
//   * memory-maps a binary token corpus (uint16 or uint32 little-endian,
//     nanoGPT .bin convention) and samples random crops, or synthesizes
//     uniform random tokens when no file is given (the reference's
//     torch.randint workload);
//   * N producer threads fill a bounded ring of prepared batches
//     (x = tokens[i : i+T], y = tokens[i+1 : i+T+1] already shifted);
//   * consumers copy a ready slot into caller memory (the JAX host buffer)
//     and release it;
//   * deterministic per-slot xorshift64* streams seeded from (seed, slot).
//
// C ABI (ctypes-friendly), no dependencies beyond pthread:
//   tds_loader*  tds_loader_create(path_or_null, vocab, batch, seq,
//                                  seed, prefetch_slots, n_threads)
//   int          tds_loader_next(loader, int32* x, int32* y)   // blocks
//   long long    tds_loader_tokens(loader)     // corpus size in tokens
//   void         tds_loader_destroy(loader)
//   const char*  tds_loader_error()            // last create error

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

thread_local std::string g_error;

struct Rng {  // xorshift64* — deterministic, cheap, good enough for crops
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 0x9e3779b97f4a7c15ULL) {}
  uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1DULL;
  }
  uint64_t below(uint64_t n) { return n ? next() % n : 0; }
};

struct Batch {
  std::vector<int32_t> x, y;
  // slot lifecycle: FREE -> FILLING (a worker owns it) -> READY -> FREE
  enum State { FREE = 0, FILLING = 1, READY = 2 };
  std::atomic<int> state{FREE};
};

struct Loader {
  // corpus (nullptr => synthetic mode)
  const uint8_t* map = nullptr;
  size_t map_bytes = 0;
  int token_width = 2;  // bytes per token in the file
  long long n_tokens = 0;
  int fd = -1;

  int vocab = 50304;
  int batch = 1, seq = 1024;
  uint64_t seed = 0;

  std::vector<std::unique_ptr<Batch>> ring;
  size_t head = 0;  // next slot the consumer takes
  std::atomic<uint64_t> produced{0};
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::vector<std::thread> workers;
  std::atomic<uint64_t> next_job{0};

  int32_t token_at(long long i) const {
    if (token_width == 2) {
      uint16_t v;
      std::memcpy(&v, map + i * 2, 2);
      return static_cast<int32_t>(v);
    }
    uint32_t v;
    std::memcpy(&v, map + i * 4, 4);
    return static_cast<int32_t>(v);
  }

  void fill(Batch& b, uint64_t job_id) {
    Rng rng(seed * 0x100000001b3ULL + job_id + 1);
    const long long usable = n_tokens - seq - 1;
    for (int r = 0; r < batch; ++r) {
      if (map && usable > 0) {
        long long start = static_cast<long long>(rng.below(usable));
        for (int t = 0; t < seq; ++t) {
          b.x[r * seq + t] = token_at(start + t);
          b.y[r * seq + t] = token_at(start + t + 1);
        }
      } else {  // synthetic: uniform tokens, targets shifted like a corpus
        int32_t prev = static_cast<int32_t>(rng.below(vocab));
        for (int t = 0; t < seq; ++t) {
          int32_t nxt = static_cast<int32_t>(rng.below(vocab));
          b.x[r * seq + t] = prev;
          b.y[r * seq + t] = nxt;
          prev = nxt;
        }
      }
    }
  }

  void worker() {
    for (;;) {
      uint64_t job;
      size_t slot;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] {
          if (stop.load()) return true;
          uint64_t j = next_job.load();
          return ring[j % ring.size()]->state.load() == Batch::FREE;
        });
        if (stop.load()) return;
        job = next_job.fetch_add(1);
        slot = job % ring.size();
        ring[slot]->state.store(Batch::FILLING);
      }
      fill(*ring[slot], job);
      {
        std::lock_guard<std::mutex> lk(mu);
        ring[slot]->state.store(Batch::READY);
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" {

const char* tds_loader_error() { return g_error.c_str(); }

void* tds_loader_create(const char* path, int vocab, int batch, int seq,
                        uint64_t seed, int prefetch_slots, int n_threads) {
  auto* L = new Loader();
  L->vocab = vocab;
  L->batch = batch;
  L->seq = seq;
  L->seed = seed;

  if (path && path[0]) {
    L->fd = ::open(path, O_RDONLY);
    if (L->fd < 0) {
      g_error = std::string("cannot open ") + path;
      delete L;
      return nullptr;
    }
    struct stat st;
    ::fstat(L->fd, &st);
    L->map_bytes = static_cast<size_t>(st.st_size);
    // token width: assume uint16 unless the size suggests uint32 via suffix
    const char* dot = std::strrchr(path, '.');
    L->token_width = (dot && std::strcmp(dot, ".u32") == 0) ? 4 : 2;
    L->n_tokens = static_cast<long long>(L->map_bytes / L->token_width);
    if (L->n_tokens < seq + 2) {
      g_error = "corpus smaller than one sequence";
      ::close(L->fd);
      delete L;
      return nullptr;
    }
    L->map = static_cast<const uint8_t*>(
        ::mmap(nullptr, L->map_bytes, PROT_READ, MAP_PRIVATE, L->fd, 0));
    if (L->map == MAP_FAILED) {
      g_error = "mmap failed";
      ::close(L->fd);
      delete L;
      return nullptr;
    }
    ::madvise(const_cast<uint8_t*>(L->map), L->map_bytes, MADV_RANDOM);
  }

  int slots = prefetch_slots > 1 ? prefetch_slots : 2;
  for (int i = 0; i < slots; ++i) {
    auto b = std::make_unique<Batch>();
    b->x.resize(static_cast<size_t>(batch) * seq);
    b->y.resize(static_cast<size_t>(batch) * seq);
    L->ring.push_back(std::move(b));
  }
  int threads = n_threads > 0 ? n_threads : 1;
  if (threads > slots) threads = slots;
  for (int i = 0; i < threads; ++i)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

int tds_loader_next(void* handle, int32_t* out_x, int32_t* out_y) {
  auto* L = static_cast<Loader*>(handle);
  size_t slot = L->head % L->ring.size();
  Batch& b = *L->ring[slot];
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_ready.wait(lk, [&] {
      return b.state.load() == Batch::READY || L->stop.load();
    });
    if (L->stop.load()) return -1;
  }
  std::memcpy(out_x, b.x.data(), b.x.size() * sizeof(int32_t));
  std::memcpy(out_y, b.y.data(), b.y.size() * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    b.state.store(Batch::FREE);
    L->head += 1;
  }
  L->cv_free.notify_all();
  return 0;
}

long long tds_loader_tokens(void* handle) {
  return static_cast<Loader*>(handle)->n_tokens;
}

void tds_loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
  }
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  if (L->map) ::munmap(const_cast<uint8_t*>(L->map), L->map_bytes);
  if (L->fd >= 0) ::close(L->fd);
  delete L;
}

}  // extern "C"
