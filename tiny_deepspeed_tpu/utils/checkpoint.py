# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Checkpoint/resume for sharded train state (Orbax-backed).

The reference has NO save/load anywhere — no state_dict on its optimizers,
no torch.save (SURVEY §5.4: "none").  Here sharded-pytree checkpointing is
first-class: each host writes only the shards it owns, and restore places
shards directly into the engine's NamedShardings (no full-replica
materialization on any single host).

    save_checkpoint(dir, state, step)
    state = load_checkpoint(dir, engine, step=None)      # None -> latest
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step:08d}")


def latest_step(directory: str) -> Optional[int]:
    """Largest saved step number, or None."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            try:
                steps.append(int(name[len("step_"):]))
            except ValueError:
                continue
    return max(steps) if steps else None


def save_checkpoint(directory: str, state, step: int) -> str:
    """Write `state` (any pytree of jax.Arrays, e.g. TrainState) at `step`."""
    path = _step_dir(directory, step)
    ckptr = _checkpointer()
    ckptr.save(path, state)
    ckptr.wait_until_finished()
    return path


def load_checkpoint(directory: str, engine=None, step: Optional[int] = None,
                    target=None):
    """Restore a checkpoint.

    With `engine`, the restored TrainState lands directly in the engine's
    resting shardings (params replicated or ZeRO-3-sharded, optimizer state
    ZeRO-sharded) — each device reads only its shard.  Alternatively pass an
    explicit `target` pytree of ShapeDtypeStruct(+sharding).
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_dir(directory, step)

    if target is None and engine is not None:
        from ..parallel.engine import TrainState

        shapes = jax.eval_shape(
            lambda: engine.init(jax.random.PRNGKey(0))
        )
        shardings = TrainState(
            params=engine._param_shardings,
            opt_state=engine._opt_shardings,
            scaler=engine._scaler_shardings,
            dropout_base=engine._dropout_shardings,
            grad_residual=getattr(engine, "_residual_shardings", None),
        )
        target = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            shapes,
            shardings,
        )
        state = _checkpointer().restore(path, target)
        if engine._dropout_shardings is not None \
                and state.dropout_base is None:
            # legacy checkpoint (saved before the dropout base moved into
            # TrainState): Orbax fills the absent leaf with None, which
            # would crash the first step.  Fall back to the fixed base the
            # old engine replayed after restore — identical masks to
            # resuming on the old code, just not seed-derived.
            import warnings
            warnings.warn(
                "checkpoint has no dropout_base (pre-round-4 format); "
                "using the legacy fixed mask-stream base — re-save to "
                "upgrade",
                stacklevel=2,
            )
            base = jax.device_put(
                jax.random.PRNGKey(0xD0), engine._dropout_shardings
            )
            state = dataclasses.replace(state, dropout_base=base)
        if getattr(engine, "_residual_shardings", None) is not None \
                and state.grad_residual is None:
            # checkpoint saved without grad_comm error feedback (or
            # pre-round-6): resume with a zero residual — the feedback
            # loop re-fills it within a step; only the one step's
            # quantization error goes uncompensated
            state = dataclasses.replace(
                state,
                grad_residual=jax.jit(
                    functools.partial(
                        jnp.zeros, engine._residual_shape, jnp.float32
                    ),
                    out_shardings=engine._residual_shardings,
                )(),
            )
        return state
    return _checkpointer().restore(path, target)
