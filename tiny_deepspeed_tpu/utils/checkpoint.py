# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Checkpoint/resume for sharded train state (Orbax-backed), preemption-safe.

The reference has NO save/load anywhere — no state_dict on its optimizers,
no torch.save (SURVEY §5.4: "none").  Here sharded-pytree checkpointing is
first-class: each host writes only the shards it owns, and restore places
shards directly into the engine's NamedShardings (no full-replica
materialization on any single host).

    save_checkpoint(dir, state, step)
    state = load_checkpoint(dir, engine, step=None)      # None -> latest

Preemption safety (the resilience subsystem rides on these guarantees):

  * atomic commit — the payload is written into a dot-prefixed tmp dir,
    os.rename'd to its final `step_XXXXXXXX` name, then a `COMMITTED`
    marker file is dropped inside.  A reader therefore never sees a
    half-written checkpoint under a `step_*` name, and a crash between
    rename and marker leaves a dir that `latest_step` SKIPS (an Orbax
    `_CHECKPOINT_METADATA` file is accepted as a legacy commit signal for
    checkpoints written before the marker existed — it is Orbax's own
    atomic-finalize artifact, absent from partial copies).
  * bounded retry — transient I/O failures around the Orbax save/restore
    are retried with exponential backoff; the final exception names the
    path and attempt count, and a telemetry `checkpoint_retries` counter
    records every retry.
  * meta sidecar — `save_checkpoint(..., meta={...})` persists a JSON
    document (mesh descriptor, data offset, ...) next to the payload;
    `read_meta` returns it.  The elastic-resume path
    (tiny_deepspeed_tpu/resilience/elastic.py) keys off it.

Fault injection: `set_io_hook(fn)` installs a callable invoked at the
"write" (before the Orbax save) and "commit" (after the tmp write, before
the rename) phases of every save attempt.  The resilience chaos harness
uses it to inject transient write failures (retried) and
`CheckpointKilled` (NOT retried — it simulates the process dying between
tmp-write and commit, so the partial dir is left behind exactly as a real
SIGKILL would).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import shutil
import time
import warnings
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp

COMMIT_MARKER = "COMMITTED"
META_FILE = "ckpt_meta.json"
# Orbax's own atomic-finalize artifact: present in any checkpoint its
# finalizer completed, absent from partial copies — the legacy commit
# signal for pre-marker checkpoints
_ORBAX_COMMIT = "_CHECKPOINT_METADATA"


class CheckpointKilled(RuntimeError):
    """Raised by a fault-injection hook to simulate the writer dying
    mid-save.  Never retried: it must propagate so the partially written
    state on disk looks exactly like a real preemption's."""


_io_hook: Optional[Callable] = None


def set_io_hook(fn: Optional[Callable]) -> None:
    """Install (or clear, with None) the save-path fault-injection hook:
    `fn(phase, path, attempt)` with phase in {"write", "commit"}; raising
    makes that attempt fail (CheckpointKilled aborts the save outright,
    anything else is retried with backoff)."""
    global _io_hook
    _io_hook = fn


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step:08d}")


def _is_committed(path: str) -> bool:
    return (
        os.path.exists(os.path.join(path, COMMIT_MARKER))
        or os.path.exists(os.path.join(path, _ORBAX_COMMIT))
    )


def list_steps(directory: str) -> Tuple[List[int], List[str]]:
    """(committed step numbers ascending, skipped uncommitted dir names).

    A dir counts only when its name parses as `step_<int>` AND it carries
    a commit signal; everything else `step_`-prefixed is reported as
    skipped so callers can say WHY a resume went further back than
    expected (a partially written or empty `step_*` dir used to win
    `max(steps)` and crash the restore)."""
    if not os.path.isdir(directory):
        return [], []
    committed, skipped = [], []
    for name in sorted(os.listdir(directory)):
        if not name.startswith("step_"):
            continue
        try:
            step = int(name[len("step_"):])
        except ValueError:
            skipped.append(name)
            continue
        if _is_committed(os.path.join(directory, name)):
            committed.append(step)
        else:
            skipped.append(name)
    return sorted(committed), skipped


def latest_step(directory: str) -> Optional[int]:
    """Largest COMMITTED step number, or None.  Uncommitted/partial
    `step_*` dirs (a crashed writer's leavings) are skipped."""
    committed, _ = list_steps(directory)
    return committed[-1] if committed else None


def _multihost_barrier(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def _with_retries(fn, what: str, *, retries: int, backoff: float,
                  telemetry=None):
    """Run `fn(attempt)` under the checkpoint I/O retry contract: bounded
    attempts with exponential backoff (`backoff * 2**attempt` sleeps),
    `checkpoint_retries` counted on `telemetry`, CheckpointKilled
    re-raised untouched (a simulated writer death must leave partial
    state exactly as a real kill would — no cleanup, no retry), and a
    final RuntimeError naming `what` and the attempt count."""
    attempts = int(retries) + 1
    last_err: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn(attempt)
        except CheckpointKilled:
            raise
        except Exception as e:  # transient I/O: back off and retry
            last_err = e
            if attempt < attempts - 1:
                if telemetry is not None:
                    telemetry.counter("checkpoint_retries").inc()
                time.sleep(backoff * (2 ** attempt))
    raise RuntimeError(
        f"{what} failed after {attempts} attempt(s); "
        f"last error: {last_err!r}"
    ) from last_err


def save_checkpoint(directory: str, state, step: int, *,
                    meta: Optional[dict] = None, retries: int = 3,
                    backoff: float = 0.5, telemetry=None) -> str:
    """Write `state` (any pytree of jax/numpy arrays, e.g. TrainState) at
    `step`, atomically: tmp dir -> rename -> COMMITTED marker.

    `meta` is persisted as a JSON sidecar (read_meta) — the elastic-resume
    path stores the mesh descriptor and data offset there.  Transient I/O
    failures retry up to `retries` times with exponential backoff
    (`backoff * 2**attempt` seconds); `telemetry.counter(
    "checkpoint_retries")` counts them when a Telemetry is passed.
    """
    directory = os.path.abspath(directory)
    if jax.process_index() == 0:
        os.makedirs(directory, exist_ok=True)
    path = _step_dir(directory, step)
    tmp = os.path.join(directory, f".tmp_step_{step:08d}")
    if os.path.exists(path) and _is_committed(path):
        # never silently destroy a committed checkpoint — and without
        # this check the os.rename below would burn every retry on
        # ENOTEMPTY before failing with a misleading message
        raise FileExistsError(
            f"checkpoint step {step} already committed at {path}; "
            f"delete it first to re-save this step"
        )
    if jax.process_count() > 1:
        # a per-host retry around a collective save would desync the
        # barrier tags (the failing host re-enters attempt k+1's
        # barriers while the others wait inside attempt k's) and hang
        # the fleet: fail fast — the job-level restart IS the
        # multi-host retry
        retries = 0

    def _attempt(attempt):
        if os.path.exists(path):
            if _is_committed(path) and attempt > 0:
                # a prior attempt of THIS call died between its rename
                # landing and the marker write (rename is atomic and
                # only runs after Orbax finished, so the payload is
                # complete): just (re)drop the marker instead of
                # burning the remaining retries on ENOTEMPTY renames
                if jax.process_index() == 0:
                    with open(os.path.join(path, COMMIT_MARKER),
                              "w") as f:
                        f.write(f"step={step}\nts={time.time()}\n")
                return path
            # a previous writer (or a prior attempt that failed between
            # rename and marker) left an uncommitted dir at the final
            # path: the payload may be complete but cannot be trusted —
            # replace it, else os.rename below fails with ENOTEMPTY
            if jax.process_index() == 0:
                shutil.rmtree(path, ignore_errors=True)
            _multihost_barrier(f"ckpt_clean_{step}_{attempt}")
        if _io_hook is not None:
            _io_hook("write", tmp, attempt)
        if jax.process_index() == 0 and os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        _multihost_barrier(f"ckpt_tmp_{step}_{attempt}")
        ckptr = _checkpointer()
        ckptr.save(tmp, state)
        ckptr.wait_until_finished()
        if jax.process_index() == 0 and meta is not None:
            with open(os.path.join(tmp, META_FILE), "w") as f:
                json.dump(meta, f, indent=1, sort_keys=True)
        if _io_hook is not None:
            _io_hook("commit", tmp, attempt)
        _multihost_barrier(f"ckpt_commit_{step}_{attempt}")
        if jax.process_index() == 0:
            os.rename(tmp, path)
            with open(os.path.join(path, COMMIT_MARKER), "w") as f:
                f.write(f"step={step}\nts={time.time()}\n")
        _multihost_barrier(f"ckpt_done_{step}_{attempt}")
        return path

    return _with_retries(
        _attempt, f"checkpoint save of step {step} to {path}",
        retries=retries, backoff=backoff, telemetry=telemetry,
    )


def read_meta(directory: str, step: int) -> Optional[dict]:
    """The JSON meta sidecar saved with `save_checkpoint(..., meta=...)`,
    or None (no sidecar / unreadable — pre-resilience checkpoints have
    none)."""
    p = os.path.join(_step_dir(directory, step), META_FILE)
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fill_legacy_leaves(state, engine):
    """Post-restore repairs shared by plain and elastic loads: legacy
    checkpoints missing dropout_base / grad_residual leaves get working
    substitutes in the engine's shardings."""
    if engine._dropout_shardings is not None and state.dropout_base is None:
        # legacy checkpoint (saved before the dropout base moved into
        # TrainState): Orbax fills the absent leaf with None, which
        # would crash the first step.  Fall back to the fixed base the
        # old engine replayed after restore — identical masks to
        # resuming on the old code, just not seed-derived.
        warnings.warn(
            "checkpoint has no dropout_base (pre-round-4 format); "
            "using the legacy fixed mask-stream base — re-save to "
            "upgrade",
            stacklevel=3,
        )
        base = jax.device_put(
            jax.random.PRNGKey(0xD0), engine._dropout_shardings
        )
        state = dataclasses.replace(state, dropout_base=base)
    if getattr(engine, "_residual_shardings", None) is not None \
            and state.grad_residual is None:
        # checkpoint saved without grad_comm error feedback (or on a
        # different topology): resume with a zero residual — the feedback
        # loop re-fills it within a step; only the one step's
        # quantization error goes uncompensated
        state = dataclasses.replace(
            state,
            grad_residual=jax.jit(
                functools.partial(
                    jnp.zeros, engine._residual_shape, jnp.float32
                ),
                out_shardings=engine._residual_shardings,
            )(),
        )
    return state


def _restore(path: str, target, retries: int = 3, backoff: float = 0.5,
             telemetry=None):
    """Orbax restore with the same bounded retry/backoff as the save."""
    if jax.process_count() > 1:
        # same reasoning as save_checkpoint: the restore is collective
        # (every process reads its shards of the global arrays) — one
        # host retrying alone diverges from the rest
        retries = 0
    return _with_retries(
        lambda attempt: _checkpointer().restore(path, target),
        f"checkpoint restore from {path}",
        retries=retries, backoff=backoff, telemetry=telemetry,
    )


def _resolve_step(directory: str, step: Optional[int]) -> int:
    committed, skipped = list_steps(directory)
    if step is None:
        if not committed:
            extra = (
                f" (skipped uncommitted/partial dirs: {skipped} — a "
                f"crashed writer's leavings; delete them or re-save)"
                if skipped else ""
            )
            raise FileNotFoundError(
                f"no committed checkpoints under {directory}{extra}"
            )
        return committed[-1]
    if step not in committed:
        path = _step_dir(directory, step)
        if os.path.isdir(path):
            raise FileNotFoundError(
                f"checkpoint step {step} under {directory} exists but "
                f"is not committed (no {COMMIT_MARKER} marker — the "
                f"writer likely died mid-save); committed steps: "
                f"{committed}"
            )
        raise FileNotFoundError(
            f"no checkpoint step {step} under {directory}; committed "
            f"steps: {committed}"
        )
    return step


def load_checkpoint(directory: str, engine=None, step: Optional[int] = None,
                    target=None, retries: int = 3, backoff: float = 0.5,
                    telemetry=None):
    """Restore a checkpoint.

    With `engine`, the restored TrainState lands directly in the engine's
    resting shardings (params replicated or ZeRO-3-sharded, optimizer state
    ZeRO-sharded) — each device reads only its shard.  Alternatively pass an
    explicit `target` pytree of ShapeDtypeStruct(+sharding).

    Only COMMITTED checkpoints are considered (atomic-save contract above);
    partial dirs are skipped and named in the error when nothing restorable
    remains.  To restore onto a mesh with a DIFFERENT device count than
    the checkpoint was saved on, use
    `tiny_deepspeed_tpu.resilience.elastic.elastic_load` — it re-derives
    topology-dependent leaves; this plain loader assumes the layout
    matches.
    """
    step = _resolve_step(directory, step)
    path = _step_dir(directory, step)

    if target is None and engine is not None:
        state = _restore(path, engine.state_target(), retries=retries,
                         backoff=backoff, telemetry=telemetry)
        return _fill_legacy_leaves(state, engine)
    return _restore(path, target, retries=retries, backoff=backoff,
                    telemetry=telemetry)
