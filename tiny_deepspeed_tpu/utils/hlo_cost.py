# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Compute/HBM cost ledger from compiled HLO: the roofline's other two axes.

`hlo_comm.collective_ledger` prices the WIRE axis of a compiled step from
the post-SPMD HLO text.  Compute, until now, was a hand formula
(bench.py `flops_tok_matmul`) and HBM traffic was not measured at all —
so "MFU" compared a measured time against an analytic numerator, and
nothing could say whether a program is compute-, HBM-, or wire-bound.

This module closes the loop with the same machinery: split the HLO into
computations, multiply while bodies by their static trip counts, and walk
the call graph from the entry — but ledger FLOPs and HBM bytes instead of
collective payloads.

FLOPs
  dot:  2 * prod(result dims) * prod(lhs contracting dim sizes) — the
        contracting-dim product is read off `lhs_contracting_dims={...}`
        against the inline lhs operand shape, so batched attention dots
        (lhs_batch_dims) come out right without special-casing.
  convolution:  2 * prod(result dims) * (rhs elems / out_channels) with
        out_channels inferred as the largest dim shared by rhs and result
        — an approximation (no conv in this repo today); such lines are
        flagged in `approx_ops` so a future conv user sees the caveat.
  Dots inside fusion payload computations are reached through the fusion
  call edge and attributed to the fusion's calling computation — on TPU
  the backend moves dots into fusions and a top-level-only scan would
  count zero FLOPs.

HBM bytes (a traffic model, not a profile)
  Per instruction: operand bytes + result bytes, i.e. every kernel reads
  its inputs from HBM and writes its output.  Bookkeeping ops that move
  no data (parameter, constant, tuple, get-tuple-element, bitcast) and
  container ops whose bodies are walked separately (while, conditional,
  call) are skipped.  A fusion LINE is counted — its operands + result
  are exactly the fused kernel's HBM traffic — and its payload
  computation is then excluded from HBM accounting (the intermediates
  live in registers/VMEM; counting them would price fusion at zero).
  `dynamic-update-slice` roots (including `*dynamic-update-slice*`
  fusions) alias their destination: only the updated slice is read into
  and written back, so the destination operand is dropped and the update
  operand counted twice (read + write).  Without this, the 1024-trip
  embedding-scatter loops in the 124M step would charge ~150 MB of
  fictitious accumulator traffic per trip.

Everything is loop-aware: while bodies multiply by `_trip_count` trips
(the 12-layer scan, the seq-length scatter loops), with an in-loop vs
top-level split mirroring the wire ledger, and a per-loop attribution
list (`loops`) that trace_view uses to size per-layer compute spans next
to the wire-sized collective spans.

tests/test_hlo_cost.py pins the dot math exactly on tiny synthetic HLO,
pins trip-count multiplication against the scan length, and pins the
124M GPT-2 train step within 2% of bench's analytic matmul formula.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from .hlo_comm import (
    _BRANCH_RE,
    _CALL_RE,
    _DTYPE_BYTES,
    _FUSION_CALL_RE,
    _SHAPE_RE,
    _TRUE_FALSE_RE,
    _WHILE_RE,
    _shape_bytes,
    _split_computations,
    _trip_count,
    collective_ledger,
)

# ---------------------------------------------------------------------------
# Per-device roofline tables (public spec-sheet numbers).
#
# Peak dense bf16 FLOP/s per chip — the same table bench.py has carried
# since round 1 (bench._peak_flops_per_chip now delegates here so the two
# can never drift).  HBM and interchip (ICI) bandwidths are per chip:
#   HBM    v4 1228 GB/s · v5e 819 GB/s · v5p 2765 GB/s · v6e 1640 GB/s
#   ICI    v4 300 GB/s  · v5e 200 GB/s · v5p 600 GB/s  · v6e 448 GB/s
# Unknown devices (the CPU mesh) fall back to v5e-class numbers, matching
# bench's long-standing default peak.
# ---------------------------------------------------------------------------

_PEAK_FLOPS_TABLE: Tuple[Tuple[str, float], ...] = (
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v6", 918e12), ("v4", 275e12),
)
DEFAULT_PEAK_FLOPS = 197e12

_HBM_BW_TABLE: Tuple[Tuple[str, float], ...] = (
    ("v5 lite", 819e9), ("v5e", 819e9), ("v5p", 2765e9),
    ("v6", 1640e9), ("v4", 1228e9),
)
DEFAULT_HBM_BW = 819e9

_WIRE_BW_TABLE: Tuple[Tuple[str, float], ...] = (
    ("v5 lite", 200e9), ("v5e", 200e9), ("v5p", 600e9),
    ("v6", 448e9), ("v4", 300e9),
)
DEFAULT_WIRE_BW = 200e9


def _lookup(table: Tuple[Tuple[str, float], ...], default: float,
            device_kind: Optional[str]) -> float:
    kind = (device_kind or "").lower()
    for key, val in table:
        if key in kind:
            return val
    return default


def peak_flops_per_chip(device_kind: Optional[str]) -> float:
    """Peak dense bf16 FLOP/s for a device-kind string (substring match)."""
    return _lookup(_PEAK_FLOPS_TABLE, DEFAULT_PEAK_FLOPS, device_kind)


def hbm_bw_per_chip(device_kind: Optional[str]) -> float:
    """HBM bandwidth (bytes/s) for a device-kind string."""
    return _lookup(_HBM_BW_TABLE, DEFAULT_HBM_BW, device_kind)


def wire_bw_per_chip(device_kind: Optional[str]) -> float:
    """Interchip (ICI) bandwidth (bytes/s) for a device-kind string."""
    return _lookup(_WIRE_BW_TABLE, DEFAULT_WIRE_BW, device_kind)


# ---------------------------------------------------------------------------
# Line parsing
# ---------------------------------------------------------------------------

# opcode after "= <result shape> " — tuple-typed results "(s32[], ...)" are
# a parenthesized group, plain results a non-space token
_OP_RE = re.compile(r"=\s*(?:\([^=]*?\)|\S+)\s+([\w\-]+)\(")
_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")

# ops that move no HBM data of their own
_HBM_SKIP_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id", "opt-barrier",
})
# container ops whose bodies are walked separately
_HBM_CONTAINER_OPS = frozenset({"while", "conditional", "call"})


def _strip_metadata(line: str) -> str:
    """Drop `metadata={...}` — op_name strings may contain shape-like text
    that would be mis-summed as payload."""
    i = line.find(", metadata=")
    return line[:i] if i >= 0 else line


def _shapes_of(line: str) -> List[int]:
    """Byte size of every typed shape on an (already metadata-stripped)
    instruction line, in textual order: result first, then operands."""
    out: List[int] = []
    for dt, dims in _SHAPE_RE.findall(line):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * _DTYPE_BYTES[dt])
    return out


def _dims_of(shape_txt: str) -> List[int]:
    return [int(d) for d in shape_txt.split(",") if d]


def _dot_flops(line: str) -> Tuple[float, str]:
    """(FLOPs, signature) of one `dot` instruction line.

    FLOPs = 2 * prod(result dims) * prod(lhs contracting dim sizes).
    Batch dims are already part of the result, so no special handling.
    """
    head, args = line.split(" dot(", 1)
    if "=" not in head:
        return 0.0, ""
    res_m = _SHAPE_RE.search(head.split("=", 1)[1])
    lhs_m = _SHAPE_RE.search(args)
    if res_m is None or lhs_m is None:
        return 0.0, ""
    res_dims = _dims_of(res_m.group(2))
    lhs_dims = _dims_of(lhs_m.group(2))
    cm = _LHS_CONTRACT_RE.search(line)
    contract = [int(d) for d in cm.group(1).split(",") if d] if cm else []
    k = 1
    for c in contract:
        if c < len(lhs_dims):
            k *= lhs_dims[c]
    n = 1
    for d in res_dims:
        n *= d
    # signature: result <- lhs, for cost-center aggregation
    shapes = _SHAPE_RE.findall(args)
    rhs_txt = ("%s[%s]" % shapes[1]) if len(shapes) > 1 else "?"
    sig = "dot %s[%s] <- %s[%s] x %s" % (
        res_m.group(1), res_m.group(2), lhs_m.group(1), lhs_m.group(2),
        rhs_txt,
    )
    return 2.0 * n * k, sig


def _conv_flops(line: str) -> Tuple[float, str]:
    """Approximate convolution FLOPs: 2 * out_elems * rhs_elems /
    out_channels, with out_channels = the largest dim shared by rhs and
    result.  Flagged via `approx_ops` — this repo emits no convolutions."""
    head, args = line.split(" convolution(", 1)
    if "=" not in head:
        return 0.0, ""
    res_m = _SHAPE_RE.search(head.split("=", 1)[1])
    shapes = _SHAPE_RE.findall(args)
    if res_m is None or len(shapes) < 2:
        return 0.0, ""
    res_dims = _dims_of(res_m.group(2))
    rhs_dims = _dims_of(shapes[1][1])
    shared = [d for d in rhs_dims if d in res_dims]
    out_ch = max(shared) if shared else 1
    n = 1
    for d in res_dims:
        n *= d
    k = 1
    for d in rhs_dims:
        k *= d
    sig = "convolution %s[%s]" % (res_m.group(1), res_m.group(2))
    return 2.0 * n * (k / max(out_ch, 1)), sig


def _hbm_bytes_of_line(line: str, op: str) -> float:
    """HBM traffic model for one instruction: operands + result, with the
    dynamic-update-slice aliasing special case (see module docstring)."""
    seg = _strip_metadata(line)
    shapes = _shapes_of(seg)
    if not shapes:
        return 0.0
    if op == "dynamic-update-slice" or "dynamic-update-slice" in \
            seg.split("=", 1)[0]:
        # result first, then operands; destination operand aliases the
        # result — drop both, count the update slice for read AND write
        result, operands = shapes[0], shapes[1:]
        dest_i = next((i for i, b in enumerate(operands) if b == result),
                      None)
        if dest_i is not None:
            rest = operands[:dest_i] + operands[dest_i + 1:]
            upd = max(rest) if rest else 0
            return float(sum(rest) + upd)
    return float(sum(shapes))


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------

def cost_ledger(compiled_text: str) -> Dict[str, object]:
    """Per-device compute/HBM totals from post-SPMD HLO text.

    Returns {
      "flops":              {op: FLOPs, loop-multiplied},
      "total_flops":        float,
      "flops_in_loops":     float,
      "hbm_bytes":          float  (modeled: operands + results),
      "hbm_bytes_in_loops": float,
      "count":              {op: flop-op executions, loop-multiplied},
      "cost_centers":       [{"sig","op","flops","count","in_loop"}] desc,
      "loops":              [{"body","trips","resolved","flops",
                              "hbm_bytes"}]  (one entry per while line,
                             totals include the trip multiplier and any
                             outer-loop multiplicity),
      "unresolved_loops":   [bodies whose trip count defaulted to 1],
      "approx_ops":         [conv lines whose FLOPs are approximate],
    }
    """
    comps = _split_computations(compiled_text)

    # fusion payload computations: reached via `calls=`; their HBM-level
    # traffic is the calling fusion line, not their internals
    fusion_payloads: set = set()
    for lines in comps.values():
        for ln in lines:
            m = _FUSION_CALL_RE.search(ln)
            if m:
                fusion_payloads.add(m.group(1))

    # per-computation local stats + call edges
    local_flops: Dict[str, List[Tuple[str, float, str, float]]] = {}
    local_hbm: Dict[str, float] = {}
    edges: Dict[str, List[Tuple[str, float, str, bool]]] = {}
    unresolved: List[str] = []
    approx_ops: List[str] = []

    for name, lines in comps.items():
        local_flops[name] = []
        local_hbm[name] = 0.0
        edges[name] = []
        count_hbm = name not in fusion_payloads
        for ln in lines:
            if "=" not in ln:
                continue
            if " dot(" in ln:
                fl, sig = _dot_flops(ln)
                if fl:
                    local_flops[name].append(
                        ("dot", fl, sig, _hbm_bytes_of_line(ln, "dot")))
            elif " convolution(" in ln:
                fl, sig = _conv_flops(ln)
                if fl:
                    local_flops[name].append(
                        ("convolution", fl, sig,
                         _hbm_bytes_of_line(ln, "convolution")))
                    approx_ops.append(ln.strip()[:160])
            om = _OP_RE.search(ln)
            op = om.group(1) if om else None
            wm = _WHILE_RE.search(ln)
            if wm:
                cond, body = wm.group(1), wm.group(2)
                trips, resolved = _trip_count(comps.get(cond, []))
                if not resolved:
                    unresolved.append(body)
                edges[name].append((body, float(trips), "while", resolved))
                edges[name].append((cond, float(trips), "while-cond",
                                    resolved))
                continue
            cm = _CALL_RE.search(ln)
            if cm and cm.group(1) in comps:
                edges[name].append((cm.group(1), 1.0, "call", True))
            fm = _FUSION_CALL_RE.search(ln)
            if fm and fm.group(1) in comps:
                edges[name].append((fm.group(1), 1.0, "fusion", True))
            bm = _BRANCH_RE.search(ln)
            if bm:
                for b in re.findall(r"%?([\w\.\-]+)", bm.group(1)):
                    if b in comps:
                        edges[name].append((b, 1.0, "branch", True))
            for tm in _TRUE_FALSE_RE.finditer(ln):
                if tm.group(1) in comps:
                    edges[name].append((tm.group(1), 1.0, "branch", True))
            if count_hbm and op is not None and op not in _HBM_SKIP_OPS \
                    and op not in _HBM_CONTAINER_OPS:
                local_hbm[name] += _hbm_bytes_of_line(ln, op)

    # entry = computation nobody calls (prefer one whose name says so)
    called = {b for es in edges.values() for b, _, _, _ in es}
    roots = [c for c in comps if c not in called]
    entry = next((c for c in roots if "main" in c or "entry" in c.lower()),
                 roots[0] if roots else next(iter(comps), None))

    flops_by_op: Dict[str, float] = {}
    count_by_op: Dict[str, float] = {}
    flops_in_loops = 0.0
    hbm_total = 0.0
    hbm_in_loops = 0.0
    centers: Dict[str, Dict[str, object]] = {}
    loops: List[Dict[str, object]] = []

    # memoized one-trip subtree totals (nested whiles multiplied inside)
    _sub_memo: Dict[str, Tuple[float, float]] = {}

    def _subtree(comp: str, seen: tuple) -> Tuple[float, float]:
        if comp in seen:
            return 0.0, 0.0
        if comp in _sub_memo:
            return _sub_memo[comp]
        fl = sum(f for _, f, _, _ in local_flops.get(comp, []))
        hb = local_hbm.get(comp, 0.0)
        for tgt, trips, kind, _res in edges.get(comp, []):
            m = trips if kind in ("while", "while-cond") else 1.0
            sfl, shb = _subtree(tgt, seen + (comp,))
            fl += m * sfl
            hb += m * shb
        _sub_memo[comp] = (fl, hb)
        return fl, hb

    def walk(comp: str, mult: float, seen: tuple,
             in_loop: bool = False) -> None:
        nonlocal flops_in_loops, hbm_total, hbm_in_loops
        if comp in seen:
            return
        for op, fl, sig, _hb in local_flops.get(comp, []):
            flops_by_op[op] = flops_by_op.get(op, 0.0) + mult * fl
            count_by_op[op] = count_by_op.get(op, 0.0) + mult
            if in_loop:
                flops_in_loops += mult * fl
            c = centers.setdefault(sig, {
                "sig": sig, "op": op, "flops": 0.0, "count": 0.0,
                "in_loop": in_loop,
            })
            c["flops"] = float(c["flops"]) + mult * fl
            c["count"] = float(c["count"]) + mult
            c["in_loop"] = bool(c["in_loop"]) or in_loop
        hbm_here = mult * local_hbm.get(comp, 0.0)
        hbm_total += hbm_here
        if in_loop:
            hbm_in_loops += hbm_here
        for tgt, trips, kind, resolved in edges.get(comp, []):
            if kind in ("while", "while-cond"):
                if kind == "while":
                    sfl, shb = _subtree(tgt, seen + (comp,))
                    loops.append({
                        "body": tgt, "trips": int(trips),
                        "resolved": bool(resolved),
                        "flops": mult * trips * sfl,
                        "hbm_bytes": mult * trips * shb,
                    })
                walk(tgt, mult * trips, seen + (comp,), True)
            else:
                walk(tgt, mult, seen + (comp,), in_loop)

    if entry is not None:
        walk(entry, 1.0, ())

    top = sorted(centers.values(), key=lambda c: -float(c["flops"]))
    return {
        "flops": flops_by_op,
        "total_flops": float(sum(flops_by_op.values())),
        "flops_in_loops": flops_in_loops,
        "hbm_bytes": hbm_total,
        "hbm_bytes_in_loops": hbm_in_loops,
        "count": count_by_op,
        "cost_centers": top,
        "loops": loops,
        "unresolved_loops": unresolved,
        "approx_ops": approx_ops,
    }


# ---------------------------------------------------------------------------
# Roofline verdict
# ---------------------------------------------------------------------------

def roofline_verdict(total_flops: float, hbm_bytes: float,
                     wire_bytes: float = 0.0,
                     device_kind: Optional[str] = None,
                     peak: Optional[float] = None,
                     hbm_bw: Optional[float] = None,
                     wire_bw: Optional[float] = None) -> Dict[str, object]:
    """Name the bound: compute-, hbm-, or wire-bound.

    Each axis gets a lower-bound time (work / peak rate); the slowest axis
    is the bound.  `arithmetic_intensity` (FLOPs/HBM byte) vs
    `ridge_intensity` (peak FLOPs / HBM BW) is the classic roofline view
    of the compute-vs-HBM race; the wire axis extends it with the ledger's
    measured collective bytes.
    """
    peak = peak if peak is not None else peak_flops_per_chip(device_kind)
    hbm_bw = hbm_bw if hbm_bw is not None else hbm_bw_per_chip(device_kind)
    wire_bw = wire_bw if wire_bw is not None \
        else wire_bw_per_chip(device_kind)
    t_compute = total_flops / peak if peak > 0 else 0.0
    t_hbm = hbm_bytes / hbm_bw if hbm_bw > 0 else 0.0
    t_wire = wire_bytes / wire_bw if wire_bw > 0 else 0.0
    times = {"compute": t_compute, "hbm": t_hbm, "wire": t_wire}
    bound = max(times, key=lambda k: times[k]) if any(times.values()) \
        else "compute"
    return {
        "bound": bound,
        "arithmetic_intensity": (total_flops / hbm_bytes)
        if hbm_bytes > 0 else 0.0,
        "ridge_intensity": peak / hbm_bw if hbm_bw > 0 else 0.0,
        "t_compute_s": t_compute,
        "t_hbm_s": t_hbm,
        "t_wire_s": t_wire,
        "peak_flops": peak,
        "hbm_bw": hbm_bw,
        "wire_bw": wire_bw,
    }


def cost_summary(led: Dict[str, object],
                 device_kind: Optional[str] = None,
                 wire_bytes: float = 0.0,
                 top_n: int = 3) -> Dict[str, object]:
    """Compact JSON-safe summary of a cost ledger + roofline verdict —
    what rides in telemetry run_meta and bench `extra.hlo_cost`."""
    verdict = roofline_verdict(
        float(led["total_flops"]), float(led["hbm_bytes"]),
        wire_bytes=wire_bytes, device_kind=device_kind)
    total = float(led["total_flops"]) or 1.0
    return {
        "total_flops": float(led["total_flops"]),
        "flops_in_loops": float(led["flops_in_loops"]),
        "hbm_bytes": float(led["hbm_bytes"]),
        "hbm_bytes_in_loops": float(led["hbm_bytes_in_loops"]),
        "wire_bytes": float(wire_bytes),
        "arithmetic_intensity": verdict["arithmetic_intensity"],
        "ridge_intensity": verdict["ridge_intensity"],
        "bound": verdict["bound"],
        "t_compute_s": verdict["t_compute_s"],
        "t_hbm_s": verdict["t_hbm_s"],
        "t_wire_s": verdict["t_wire_s"],
        "top_cost_centers": [
            {"sig": c["sig"], "flops": float(c["flops"]),
             "count": float(c["count"]), "in_loop": bool(c["in_loop"]),
             "share": float(c["flops"]) / total}
            for c in list(led["cost_centers"])[:top_n]
        ],
        "unresolved_loops": len(list(led["unresolved_loops"])),
        "approx_ops": len(list(led["approx_ops"])),
    }


def hlo_cost_report(engine, state, batch) -> Dict[str, object]:
    """Convenience: compile an engine's step and return its cost ledger +
    summary (post-hoc analysis only — does not touch the cached step)."""
    compiled = engine._step.lower(state, batch).compile()
    text = compiled.as_text()
    led = cost_ledger(text)
    wire = float(collective_ledger(text).get("total_wire_bytes", 0.0))
    dev = None
    try:
        import jax
        dev = jax.devices()[0].device_kind
    except Exception:
        pass
    return {"ledger": led,
            "summary": cost_summary(led, device_kind=dev, wire_bytes=wire)}
