# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Collective ledger from compiled HLO: what the partitioner ACTUALLY emits.

`comm_report` (profiling.py) predicts per-step collective bytes from ring-
algorithm formulas — the quantitative version of the reference's comment
ledger ("2g" ddp/module.py:17).  Round-2 verdict: those formulas had never
been validated against a compiled program.  This module closes the loop: it
parses the post-SPMD HLO of a compiled step, attributes every collective to
its computation, multiplies while-loop bodies by their static trip counts
(the layer scan runs its body n_layer times — a text grep alone undercounts
L-fold), and converts payloads to ring-model wire bytes.

Ring wire-cost model per op (n = participating devices, from the op's
replica_groups):
    all-reduce(p)        -> 2 p (n-1)/n     (reduce-scatter + all-gather)
    all-gather(out p)    ->   p (n-1)/n
    reduce-scatter(out p)->   p (n-1)       (input = n p moves (n-1)/n of itself)
    collective-permute(p)->   p
    all-to-all(p)        ->   p (n-1)/n

tests/test_profiling.py compares this ledger against comm_report per ZeRO
stage and pins their agreement.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# an HLO computation header:  %name (args...) -> result {   /  ENTRY %name ...
# args may contain nested parens (tuple-typed while params), so the only
# safe discriminators are: name directly followed by "(", "->" later, "{"
# at end, and NO "=" before the paren (instructions are "%n = shape op(").
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w\.\-]+)\s*\(.*\)\s*->.*\{\s*$")
# iota v2 "[groups,size]<=[...]", 1-D iota "[N]<=[N]", explicit list "{{0,1},..}"
_GROUPS_2D_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_GROUPS_1D_RE = re.compile(r"replica_groups=\[(\d+)\]<=")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_WHILE_RE = re.compile(
    r"while\(.*?\)?, condition=%?([\w\.\-]+), body=%?([\w\.\-]+)"
)
_CALL_RE = re.compile(r"to_apply=%?([\w\.\-]+)")
_BRANCH_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_TRUE_FALSE_RE = re.compile(
    r"(?:true_computation|false_computation)=%?([\w\.\-]+)"
)


def _shape_bytes(segment: str) -> int:
    total = 0
    for dt, dims in _SHAPE_RE.findall(segment):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _split_computations(text: str) -> Dict[str, List[str]]:
    comps: Dict[str, List[str]] = {}
    cur = None
    for line in text.splitlines():
        s = line.strip()
        m = _COMP_RE.match(s)
        is_header = (
            m and not s.startswith("ROOT")
            and "=" not in s.split("(", 1)[0]
        )
        if is_header:
            cur = m.group(1)
            comps[cur] = []
        elif cur is not None:
            comps[cur].append(line)
    return comps


def _trip_count(cond_lines: List[str]) -> Tuple[int, bool]:
    """(static trip count, resolved?) of a while loop, from its condition
    computation: the bound is the (usually unique) integer constant the
    induction variable compares against.  (1, False) when no constant is
    found — an undercount the caller flags in `unresolved_loops`."""
    consts = []
    for ln in cond_lines:
        for m in re.finditer(r"s32\[\]\s+constant\((\d+)\)", ln):
            consts.append(int(m.group(1)))
    return (max(consts), True) if consts else (1, False)


def _group_size(line: str):
    """Participant count of a collective from its replica_groups attr, or
    None when the format is unrecognized (caller flags it)."""
    m = _GROUPS_2D_RE.search(line)
    if m:
        return int(m.group(2))
    m = _GROUPS_1D_RE.search(line)
    if m:
        return int(m.group(1))
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len(m.group(1).split(","))
    return None


def collective_ledger(compiled_text: str) -> Dict[str, object]:
    """Per-device, per-step collective totals from post-SPMD HLO text.

    Returns {
      "payload_bytes": {op: logical result bytes, loop-multiplied},
      "wire_bytes":    {op: ring-model wire bytes},
      "count":         {op: op executions},
      "total_wire_bytes": float,
      "unresolved_loops": [loop bodies whose trip count defaulted to 1],
      "unresolved_groups": [lines whose replica_groups format was unknown
                            — their wire bytes default to 0],
    }
    """
    comps = _split_computations(compiled_text)

    # per-computation: local collectives and calls to other computations
    local: Dict[str, List[Tuple[str, int, int]]] = {}
    edges: Dict[str, List[Tuple[str, int, str]]] = {}
    unresolved: List[str] = []
    unresolved_groups: List[str] = []
    for name, lines in comps.items():
        local[name] = []
        edges[name] = []
        for ln in lines:
            for op in _COLLECTIVES:
                # plain op: "= <shapes> op(...)"; async pair: count the
                # -done (its result is the final payload), skip the -start
                token = f" {op}("
                done = f" {op}-done("
                if done in ln:
                    seg = ln.split(done)[0]
                elif token in ln and f"{op}-start" not in ln:
                    seg = ln.split(token)[0]
                else:
                    continue
                if "=" not in seg:
                    continue
                seg = seg.split("=", 1)[1]
                n = _group_size(ln)
                if n is None:
                    unresolved_groups.append(ln.strip()[:160])
                    n = 1
                local[name].append((op, _shape_bytes(seg), n))
                break
            wm = _WHILE_RE.search(ln)
            if wm:
                cond, body = wm.group(1), wm.group(2)
                trips, resolved = _trip_count(comps.get(cond, []))
                if not resolved:
                    unresolved.append(body)
                edges[name].append((body, trips, "while"))
                edges[name].append((cond, trips, "while-cond"))
                continue
            cm = _CALL_RE.search(ln)
            if cm and cm.group(1) in comps:
                edges[name].append((cm.group(1), 1, "call"))
            bm = _BRANCH_RE.search(ln)
            if bm:
                for b in re.findall(r"%?([\w\.\-]+)", bm.group(1)):
                    if b in comps:
                        edges[name].append((b, 1, "branch"))
            for tm in _TRUE_FALSE_RE.finditer(ln):
                if tm.group(1) in comps:
                    edges[name].append((tm.group(1), 1, "branch"))

    # entry = computation nobody calls (prefer one whose name says so)
    called = {b for es in edges.values() for b, _, _ in es}
    roots = [c for c in comps if c not in called]
    entry = next((c for c in roots if "main" in c or "entry" in c.lower()),
                 roots[0] if roots else next(iter(comps), None))

    payload: Dict[str, float] = {}
    wire: Dict[str, float] = {}
    count: Dict[str, float] = {}

    def walk(comp: str, mult: float, seen: tuple) -> None:
        if comp in seen:  # cycles don't exist in HLO; belt and braces
            return
        for op, b, n in local.get(comp, []):
            payload[op] = payload.get(op, 0.0) + mult * b
            count[op] = count.get(op, 0.0) + mult
            if op == "all-reduce":
                w = 2.0 * b * (n - 1) / n if n > 1 else 0.0
            elif op == "all-gather":
                w = b * (n - 1) / n if n > 1 else 0.0
            elif op == "reduce-scatter":
                w = float(b * (n - 1))
            elif op == "collective-permute":
                w = float(b)
            else:  # all-to-all
                w = b * (n - 1) / n if n > 1 else 0.0
            wire[op] = wire.get(op, 0.0) + mult * w
        for child, trips, _kind in edges.get(comp, []):
            walk(child, mult * trips, seen + (comp,))

    if entry is not None:
        walk(entry, 1.0, ())

    return {
        "payload_bytes": payload,
        "wire_bytes": wire,
        "count": count,
        "total_wire_bytes": sum(wire.values()),
        "unresolved_loops": unresolved,
        "unresolved_groups": unresolved_groups,
    }


def hlo_comm_report(engine, state, batch) -> Dict[str, object]:
    """Compile the engine's step for (state, batch) and return its
    collective ledger — the measured counterpart to
    `profiling.comm_report(engine)`'s formulas."""
    compiled = engine._step.lower(state, batch).compile()
    return collective_ledger(compiled.as_text())
