# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Collective ledger from compiled HLO: what the partitioner ACTUALLY emits.

`comm_report` (profiling.py) predicts per-step collective bytes from ring-
algorithm formulas — the quantitative version of the reference's comment
ledger ("2g" ddp/module.py:17).  Round-2 verdict: those formulas had never
been validated against a compiled program.  This module closes the loop: it
parses the post-SPMD HLO of a compiled step, attributes every collective to
its computation, multiplies while-loop bodies by their static trip counts
(the layer scan runs its body n_layer times — a text grep alone undercounts
L-fold), and converts payloads to ring-model wire bytes.

Ring wire-cost model per op (n = participating devices, from the op's
replica_groups):
    all-reduce(p)        -> 2 p (n-1)/n     (reduce-scatter + all-gather)
    all-gather(out p)    ->   p (n-1)/n
    reduce-scatter(out p)->   p (n-1)       (input = n p moves (n-1)/n of itself)
    collective-permute(p)->   p
    all-to-all(p)        ->   p (n-1)/n

tests/test_profiling.py compares this ledger against comm_report per ZeRO
stage and pins their agreement.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# an HLO computation header:  %name (args...) -> result {   /  ENTRY %name ...
# args may contain nested parens (tuple-typed while params), so the only
# safe discriminators are: name directly followed by "(", "->" later, "{"
# at end, and NO "=" before the paren (instructions are "%n = shape op(").
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w\.\-]+)\s*\(.*\)\s*->.*\{\s*$")
# iota v2 "[groups,size]<=[...]", 1-D iota "[N]<=[N]", explicit list "{{0,1},..}"
_GROUPS_2D_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_GROUPS_1D_RE = re.compile(r"replica_groups=\[(\d+)\]<=")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_WHILE_RE = re.compile(
    r"while\(.*?\)?, condition=%?([\w\.\-]+), body=%?([\w\.\-]+)"
)
_CALL_RE = re.compile(r"to_apply=%?([\w\.\-]+)")
# fusion call edge (TPU backend): collectives on TPU live INSIDE fusion
# computations — plain `calls=%fused_computation.N` wrappers (async
# collective starts among them) and kCustom collective-fusion kernels.
# The latter's `calls=%all-reduce-scatter.N` IS the TPU reduce-scatter:
# a ring kernel fusing all-reduce + scatter (backend_config emitter
# "AllReduceScatterFusion", StrategyRing), printed as an inner all-reduce
# + slice.  It is classified from the fusion line (payload = the fusion's
# OUTPUT, the shard) and NOT walked into — counting the inner all-reduce
# would price the ring at 2x its real wire bytes.
_FUSION_CALL_RE = re.compile(r"\bcalls=%?([\w\.\-]+)")
_RS_FUSION_PREFIX = "all-reduce-scatter"
# Async copies: the TPU scheduler prints ONE logical collective in SEVERAL
# fusion payload computations — an AsyncCollectiveStart-rooted wrapper, one
# "in flight during this kernel" copy per compute fusion it overlaps (up to
# 5 observed), and an AsyncCollectiveDone-rooted completion — all with the
# SAME channel_id and result shape, all called from the SAME computation.
# Within fusion payloads sharing a caller, the channel is therefore the
# identity of the transfer and is counted once.  The dedup is scoped to
# (channel, caller): a peeled clone whose fusion payload is called from a
# DIFFERENT computation is a second real transfer and keeps its count.
# Plain computations (entry, while bodies, shard_map bodies) are exempt
# entirely — there a repeated channel is always a legitimate clone.
_CHANNEL_RE = re.compile(r"\bchannel_id=(\d+)\b")
_BRANCH_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_TRUE_FALSE_RE = re.compile(
    r"(?:true_computation|false_computation)=%?([\w\.\-]+)"
)


def _shape_bytes_by_dtype(segment: str) -> Dict[str, int]:
    """Payload bytes of every typed shape in an HLO segment, keyed by
    dtype — the per-dtype split is what lets the ledger show a quantized
    collective's s8/f8 bytes next to its f32 scales (grad_comm,
    parallel/comm.py)."""
    out: Dict[str, int] = {}
    for dt, dims in _SHAPE_RE.findall(segment):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out[dt] = out.get(dt, 0) + n * _DTYPE_BYTES[dt]
    return out


def _shape_bytes(segment: str) -> int:
    return sum(_shape_bytes_by_dtype(segment).values())


def _split_computations(text: str) -> Dict[str, List[str]]:
    comps: Dict[str, List[str]] = {}
    cur = None
    for line in text.splitlines():
        s = line.strip()
        m = _COMP_RE.match(s)
        is_header = (
            m and not s.startswith("ROOT")
            and "=" not in s.split("(", 1)[0]
        )
        if is_header:
            cur = m.group(1)
            comps[cur] = []
        elif cur is not None:
            comps[cur].append(line)
    return comps


# the optional {...} after the shape is a TPU layout annotation
# (e.g. "s32[]{:T(128)} constant(4)")
_CONST_DEF_RE = re.compile(
    r"%?([\w\.\-]+)\s*=\s*s32\[\](?:\{[^}]*\})?\s+constant\((\d+)\)"
)
# operands may carry layout annotations containing parens ("s32[]{:T(128)}
# %iv"), so "up to the first ')'" truncates mid-annotation; compare ops
# always print ", direction=" after the close paren — anchor on that, with
# the paren-free form as fallback
_COMPARE_ARGS_RE = re.compile(
    r"\bcompare\((.*)\),\s*direction=|\bcompare\(([^)]*)\)"
)


def _trip_count(cond_lines: List[str]) -> Tuple[int, bool]:
    """(static trip count, resolved?) of a while loop, from its condition
    computation: the bound is the integer constant the induction variable
    compares against.  Resolution order (round-3 advice: "max constant
    anywhere" silently inflated the multiplier when the condition carried
    an unrelated larger constant, e.g. a clamp bound):
      1. compare ops (ROOT or not — the compare may feed a ROOT and/or)
         whose operands resolve to exactly ONE distinct constant;
      2. a condition with NO compare at all but agreeing constants;
      3. otherwise: (max-or-1, False) — the caller flags it in
         `unresolved_loops` so tests catch the ambiguity instead of
         trusting the total."""
    consts: Dict[str, int] = {}
    for ln in cond_lines:
        for m in _CONST_DEF_RE.finditer(ln):
            consts[m.group(1)] = int(m.group(2))

    def _const_operands(line: str):
        cm = _COMPARE_ARGS_RE.search(line)
        if not cm:
            return None  # not a compare
        args = cm.group(1) if cm.group(1) is not None else cm.group(2)
        # layout braces ("{1,0:T(8,128)}") contain commas; strip first
        args = re.sub(r"\{[^}]*\}", "", args)
        vals = set()
        for arg in args.split(","):
            arg = arg.strip()
            if arg and arg.split()[-1].lstrip("%") in consts:
                vals.add(consts[arg.split()[-1].lstrip("%")])
        return vals

    # rule 1: the ROOT compare is authoritative when present — a stray
    # compare elsewhere (a clamp, a flag test) must neither override a
    # resolved ROOT bound nor resolve a dynamic one
    for ln in cond_lines:
        s = ln.strip()
        if not s.startswith("ROOT"):
            continue
        vals = _const_operands(s)
        if vals is None:
            continue
        if len(vals) == 1:
            return next(iter(vals)), True
        return (max(consts.values()), False) if consts else (1, False)
    # rule 2: no ROOT compare (e.g. the compare feeds a ROOT `and`) —
    # resolve iff every compare in the condition agrees on ONE constant
    all_vals, compare_seen = set(), False
    for ln in cond_lines:
        vals = _const_operands(ln)
        if vals is None:
            continue
        compare_seen = True
        all_vals |= vals
    if len(all_vals) == 1:
        return next(iter(all_vals)), True
    if compare_seen:
        return (max(consts.values()), False) if consts else (1, False)
    # rule 3: no compares at all — agreeing constants are unambiguous
    distinct = set(consts.values())
    if len(distinct) == 1:
        return next(iter(distinct)), True
    return (max(distinct), False) if distinct else (1, False)


def _group_size(line: str):
    """Participant count of a collective from its replica_groups attr, or
    None when the format is unrecognized (caller flags it)."""
    m = _GROUPS_2D_RE.search(line)
    if m:
        return int(m.group(2))
    m = _GROUPS_1D_RE.search(line)
    if m:
        return int(m.group(1))
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len(m.group(1).split(","))
    return None


# full membership parse (the ICI-vs-DCN link split needs WHICH devices,
# not just how many): the explicit list form, and the iota v2 form
# "[shape]<=[dims]" with an optional T(perm) transpose — the general
# encoding XLA prints (arange(prod(dims)).reshape(dims).transpose(perm)
# .reshape(shape); rows are the groups)
_GROUPS_FULL_LIST_RE = re.compile(
    r"replica_groups=\{(\{[\d,]+\}(?:,\{[\d,]+\})*)\}"
)
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+(?:,\d+)*)\]<=\[(\d+(?:,\d+)*)\]"
    r"(?:T\((\d+(?:,\d+)*)\))?"
)


def _group_members(line: str):
    """Tuple of per-group participant-id tuples for a collective's
    replica_groups, or None when the encoding is unrecognized.  Ids are
    the program's logical device ids — positions in the mesh's flattened
    device order for the SPMD programs this repo compiles."""
    m = _GROUPS_FULL_LIST_RE.search(line)
    if m:
        return tuple(
            tuple(int(x) for x in grp.split(","))
            for grp in re.findall(r"\{([\d,]+)\}", m.group(1))
        )
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        import numpy as np

        shape = [int(x) for x in m.group(1).split(",")]
        dims = [int(x) for x in m.group(2).split(",")]
        n = 1
        for d in dims:
            n *= d
        ids = np.arange(n).reshape(dims)
        if m.group(3):
            ids = ids.transpose([int(x) for x in m.group(3).split(",")])
        if len(shape) == 1:
            shape = [1] + shape  # "[N]<=[N]": one group of everybody
        ids = ids.reshape(shape)
        return tuple(tuple(int(x) for x in row) for row in ids)
    return None


def collective_ledger(compiled_text: str) -> Dict[str, object]:
    """Per-device, per-step collective totals from post-SPMD HLO text.

    Returns {
      "payload_bytes": {op: logical result bytes, loop-multiplied},
      "wire_bytes":    {op: ring-model wire bytes},
      "wire_bytes_by_dtype": {dtype: ring-model wire bytes — how much of
                            the wire moves at which precision; the honest
                            view of quantized collectives (grad_comm s8/f8
                            values vs their f32 scales)},
      "count":         {op: op executions},
      "total_wire_bytes": float,
      "unresolved_loops": [loop bodies whose trip count defaulted to 1],
      "unresolved_groups": [lines whose replica_groups format was unknown
                            — their wire bytes default to 0],
    }
    """
    comps = _split_computations(compiled_text)

    def _comp_group_size(comp_name: str):
        """Participant count for a collective-fusion kernel, read off the
        first replica_groups inside its called computation."""
        for ln in comps.get(comp_name, []):
            if "replica_groups=" in ln:
                return _group_size(ln)
        return None

    def _comp_group_members(comp_name: str):
        for ln in comps.get(comp_name, []):
            if "replica_groups=" in ln:
                return _group_members(ln)
        return None

    # fusion payload computation -> the computation that calls it (see the
    # channel-dedup note above; payloads have a single fusion call site)
    fusion_caller: Dict[str, str] = {}
    for caller, lines in comps.items():
        for ln in lines:
            m = _FUSION_CALL_RE.search(ln)
            if m:
                fusion_caller.setdefault(m.group(1), caller)

    # per-computation: local collectives and calls to other computations
    local: Dict[str, List[Tuple[str, int, int, Dict[str, int]]]] = {}
    edges: Dict[str, List[Tuple[str, int, str]]] = {}
    unresolved: List[str] = []
    unresolved_groups: List[str] = []
    seen_channels: set = set()
    for name, lines in comps.items():
        local[name] = []
        edges[name] = []
        if name.startswith(_RS_FUSION_PREFIX):
            # payload of a TPU ring reduce-scatter kernel: its inner
            # all-reduce is an implementation detail of the fused kernel,
            # accounted by the CALLING fusion line's classification
            continue
        dedup_scope = fusion_caller.get(name)
        for ln in lines:
            fm = _FUSION_CALL_RE.search(ln)
            if fm and fm.group(1).startswith(_RS_FUSION_PREFIX) \
                    and " fusion(" in ln and "=" in ln.split(" fusion(")[0]:
                # TPU ring reduce-scatter kernel: payload = fusion output
                seg = ln.split(" fusion(")[0].split("=", 1)[1]
                n = _comp_group_size(fm.group(1))
                if n is None:
                    unresolved_groups.append(ln.strip()[:160])
                    n = 1
                by_dt = _shape_bytes_by_dtype(seg)
                local[name].append(
                    ("reduce-scatter", sum(by_dt.values()), n, by_dt,
                     _comp_group_members(fm.group(1)))
                )
                continue  # deliberately NOT walked into (see _FUSION_CALL_RE)
            for op in _COLLECTIVES:
                # plain op: "= <shapes> op(...)"; async pair: count the
                # -done (its result is the final payload), skip the -start
                token = f" {op}("
                done = f" {op}-done("
                # the -start exclusion matches the OP TOKEN only: TPU HLO
                # tags async-scheduled plain ops with frontend_attributes=
                # {async_collective_name="all-gather-start.N"}, and a
                # substring test would skip those real ops entirely
                if done in ln:
                    seg = ln.split(done)[0]
                elif token in ln and f" {op}-start(" not in ln:
                    seg = ln.split(token)[0]
                else:
                    continue
                if "=" not in seg:
                    continue
                if dedup_scope is not None:
                    chm = _CHANNEL_RE.search(ln)
                    if chm is not None:
                        key = (chm.group(1), dedup_scope)
                        if key in seen_channels:
                            break  # async copy of a counted transfer
                        seen_channels.add(key)
                seg = seg.split("=", 1)[1]
                n = _group_size(ln)
                if n is None:
                    unresolved_groups.append(ln.strip()[:160])
                    n = 1
                by_dt = _shape_bytes_by_dtype(seg)
                local[name].append((op, sum(by_dt.values()), n, by_dt,
                                    _group_members(ln)))
                break
            wm = _WHILE_RE.search(ln)
            if wm:
                cond, body = wm.group(1), wm.group(2)
                trips, resolved = _trip_count(comps.get(cond, []))
                if not resolved:
                    unresolved.append(body)
                edges[name].append((body, trips, "while"))
                edges[name].append((cond, trips, "while-cond"))
                continue
            cm = _CALL_RE.search(ln)
            if cm and cm.group(1) in comps:
                edges[name].append((cm.group(1), 1, "call"))
            if fm and fm.group(1) in comps:
                edges[name].append((fm.group(1), 1, "fusion"))
            bm = _BRANCH_RE.search(ln)
            if bm:
                for b in re.findall(r"%?([\w\.\-]+)", bm.group(1)):
                    if b in comps:
                        edges[name].append((b, 1, "branch"))
            for tm in _TRUE_FALSE_RE.finditer(ln):
                if tm.group(1) in comps:
                    edges[name].append((tm.group(1), 1, "branch"))

    # entry = computation nobody calls (prefer one whose name says so)
    called = {b for es in edges.values() for b, _, _ in es}
    roots = [c for c in comps if c not in called]
    entry = next((c for c in roots if "main" in c or "entry" in c.lower()),
                 roots[0] if roots else next(iter(comps), None))

    payload: Dict[str, float] = {}
    wire: Dict[str, float] = {}
    wire_by_dtype: Dict[str, float] = {}
    wire_by_op_dtype: Dict[str, Dict[str, float]] = {}
    count: Dict[str, float] = {}
    wire_in_loops: Dict[str, float] = {}
    count_in_loops: Dict[str, float] = {}
    # wire keyed by the collective's PARTICIPANT groups (None = encoding
    # unrecognized) — what wire_link_split classifies as ICI vs DCN
    wire_by_groups: Dict[object, float] = {}
    # the same keying, but per OP and restricted to LOOP-RESIDENT
    # collectives — what lets wire_link_split answer "which link do the
    # IN-SCAN gathers ride" (the hpZ acceptance: in-scan gather DCN
    # bytes ~zero while the top-level secondary-partition rebuild still
    # crosses DCN)
    wire_by_op_groups_in_loops: Dict[str, Dict[object, float]] = {}

    def walk(comp: str, mult: float, seen: tuple,
             in_loop: bool = False) -> None:
        if comp in seen:  # cycles don't exist in HLO; belt and braces
            return
        for op, b, n, by_dt, members in local.get(comp, []):
            payload[op] = payload.get(op, 0.0) + mult * b
            count[op] = count.get(op, 0.0) + mult
            if op == "all-reduce":
                w = 2.0 * b * (n - 1) / n if n > 1 else 0.0
            elif op == "all-gather":
                w = b * (n - 1) / n if n > 1 else 0.0
            elif op == "reduce-scatter":
                w = float(b * (n - 1))
            elif op == "collective-permute":
                w = float(b)
            else:  # all-to-all
                w = b * (n - 1) / n if n > 1 else 0.0
            wire[op] = wire.get(op, 0.0) + mult * w
            wire_by_groups[members] = (
                wire_by_groups.get(members, 0.0) + mult * w)
            if in_loop:
                # a collective INSIDE a while body runs before the loop
                # finishes — for the backward scan, before the backward
                # completes; that is the statically-verifiable half of
                # "the scheduler can hide this wire behind compute"
                # (overlap_report builds on this split)
                wire_in_loops[op] = wire_in_loops.get(op, 0.0) + mult * w
                count_in_loops[op] = count_in_loops.get(op, 0.0) + mult
                per_grp = wire_by_op_groups_in_loops.setdefault(op, {})
                per_grp[members] = per_grp.get(members, 0.0) + mult * w
            if b:
                # the ring formulas above are linear in the payload, so
                # the per-dtype wire split is just proportional; kept both
                # globally and per op (the per-op split is what lets a
                # trace span say WHICH precision its wire moved at —
                # telemetry/trace.collective_span_template)
                per_op = wire_by_op_dtype.setdefault(op, {})
                for dt, db in by_dt.items():
                    share = mult * w * db / b
                    wire_by_dtype[dt] = wire_by_dtype.get(dt, 0.0) + share
                    per_op[dt] = per_op.get(dt, 0.0) + share
        for child, trips, kind in edges.get(comp, []):
            walk(child, mult * trips, seen + (comp,),
                 in_loop or kind.startswith("while"))

    if entry is not None:
        walk(entry, 1.0, ())

    return {
        "payload_bytes": payload,
        "wire_bytes": wire,
        "wire_bytes_by_dtype": wire_by_dtype,
        "wire_bytes_by_op_dtype": wire_by_op_dtype,
        "count": count,
        "wire_bytes_in_loops": wire_in_loops,
        "count_in_loops": count_in_loops,
        "wire_bytes_by_groups": wire_by_groups,
        "wire_bytes_by_op_groups_in_loops": wire_by_op_groups_in_loops,
        "total_wire_bytes": sum(wire.values()),
        "unresolved_loops": unresolved,
        "unresolved_groups": unresolved_groups,
    }


_REDUCE_OPS = ("all-reduce", "reduce-scatter", "all-to-all")
_START_RE = re.compile(r"%?([\w\.\-]+)\s*=\s*(?:\([^=]*\)|\S+)\s+"
                       r"((?:all-reduce|all-gather|reduce-scatter|"
                       r"all-to-all|collective-permute)-start)\(")


def async_windows(compiled_text: str) -> List[Dict[str, object]]:
    """Start→done windows of async collectives in a compiled module.

    For every `<op>-start` instruction, finds the matching `<op>-done`
    in the same computation (by operand name) and measures the schedule
    distance: how many instructions sit between issue and completion,
    and how many of them are compute (fusions / dots / convolutions) the
    collective's wire time can hide behind — the same style as the
    offload copy-pair analysis (engine._offload_update docstring: "86/110
    copy pairs overlap >=1 fusion").  The post-scheduling TPU/GPU HLO is
    where these pairs appear; the XLA CPU backend emits synchronous
    collectives, so there this returns [] and the while-body placement
    split (`overlap_report`) is the portable signal."""
    out: List[Dict[str, object]] = []
    for comp, lines in _split_computations(compiled_text).items():
        starts: Dict[str, Tuple[str, int]] = {}
        for i, ln in enumerate(lines):
            m = _START_RE.search(ln)
            if m:
                starts[m.group(1)] = (m.group(2)[: -len("-start")], i)
                continue
            if "-done(" not in ln:
                continue
            for name, (op, i0) in list(starts.items()):
                # delimited operand match: a bare substring test would
                # pair %foo-start.1 with %foo-start.12's done line
                if f" {op}-done(" in ln and re.search(
                        rf"%{re.escape(name)}\b", ln):
                    window = lines[i0 + 1: i]
                    fusions = sum(
                        1 for w in window
                        if " fusion(" in w or " dot(" in w
                        or " convolution(" in w
                    )
                    out.append({
                        "computation": comp,
                        "op": op,
                        "name": name,
                        "distance": i - i0 - 1,
                        "compute_in_flight": fusions,
                    })
                    del starts[name]
                    break
    return out


# the GATHERING classification is all-gather ONLY, deliberately: every
# weight-gather schedule in this codebase (the GSPMD on-demand path, the
# prefetched constraint, and both hops of the hierarchical 2-hop gather)
# lowers to all-gather, while collective-permute in these programs always
# carries ring-attention K/V rotation or pipeline microbatch hops —
# activation traffic whose loop residency would both inflate
# gather_overlap_frac on engines with no weight gathers at all and MASK a
# real gather-hoist regression under zero3 x seq-parallel (the ppermute
# bytes would keep the frac high after every all-gather left the loop)
_GATHER_OPS = ("all-gather",)


def overlap_report(compiled_text: str,
                   led: Optional[Dict[str, object]] = None
                   ) -> Dict[str, object]:
    """Overlap-window analysis of a compiled step's collectives: how much
    of the gradient AND weight-gather wire is issued where the scheduler
    can hide it.

    Two complementary signals:

      * while-body placement (portable, works on the CPU ledger): a
        collective inside a while-loop body runs BEFORE the loop — for
        the backward layer scan, before the backward completes, so its
        wire time can overlap remaining backward compute.  The monolithic
        grad_comm schedule puts every gradient byte AFTER the backward
        (top-level); grad_buckets > 1 moves the layer buckets into the
        scan body.  `grad_comm_overlap_frac` = loop-resident wire /
        total wire over the reducing ops (all-reduce, reduce-scatter,
        all-to-all — the ops a gradient sync lowers to; all-gathers are
        excluded because ZeRO-3's per-layer WEIGHT gathers are loop-
        resident by construction and would mask the gradient signal).
      * async start→done windows (`async_windows`): on post-scheduling
        TPU/GPU HLO, how many compute ops are actually in flight between
        a collective's issue and its completion.

    The GATHERING side (ISSUE 4, `gather_prefetch`) is classified
    symmetrically: `gather_overlap_frac` = loop-resident wire / total
    wire over the all-gathers (the op every weight-gather schedule here
    lowers to, 2-hop hierarchical gathers included; collective-permute
    is deliberately excluded — see _GATHER_OPS), plus the gather-only
    async window counts.  Under ZeRO-3 the per-layer gathers
    are loop-resident whether on-demand or prefetched — the frac catches
    hoist regressions (a gather pulled out of the scan = full-model HBM);
    WHERE in the body the gather issues (ahead of the consuming layer's
    compute or serialized in front of it) is the async-window half, a
    post-scheduling TPU/GPU signal.

    `led` reuses an already-built `collective_ledger` of the same text
    (the regex computation-graph walk over a multi-MB module is the
    expensive part; telemetry's capture_compiled passes its own).
    """
    if led is None:
        led = collective_ledger(compiled_text)
    loop_w = sum(
        led["wire_bytes_in_loops"].get(op, 0.0) for op in _REDUCE_OPS
    )
    total_w = sum(led["wire_bytes"].get(op, 0.0) for op in _REDUCE_OPS)
    g_loop = sum(
        led["wire_bytes_in_loops"].get(op, 0.0) for op in _GATHER_OPS
    )
    g_total = sum(led["wire_bytes"].get(op, 0.0) for op in _GATHER_OPS)
    windows = async_windows(compiled_text)
    g_windows = [w for w in windows if w["op"] in _GATHER_OPS]
    return {
        "reduce_wire_bytes_in_loops": float(loop_w),
        "reduce_wire_bytes_total": float(total_w),
        "grad_comm_overlap_frac": float(loop_w / total_w) if total_w
        else 0.0,
        "gather_wire_bytes_in_loops": float(g_loop),
        "gather_wire_bytes_total": float(g_total),
        "gather_overlap_frac": float(g_loop / g_total) if g_total
        else 0.0,
        "loop_collective_counts": {
            k: float(v) for k, v in led["count_in_loops"].items()
        },
        "async_windows": len(windows),
        "async_windows_overlapped": sum(
            1 for w in windows if w["compute_in_flight"] > 0
        ),
        "async_window_max_distance": max(
            (w["distance"] for w in windows), default=0
        ),
        "gather_async_windows": len(g_windows),
        "gather_async_windows_overlapped": sum(
            1 for w in g_windows if w["compute_in_flight"] > 0
        ),
    }


def wire_link_split(led: Dict[str, object],
                    granule_of: Dict[int, int]) -> Dict[str, float]:
    """ICI-vs-DCN wire split of a compiled step's collectives, MEASURED
    from their replica_groups — the per-axis accounting the ZeRO++
    agenda needs (cross-slice bytes as a pinned number, not a model;
    arXiv:2306.10209 motivates why the split matters: DCN is an order
    of magnitude slower than ICI, so a byte's cost depends on which
    link carries it).

    `granule_of` maps a logical device id (position in the mesh's
    flattened device order — `parallel/mesh.granule_map`) to its DCN
    granule (slice / process).  A collective whose participant group
    stays inside ONE granule rides ICI; a group spanning granules must
    cross DCN, and ALL of its wire is billed to DCN (the conservative
    reading: the ring topology inside a crossing group is XLA's choice,
    not visible in the HLO).  Collectives whose replica_groups encoding
    was unrecognized are reported, not guessed."""
    ici = dcn = unresolved = 0.0
    dcn_groups = []
    for members, w in led.get("wire_bytes_by_groups", {}).items():
        if members is None:
            unresolved += w
            continue
        crossing = any(
            len({granule_of.get(d) for d in grp}) > 1
            for grp in members
        )
        if crossing:
            dcn += w
            dcn_groups.append(members)
        else:
            ici += w
    total = ici + dcn
    return {
        "ici_wire_bytes": float(ici),
        "dcn_wire_bytes": float(dcn),
        "dcn_frac": float(dcn / total) if total else 0.0,
        "unresolved_wire_bytes": float(unresolved),
        "dcn_crossing_collectives": len(dcn_groups),
    }


def gather_link_split_in_loops(led: Dict[str, object],
                               granule_of: Dict[int, int]
                               ) -> Dict[str, float]:
    """ICI-vs-DCN split of the LOOP-RESIDENT all-gather wire only — the
    in-scan weight gathers.  This is the hpZ acceptance number (ZeRO++
    arXiv:2306.10209): with the secondary weight partition, every
    forward/backward gather inside the block scan rides the intra-slice
    group (ICI) and `dcn_wire_bytes` here drops to ~zero, while the ONE
    top-level inter-slice rebuild of the secondary partition still
    (correctly) crosses DCN and stays visible in the full
    `wire_link_split`."""
    per_op = led.get("wire_bytes_by_op_groups_in_loops", {})
    merged: Dict[object, float] = {}
    for op in _GATHER_OPS:
        for members, w in per_op.get(op, {}).items():
            merged[members] = merged.get(members, 0.0) + w
    return wire_link_split({"wire_bytes_by_groups": merged}, granule_of)


def group_wire_outside_loops(led: Dict[str, object],
                             groups) -> float:
    """Wire bytes of the OUTSIDE-loop collectives whose replica groups
    match `groups` exactly (an iterable of participant-id iterables,
    order-insensitive).  This isolates ONE named hop: e.g. the hpZ
    secondary rebuild's inter-granule all_gather rides exactly the
    `inter` groups the scheduler built, and nothing else outside the
    scan shares them — so (total wire on those groups) minus (the
    in-loop wire on them) IS the rebuild's bytes, undiluted by the
    tail gathers / grad syncs that share the DCN link but run on
    different groups.  The qwZ acceptance pin (fp8 rebuild ~4x lower
    than fp32) reads this number."""
    want = tuple(sorted(tuple(sorted(int(d) for d in g))
                        for g in groups))
    total = 0.0
    for members, w in led.get("wire_bytes_by_groups", {}).items():
        if members is None:
            continue
        if tuple(sorted(tuple(sorted(g)) for g in members)) == want:
            total += w
    in_loops = 0.0
    per_op = led.get("wire_bytes_by_op_groups_in_loops", {})
    for op, per in per_op.items():
        for members, w in per.items():
            if members is None:
                continue
            if tuple(sorted(tuple(sorted(g)) for g in members)) == want:
                in_loops += w
    return float(max(total - in_loops, 0.0))


def ledger_summary(led: Dict[str, object],
                   granule_of: Optional[Dict[int, int]] = None
                   ) -> Dict[str, object]:
    """JSON-safe compact form of a `collective_ledger` result for the
    telemetry run_meta record: per-op wire/payload bytes and counts plus
    unresolved-attribution COUNTS (the full flagged lines stay with the
    ledger; a metrics file only needs to know whether attribution was
    complete).  With `granule_of` (a hybrid ICI×DCN mesh —
    `parallel/mesh.granule_map`), adds the measured per-link wire split
    under `wire_bytes_by_link`."""
    out = {
        "wire_bytes": {k: float(v) for k, v in led["wire_bytes"].items()},
        "payload_bytes": {
            k: float(v) for k, v in led["payload_bytes"].items()
        },
        "wire_bytes_by_dtype": {
            k: float(v)
            for k, v in led.get("wire_bytes_by_dtype", {}).items()
        },
        "wire_bytes_by_op_dtype": {
            op: {k: float(v) for k, v in per.items()}
            for op, per in led.get("wire_bytes_by_op_dtype", {}).items()
        },
        "wire_bytes_in_loops": {
            k: float(v)
            for k, v in led.get("wire_bytes_in_loops", {}).items()
        },
        "count_in_loops": {
            k: float(v)
            for k, v in led.get("count_in_loops", {}).items()
        },
        "count": {k: float(v) for k, v in led["count"].items()},
        "total_wire_bytes": float(led["total_wire_bytes"]),
        "unresolved_loops": len(led["unresolved_loops"]),
        "unresolved_groups": len(led["unresolved_groups"]),
    }
    if granule_of is not None:
        out["wire_bytes_by_link"] = wire_link_split(led, granule_of)
    return out


def hlo_comm_report(engine, state, batch) -> Dict[str, object]:
    """Compile the engine's step for (state, batch) and return its
    collective ledger — the measured counterpart to
    `profiling.comm_report(engine)`'s formulas."""
    compiled = engine._step.lower(state, batch).compile()
    return collective_ledger(compiled.as_text())
