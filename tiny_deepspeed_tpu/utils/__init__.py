# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Utilities: checkpointing, profiling, metrics."""

from .checkpoint import save_checkpoint, load_checkpoint, latest_step
from .profiling import (
    trace, StepTimer, comm_report, MetricsLogger, device_sync,
)

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "latest_step",
    "trace",
    "StepTimer",
    "comm_report",
    "MetricsLogger",
    "device_sync",
]
