"""Utilities: checkpointing, profiling."""

from .checkpoint import save_checkpoint, load_checkpoint, latest_step

__all__ = ["save_checkpoint", "load_checkpoint", "latest_step"]
