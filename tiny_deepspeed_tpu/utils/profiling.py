# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Tracing, timing, and communication-cost reporting.

The reference's entire observability surface is the autotuner's wall-clock
timer (reference runtime_tuner.py:34-39), rank-0 loss prints, and
comm-complexity *comments* ("2g" ddp/module.py:17, "g" zero1/optim.py:20).
Here those become real subsystems:

  * `trace(logdir)`     — context manager around jax.profiler (XPlane/
    TensorBoard format) for device timelines.
  * `StepTimer`         — per-step wall timing with a device sync that works
    on the axon tunnel (block_until_ready is unreliable there; a 1-element
    device->host transfer is the barrier).
  * `comm_report(engine)` — the reference's "g"/"2g" comments as computed
    per-step collective byte counts for the engine's actual stage/mesh.
  * `MetricsLogger`     — rank-0 structured JSONL metrics (loss, step time,
    tokens/s), replacing bare prints (reference ddp/train.py:34-35).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler trace (view in TensorBoard / xprof)."""
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def device_sync(x) -> float:
    """Barrier: materialize one element on the host; returns it as float."""
    leaf = jax.tree.leaves(x)[0]
    return float(np.asarray(leaf.ravel()[0:1])[0])


def _quantile(xs, q: float) -> float:
    """Linear-interpolated quantile of a list (no numpy dependency on the
    hot host path)."""
    if not xs:
        return 0.0
    ys = sorted(xs)
    if len(ys) == 1:
        return ys[0]
    pos = q * (len(ys) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(ys) - 1)
    return ys[lo] + (ys[hi] - ys[lo]) * (pos - lo)


class StepTimer:
    """Rolling per-step timing: `with timer.step(): ... engine.step(...)`.

    Upgraded for the telemetry subsystem (tiny_deepspeed_tpu/telemetry/):

      * `mark(name)` inside the step splits the wall time into named
        segments (`data_s` loader wait, `h2d_s` host->device staging, ...);
        the tail after the last mark — the dispatched device work plus the
        sync — lands in `compute_s`.  Per-step dicts in `self.segments`.
      * `watch(target)` registers a compile-count source (an engine, a
        jitted fn, or a zero-arg int callable); each step records how many
        NEW lowerings the watched jit cache grew by (`self.compiled_steps`),
        so first-compile and shape-driven recompiles are attributed to the
        step that paid for them.
      * `p50_s` / `p95_s` / `p99_s` / `max_s` tail properties next to
        `mean_s`.
      * a step whose body RAISES clears the observed output instead of
        leaking it into the next step's sync, and records no sample.
      * `fetch_full=True` makes the closing sync materialize the whole
        observed leaf (<= 1024 elements) on the host in `last_host` —
        one transfer that both closes the clock and delivers the packed
        telemetry health vector; `last_value` is always element 0.
    """

    def __init__(self, sync_every: int = 1, fetch_full: bool = False):
        self.sync_every = sync_every
        self.fetch_full = fetch_full
        self.times = []
        self.segments = []       # per step: {"data_s": .., "compute_s": ..}
        self.compiled_steps = []  # per step: lowerings paid by this step
        self.last_value = None   # float(element 0) of the observed output
        self.last_host = None    # host copy of the observed leaf (fetch_full)
        self._last_out = None
        self._watched = []
        self._segs = {}
        self._seg_t0 = 0.0

    # -- compile watching ---------------------------------------------------

    def watch(self, target) -> None:
        """Count lowerings of `target`: a ZeroEngine (tracks its `_step`
        across retune rebuilds), a jitted function, or a callable -> int."""
        if hasattr(target, "_cache_size"):
            fn = target._cache_size
        elif hasattr(target, "step"):
            # engine-like: read its CURRENT jitted step each time, so
            # attach-at-construction (before the first _build_step) and
            # retune() rebuilds both stay counted
            def fn(eng=target):
                step = getattr(eng, "_step", None)
                return step._cache_size() if step is not None else 0
        elif callable(target):
            fn = target
        else:
            raise TypeError(f"cannot watch {type(target).__name__}")
        self._watched.append(fn)

    def _watched_lowerings(self) -> int:
        total = 0
        for fn in self._watched:
            try:
                total += int(fn())
            except Exception:
                pass
        return total

    # -- the step context ---------------------------------------------------

    @contextlib.contextmanager
    def step(self):
        t0 = time.perf_counter()
        self._seg_t0 = t0
        self._segs = {}
        c0 = self._watched_lowerings()
        try:
            yield self
        except BaseException:
            # a failed step must not leak its stale output into the next
            # step's sync barrier
            self._last_out = None
            raise
        if self._last_out is not None:
            leaf = jax.tree.leaves(self._last_out)[0]
            if self.fetch_full and leaf.size <= 1024:
                host = np.asarray(leaf).ravel()
            else:
                host = np.asarray(leaf.ravel()[0:1])
            self.last_host = host
            self.last_value = float(host[0])
            self._last_out = None
        now = time.perf_counter()
        if self._segs:
            self._segs["compute_s"] = now - self._seg_t0
            self.segments.append(self._segs)
        self.times.append(now - t0)
        self.compiled_steps.append(self._watched_lowerings() - c0)
        self._segs = {}

    def mark(self, name: str) -> None:
        """Close the current wall segment as `<name>_s`; the remainder of
        the step (device dispatch + sync) becomes `compute_s`."""
        now = time.perf_counter()
        self._segs[f"{name}_s"] = now - self._seg_t0
        self._seg_t0 = now

    def observe(self, out):
        """Register a step output to sync on before stopping the clock."""
        self._last_out = out
        return out

    # -- summaries ----------------------------------------------------------

    def _sample(self):
        # drop the first step (compile) once there is more than one sample
        return self.times[1:] if len(self.times) > 1 else self.times

    @property
    def mean_s(self) -> float:
        xs = self._sample()
        return sum(xs) / max(1, len(xs))

    @property
    def p50_s(self) -> float:
        return _quantile(self._sample(), 0.50)

    @property
    def p95_s(self) -> float:
        return _quantile(self._sample(), 0.95)

    @property
    def p99_s(self) -> float:
        return _quantile(self._sample(), 0.99)

    @property
    def max_s(self) -> float:
        """Worst warm step — with p99, the tail the straggler/anomaly
        analysis cares about (the p50/p95 pair hides a single stall)."""
        xs = self._sample()
        return max(xs) if xs else 0.0

    @property
    def compile_count(self) -> int:
        """Total lowerings of the watched jits across recorded steps —
        1 is the first compile; anything above is a recompile."""
        return sum(self.compiled_steps)


def _bytes(tree) -> int:
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(tree)
    )


def comm_report(engine) -> Dict[str, float]:
    """Estimated per-step collective traffic for the engine's stage/mesh.

    Uses ring-algorithm costs over the data axis (n devices, payload g bytes
    of gradients/params): all-reduce 2g(n-1)/n, reduce-scatter g(n-1)/n,
    all-gather g(n-1)/n — the quantitative version of the reference's comment
    ledger (ddp/module.py:17 "2g"; zero1/module.py:17, optim.py:13,20 "g").

    Round 3: validated against the compiled step's ledger
    (utils/hlo_comm.py, tests/test_profiling.py, PROFILE.md).  Findings
    baked in:
      * DDP / ZeRO-1 rows match the compiled HLO to <0.01%.
      * ZeRO-3 per-layer gathers move the BLOCK params twice (fwd + remat
        bwd) and the non-block params (wte/wpe/ln_f/lm_head) once, all in
        COMPUTE dtype — the previous hard-coded 0.5 "bf16 factor" was
        wrong for f32-compute models.
      * grad_reduce_scatter is the ring-model INTENT of the sharded-grad
        constraint; XLA's CPU partitioner instead realizes it as a full
        all-reduce + slice (2x the wire bytes).  The report exposes this
        as `grad_reduce_scatter_is_upper_bounded_by_allreduce`.
    """
    n = engine.n_shard
    shapes = engine.model.param_shapes()
    g = _bytes(shapes)  # grads are param-sized
    ring = (n - 1) / n if n > 1 else 0.0
    stage = engine.stage

    cfg = getattr(engine.model, "config", None)
    cd_itemsize = (
        jnp.dtype(cfg.compute_dtype).itemsize if cfg is not None else 4
    )
    block_cd = nonblock_cd = block_deq = 0
    if stage == 3:
        block_deq = sum(
            int(np.prod(s.shape)) * cd_itemsize
            for name, s in shapes.items() if name.startswith("h.")
        )
        try:
            # what the per-layer gathers ACTUALLY move: the stacked compute
            # tree's own dtypes (compute dtype normally; f8 + f32 scales
            # under gather_quant="fp8" — pricing h.* at cd_itemsize would
            # overstate the quantized gathers ~2-4x)
            stacked = jax.eval_shape(
                engine.model.stacked_compute_params, shapes
            )
            block_cd = _bytes(stacked)
        except Exception:
            block_cd = sum(
                int(np.prod(s.shape)) * cd_itemsize
                for name, s in shapes.items() if name.startswith("h.")
            )
        nonblock_cd = sum(
            int(np.prod(s.shape)) * cd_itemsize
            for name, s in shapes.items() if not name.startswith("h.")
        )

    # Round 4, measured on the v5e:4x2 compile-only topology (PROFILE.md
    # "TPU topology HLO"): the replicated-grad all-reduce rides in COMPUTE
    # dtype — XLA commutes the reduction with the grad's f32 cast — so
    # DDP/ZeRO-1 reduction payloads are cd-priced (halves the bf16 bill vs
    # the old f32-grad pricing; exact on f32-compute models).  The sharded
    # -grad reduce-scatter of ZeRO-2/3 stays in PARAM dtype: the constraint
    # lands on the post-cast f32 grads and the partitioner keeps it.
    g_cd = sum(
        int(np.prod(s.shape)) * cd_itemsize for s in shapes.values()
    )
    # Microbatch accumulation: stage <= 1 keeps grads replicated and truly
    # pays ONE all-reduce after the local sum; stage >= 2 constrains the
    # f32 accumulator SHARDED, so every microbatch reduce-scatters into
    # the shard — accum_steps x the wire bytes (TPU topology measurement,
    # PROFILE.md zero2-accum4 row: 4x the single-step reduce-scatter).
    n_sync = int(getattr(engine, "accum_steps", 1)) if stage >= 2 else 1
    # grad_comm != fp32 (parallel/comm.py): the explicit quantized
    # schedule REPLACES the partitioner's gradient collective — one
    # error-fed int8/fp8 all-to-all reduce-scatter + quantized all-gather
    # per step (accumulation syncs once, so no n_sync multiplier), priced
    # by the same ring conventions via comm.modeled_wire_bytes
    quant = bool(getattr(engine, "_grad_comm_active", False))
    tmode = str(getattr(engine, "grad_comm_tail", "fp32"))
    # composed ZeRO-3: the non-block tail is RELEASED SEPARATELY from
    # the codec'd block syncs — through the differentiable gather's
    # fp32 transpose, or (grad_comm_tail != fp32) its own quantized
    # sync.  Price it under zero3_tail_release_bytes, not inside the
    # grad codec model (round-5 ledger finding: the old qt term billed
    # the tail to the block codec and missed the fp32 transpose).
    z3_split_tail = quant and stage == 3
    tail_elems_total = sum(
        int(np.prod(s.shape)) for nm, s in shapes.items()
        if not nm.startswith("h.")
    )
    quant_model = None
    if quant:
        from ..parallel.comm import modeled_wire_bytes
        n_elems = sum(int(np.prod(s.shape)) for s in shapes.values())
        if z3_split_tail:
            n_elems -= tail_elems_total
        quant_model = modeled_wire_bytes(
            n_elems, n, engine.grad_comm,
            block=engine.grad_comm_block,
            inner=engine.grad_comm_groups,
        )
        lay = getattr(engine, "_bucket_layout", None)
        if lay is not None:
            # bucketed release (grad_buckets > 1): K layer syncs + one
            # tail sync, each padded per-bucket — slightly more wire than
            # the monolithic schedule (the per-bucket padding/scale
            # overhead the acceptance tolerance prices).  The fp32
            # all-reduce baseline stays the monolithic model's — ONE
            # accounting site for the ring convention.
            qb = modeled_wire_bytes(
                lay["bucket_elems"], n, engine.grad_comm,
                block=engine.grad_comm_block,
                inner=engine.grad_comm_groups,
            )
            qt = modeled_wire_bytes(
                lay["tail_elems"], n, engine.grad_comm,
                block=engine.grad_comm_block,
                inner=engine.grad_comm_groups,
            ) if (lay["tail_elems"] and not z3_split_tail) else {
                "elems_padded": 0, "quant_wire_bytes": 0.0}
            k = lay["n_buckets"]
            quant_model = dict(
                quant_model,
                grad_buckets=k,
                elems_padded=k * qb["elems_padded"] + qt["elems_padded"],
                quant_wire_bytes=k * qb["quant_wire_bytes"]
                + qt["quant_wire_bytes"],
            )
    # the composed ZeRO-3 tail release itself (once per step, outside
    # the scans): fp32 = the transpose reduce-scatter on sharded leaves
    # (param dtype) + the explicit psum on replicated ones; quantized =
    # comm.modeled_wire_bytes on the tail's elems under the tail codec
    zero3_tail_release = 0.0
    if z3_split_tail:
        if tmode == "fp32":
            spec_rest = getattr(engine, "_param_spec_rest", {}) or {}
            for nm, s in shapes.items():
                if nm.startswith("h."):
                    continue
                b = int(np.prod(s.shape)) * int(jnp.dtype(s.dtype).itemsize)
                spec = spec_rest.get(nm)
                sharded = spec is not None and any(
                    d is not None for d in tuple(spec)
                )
                # reduce-scatter g*ring vs all-reduce 2g*ring
                zero3_tail_release += (1 if sharded else 2) * b * ring
        else:
            from ..parallel.comm import modeled_wire_bytes
            zero3_tail_release = modeled_wire_bytes(
                tail_elems_total, n, tmode,
                block=engine.grad_comm_block,
            )["quant_wire_bytes"]
    # hpZ secondary rebuild (qwZ): the once-per-step inter-granule
    # all-gather of this rank's resting shard — compute-dtype bytes at
    # fp32, fp8 blocks + scales under hpz_comm='fp8'
    hpz_rebuild = 0.0
    geom = getattr(getattr(engine, "_schedule", None), "hpz_geom", None)
    if getattr(engine, "hpz", False) and geom is not None and stage == 3:
        from ..parallel.comm import modeled_hpz_rebuild_bytes
        n_gran = geom[3]
        block_elems = sum(
            int(np.prod(s.shape)) for nm, s in shapes.items()
            if nm.startswith("h.")
        )
        hpz_rebuild = modeled_hpz_rebuild_bytes(
            block_cd // n, block_elems // n, n_gran,
            str(getattr(engine, "hpz_comm", "fp32")),
        )
    # gather_prefetch (parallel/schedule.GatherPrefetchScan): the explicit
    # prefetched schedule issues K-1 extra clamped end-of-scan gathers
    # per pass (fwd + remat bwd each run L+K-1 layer gathers), and
    # gather_groups reroutes each layer's gather through the 2-hop
    # shard_map (resting precision intra-group, compute dtype inter) —
    # priced by comm.modeled_gather_wire_bytes, the same accounting site
    # telemetry reads
    gp = int(getattr(engine, "gather_prefetch", 0) or 0)
    gg = getattr(engine, "gather_groups", None)
    gp_active = bool(getattr(engine, "_gather_prefetch_active", False))
    z3_gather = (2 * block_cd + nonblock_cd) * ring if stage == 3 else 0.0
    if stage == 3 and gp_active:
        from ..parallel.comm import modeled_gather_wire_bytes
        nl = int(getattr(cfg, "n_layer", 0) or 0)
        passes = 2.0 * (nl + gp - 1) / nl if nl else 2.0
        per_pass = modeled_gather_wire_bytes(
            block_cd, block_deq, n, inner=gg
        )
        z3_gather = passes * per_pass + nonblock_cd * ring

    report = {
        "devices": n,
        "param_bytes": g,
        "grad_comm": getattr(engine, "grad_comm", "fp32"),
        "grad_buckets": int(getattr(engine, "grad_buckets", 1)),
        "gather_prefetch": gp,
        "gather_groups": int(gg) if gg else 0,
        # full schedule model kept alongside the headline number so
        # downstream gauges (telemetry capture_compiled) read ONE
        # accounting site instead of re-deriving it
        "grad_comm_model": quant_model,
        "grad_quant_sync_bytes":
        quant_model["quant_wire_bytes"] if quant_model else 0.0,
        "grad_allreduce_bytes": 2 * g_cd * ring
        if stage <= 1 and n > 1 and not quant else 0.0,
        "grad_reduce_scatter_bytes": n_sync * g * ring
        if stage >= 2 and not quant else 0.0,
        "grad_reduce_scatter_is_upper_bounded_by_allreduce":
        stage >= 2 and not quant,
        "param_all_gather_bytes": g * ring if stage in (1, 2) else 0.0,
        # ZeRO-3: block params gathered per layer in fwd AND in the remat
        # bwd; non-block params once — all at compute precision (plus the
        # prefetch overshoot / 2-hop reroute when gather_prefetch is on)
        "zero3_layer_gather_bytes": z3_gather,
        # composed ZeRO-3 tail release + hpZ secondary rebuild — the
        # wire-agenda hops, modeled at the same ring conventions the
        # ledger measures (zero3_tail_wire_bytes /
        # hpz_rebuild_dcn_bytes gauges)
        "zero3_tail_release_bytes": zero3_tail_release,
        "hpz_rebuild_bytes": hpz_rebuild,
    }
    report["total_bytes_per_step"] = sum(
        v for k, v in report.items()
        if k.endswith("_bytes") and k != "param_bytes"
    )
    return report


class MetricsLogger:
    """Rank-0 structured metrics: JSONL file and/or stdout.

    Usable as a context manager so the file handle cannot leak when the
    training loop raises; `close()` keeps working for manual lifetimes.
    The record schema (step records + `kind`-tagged meta records from
    `log_meta`) is defined in `tiny_deepspeed_tpu/telemetry/schema.py` and
    validated by `scripts/report_run.py --check`.
    """

    def __init__(self, path: Optional[str] = None, stdout: bool = True):
        self.is_rank0 = jax.process_index() == 0
        self.stdout = stdout
        self._fh = None
        if path and self.is_rank0:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a")

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def log(self, step: int, **metrics) -> None:
        if not self.is_rank0:
            return
        rec = {"step": step, "ts": time.time(), **metrics}
        if self._fh:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        if self.stdout:
            shown = " ".join(
                f"{k} {v:.4f}" if isinstance(v, float) else f"{k} {v}"
                for k, v in metrics.items()
            )
            print(f"step {step:5d} {shown}")

    def log_meta(self, kind: str = "run_meta", **fields) -> None:
        """One `kind`-tagged non-step record (run metadata, telemetry
        summaries) — JSONL only, never echoed to stdout."""
        if not self.is_rank0 or not self._fh:
            return
        rec = {"kind": kind, "ts": time.time(), **fields}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None
