# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Mixture-of-Experts GPT: top-k routed experts with expert parallelism.

ABSENT from the reference (SURVEY §2.20: no expert parallelism of any kind —
its parallelism surface is DP + ZeRO-1/2/3 only); first-class here because
the build targets the full tp/pp/dp/sp/ep sharding surface.

TPU-first design:
  * Every block's MLP is replaced by a router + E experts; blocks stay
    UNIFORM so the stacked-layer `lax.scan` (O(1) compile depth) is kept —
    expert tensors just carry an extra (E,) axis after the layer axis.
  * Routing is GShard-style top-k with a STATIC capacity: dispatch/combine
    are dense one-hot einsums over (tokens, experts, capacity) — no dynamic
    shapes, no sorting scatter, so XLA tiles everything onto the MXU.
  * Expert parallelism = sharding the (E,) axis over an "expert" mesh axis
    (`ep_rules`); the dispatch einsum's contraction over tokens makes GSPMD
    emit the all-to-all.  Composes with TP (experts' ff dim over "model")
    and every ZeRO stage (data axis on a remaining dim).
  * Load-balancing auxiliary loss (Switch-Transformer form) accumulates
    through the scan carry and is added to the LM loss.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..ops import linear, layernorm
from ..ops.attention import sharded_attention
from .gpt2 import GPTConfig, GPT2Model, _dropout


@dataclasses.dataclass(frozen=True)
class MoEConfig(GPTConfig):
    """GPTConfig + routing hyperparameters."""

    n_expert: int = 8
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    ff_mult: int = 4  # expert hidden = ff_mult * n_embd
    # dispatch/combine mechanism: "einsum" (GShard-style dense one-hot
    # matmuls over (S, E, C) — the all-to-all boundary under expert
    # parallelism) or "sort" (argsort tokens by expert, gather rows into
    # (E, C, D), scatter-add back).  The einsum pair costs 2*2*S*(E*C)*D
    # FLOPs per layer — at moe-8x124m bench shape ~2/3 of the expert
    # matmul FLOPs themselves — while the sort path moves the same rows
    # with O(S*k log) sort + gather.  Round 16: the einsum cost IS now
    # counted as model compute — `dispatch_combine_flops_per_token`
    # below feeds bench's flops_tok_matmul when the effective dispatch
    # is einsum, and tests/test_hlo_cost.py pins the analytic number
    # against the HLO-counted FLOPs of the compiled step.
    # "sort" runs single-device and — round 5 — SHARD-LOCAL under pure
    # data parallelism (experts replicated: each device argsorts its own
    # token shard inside a shard_map, capacity prorated by shard, zero
    # extra communication).  It still falls back to einsum under
    # ep/tp/sp/pipe: with EP the einsum contraction IS what GSPMD turns
    # into the all-to-all, and the other axes would put the gather/
    # scatter on partially-manual meshes (`effective_dispatch` is the
    # single predicate; bench.py records its answer).  Slot
    # assignment differs under capacity overflow: einsum fills all 1st
    # choices before 2nd choices, sort fills token-major — identical
    # outputs whenever nothing drops (pinned by test).
    moe_dispatch: str = "einsum"


def effective_dispatch(cfg, pctx) -> str:
    """The dispatch mechanism a step with this config/mesh actually runs —
    ONE predicate shared by `_moe_mlp` and bench.py's A/B record, so a
    measurement can never be labeled with a knob value that fell back."""
    if cfg.moe_dispatch != "sort":
        return cfg.moe_dispatch
    if pctx is None or not pctx.is_multi_device:
        return "sort"
    if (pctx.expert_parallel or pctx.tensor_parallel
            or pctx.seq_parallel or pctx.pipe_parallel):
        return "einsum"
    return "sort"


def dispatch_combine_flops_per_token(cfg, panel_tokens: int) -> float:
    """Analytic TRAIN FLOPs per token of the einsum dispatch/combine pair
    across all layers — the undercount the MoEConfig docstring used to
    only apologize for.

    Per layer the compiled step runs FIVE S-contracting matmuls of
    2*S*E*C*D FLOPs each: dispatch ("sec,sd->ecd") + combine
    ("sec,ecd->sd") forward, then THREE backward — d_xs from the
    dispatch einsum, d_combine and d_ye from the combine einsum.  The
    dispatch one-hot's own cotangent is dead (routing reaches it through
    argmax; only `combine` carries the differentiable gates), so the
    naive 3x-forward rule's sixth matmul never exists.  Divided by the S
    tokens of the routing panel: 10 * n_layer * E * C * D per token,
    with C the same capacity expression `_route` computes from
    `panel_tokens` (= b*t single-device; the per-shard panel under dp
    sharding).  Only the einsum path pays this — `effective_dispatch`
    says whether it runs.  tests/test_hlo_cost.py pins this formula
    against the HLO-counted FLOPs of the compiled moe step."""
    e, k = cfg.n_expert, cfg.expert_top_k
    cap = max(1, int(cfg.capacity_factor * k * panel_tokens / e))
    return 10.0 * cfg.n_layer * e * cap * cfg.n_embd


# Entry-point presets (one flat namespace with gpt2-*/llama-*,
# models/__init__.ALL_PRESETS).  "moe-tiny" smoke-tests on the virtual CPU
# mesh in seconds; "moe-8x124m" is the GPT-2-124M skeleton with 8 experts
# per block (~0.9B params, top-2 routed — the classic Switch/GShard shape).
MOE_PRESETS = {
    "moe-tiny": MoEConfig(
        block_size=256, vocab_size=512, n_layer=2, n_head=2, n_embd=64,
        n_expert=4, expert_top_k=2, compute_dtype=jnp.float32,
    ),
    "moe-8x124m": MoEConfig(
        n_layer=12, n_head=12, n_embd=768, n_expert=8, expert_top_k=2,
    ),
}


class MoEGPT(GPT2Model):
    """GPT-2 skeleton with MoE MLPs.  Same functional API as GPT2Model."""

    # apply() carries the aux load-balance loss through the scan AND through
    # the GPipe pipeline (spmd_pipeline with_aux: bubble ticks masked)
    pipeline_capable = True
    # apply() below re-implements the layer scan with the aux-loss
    # accumulator in the carry and does not thread the scheduler seam
    # (parallel/schedule.py sched=): the grad slot's bucketed release,
    # the gather slot's prefetched/hpZ scan, and the probe slot's
    # health row all sit out — build_schedule refuses each, naming the
    # slot (ScheduleConflictError for compositions)
    grad_bucket_capable = False
    gather_prefetch_capable = False
    layer_health_capable = False
    # ...nor the serving tier's paged decode: expert dispatch routes a
    # whole batch through static per-expert capacity, which a mixed-
    # position slot batch would skew; serving.ServingEngine refuses it
    paged_decode_capable = False
    # 1F1B (round 3): the aux loss joins as a constant-cotangent second
    # output of the layer slab (pipeline.py with_aux), so MoE runs the
    # O(S)-memory schedule too
    supports_1f1b = True
    # ...but NOT the table schedules (interleaved/zbub): the aux loss
    # would have to ride every F tick and replay in W's re-linearization
    # — build_schedule refuses, naming the pipe slot
    supports_pipe_table = False

    def _block_aux_fn(self, pctx):
        """(x, bp) -> (x, aux) with the remat policy applied — shared by
        the GPipe apply() branch and the 1F1B hook."""

        def block_aux(x, bp):
            return self._block(x, bp, pctx)

        if self.config.remat:
            block_aux = jax.checkpoint(block_aux,
                                       policy=self.remat_policy())
        return block_aux

    def _pipeline_1f1b_block(self, pctx):
        c = self.config
        # apply() adds aux_loss_weight * aux_sum / n_layer (below)
        return self._block_aux_fn(pctx), c.aux_loss_weight / c.n_layer, True

    def __init__(self, config: MoEConfig):
        super().__init__(config)

    # -- params ------------------------------------------------------------

    def init(self, key) -> Dict[str, jax.Array]:
        c = self.config
        d, l, v, t, e = c.n_embd, c.n_layer, c.vocab_size, c.block_size, c.n_expert
        f = c.ff_mult * d
        std = 0.02
        pstd = std / math.sqrt(2 * l)
        keys = iter(jax.random.split(key, 16))

        def nrm(k, shape, s):
            return (jax.random.normal(k, shape, jnp.float32) * s).astype(
                c.param_dtype
            )

        def zeros(shape):
            return jnp.zeros(shape, c.param_dtype)

        params = {
            "wte": nrm(next(keys), (v, d), std),
            "wpe": nrm(next(keys), (t, d), std),
            "h.ln_1.w": jnp.ones((l, d), c.param_dtype),
            "h.ln_1.b": zeros((l, d)),
            "h.attn.qkv.w": nrm(next(keys), (l, d, 3 * d), std),
            "h.attn.qkv.b": zeros((l, 3 * d)),
            "h.attn.proj.w": nrm(next(keys), (l, d, d), pstd),
            "h.attn.proj.b": zeros((l, d)),
            "h.ln_2.w": jnp.ones((l, d), c.param_dtype),
            "h.ln_2.b": zeros((l, d)),
            "h.moe.router.w": nrm(next(keys), (l, d, e), std),
            "h.moe.fc.w": nrm(next(keys), (l, e, d, f), std),
            "h.moe.fc.b": zeros((l, e, f)),
            "h.moe.proj.w": nrm(next(keys), (l, e, f, d), pstd),
            "h.moe.proj.b": zeros((l, e, d)),
            "ln_f.w": jnp.ones((d,), c.param_dtype),
            "ln_f.b": zeros((d,)),
            "lm_head.w": nrm(next(keys), (d, v), std),
        }
        if not c.bias:
            # same scope as GPT2Model: projection biases (attn + experts)
            for name in ("h.attn.qkv.b", "h.attn.proj.b",
                         "h.moe.fc.b", "h.moe.proj.b"):
                del params[name]
        if c.tie_weights:
            del params["lm_head.w"]
        return params

    def tp_rules(self) -> Dict[str, int]:
        return {
            "h.attn.qkv.w": 2,
            "h.attn.qkv.b": 1,
            "h.attn.proj.w": 1,
            "h.moe.fc.w": 3,
            "h.moe.fc.b": 2,
            "h.moe.proj.w": 2,
            "lm_head.w": 1,
        }

    def ep_rules(self) -> Dict[str, int]:
        """{param: dim of the (E,) experts axis} — sharded over "expert"."""
        return {
            "h.moe.fc.w": 1,
            "h.moe.fc.b": 1,
            "h.moe.proj.w": 1,
            "h.moe.proj.b": 1,
        }

    # -- routing -----------------------------------------------------------

    def _route(self, x, router_w, capacity=None):
        """Top-k dispatch/combine tensors.  x: (S, D) float32 router input.

        Returns (dispatch (S,E,C) bool-ish, combine (S,E,C), aux scalar).
        Static capacity C = cf * k * S / E; overflow tokens drop (standard
        GShard semantics — the residual stream still carries them).
        `capacity` overrides the formula (the decode path passes the
        drop-free bound S*k: at one position S is tiny, so the train-time
        formula would collapse to ~1 slot and drop tokens the full-sequence
        path keeps).
        """
        c = self.config
        s = x.shape[0]
        e, k = c.n_expert, c.expert_top_k
        cap = capacity or max(1, int(c.capacity_factor * k * s / e))

        gate_vals, expert_idx, aux = self._router(x, router_w)

        dispatch = jnp.zeros((s, e, cap), jnp.float32)
        combine = jnp.zeros((s, e, cap), jnp.float32)
        counts = jnp.zeros((e,), jnp.float32)  # slots used per expert
        for j in range(k):  # k is tiny + static: unrolled
            m = jax.nn.one_hot(expert_idx[:, j], e, dtype=jnp.float32)
            pos = jnp.cumsum(m, axis=0) - 1 + counts[None]  # (S, E)
            keep = m * (pos < cap)
            slot = jax.nn.one_hot(pos.astype(jnp.int32), cap) * keep[..., None]
            dispatch = dispatch + slot
            combine = combine + gate_vals[:, j, None, None] * slot
            counts = counts + jnp.sum(keep, axis=0)

        return dispatch, combine, aux

    def _router(self, x, router_w):
        """Shared router head: (gate_vals (S,k) renormalized, expert_idx
        (S,k), Switch-Transformer aux scalar E * <frac_tokens_e * prob_e>)."""
        c = self.config
        e, k = c.n_expert, c.expert_top_k
        logits = jnp.einsum(
            "sd,de->se", x, router_w, preferred_element_type=jnp.float32
        )
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        gate_vals, expert_idx = jax.lax.top_k(probs, k)  # (S, k)
        gate_vals = gate_vals / (
            jnp.sum(gate_vals, axis=-1, keepdims=True) + 1e-9
        )
        frac = jnp.mean(
            jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32), axis=0
        )
        aux = e * jnp.sum(frac * jnp.mean(probs, axis=0))
        return gate_vals, expert_idx, aux

    def _route_sort(self, x, router_w, capacity=None):
        """Sort-based dispatch tables (moe_dispatch="sort").

        Returns (src (E*C,) int32 token index per expert slot — S for an
        empty slot, gate (E*C,) f32 combine weight per slot, aux).  Same
        router head and capacity formula as `_route`; slots fill
        token-major (stable argsort by expert), so under overflow the
        dropped SET can differ from the einsum path's
        first-choices-first fill — outputs are identical whenever
        capacity drops nothing."""
        c = self.config
        s = x.shape[0]
        e, k = c.n_expert, c.expert_top_k
        cap = capacity or max(1, int(c.capacity_factor * k * s / e))
        gate_vals, expert_idx, aux = self._router(x, router_w)

        flat_e = expert_idx.reshape(-1)              # (S*k,) token-major
        order = jnp.argsort(flat_e, stable=True)
        sorted_e = flat_e[order]
        counts = jnp.sum(
            jax.nn.one_hot(flat_e, e, dtype=jnp.int32), axis=0)  # (E,)
        starts = jnp.cumsum(counts) - counts
        pos_in_e = jnp.arange(s * k, dtype=jnp.int32) - starts[sorted_e]
        keep = pos_in_e < cap
        # kept slots are unique; overflow entries all land on dump slot E*C
        slot = jnp.where(keep, sorted_e * cap + pos_in_e, e * cap)
        tok = (order // k).astype(jnp.int32)
        gate = gate_vals.reshape(-1)[order]
        src = jnp.full((e * cap + 1,), s, jnp.int32).at[slot].set(
            jnp.where(keep, tok, s))[: e * cap]
        gate_tab = jnp.zeros((e * cap + 1,), jnp.float32).at[slot].set(
            jnp.where(keep, gate, 0.0))[: e * cap]
        return src, gate_tab, aux

    # -- forward -----------------------------------------------------------

    def _moe_mlp(self, x, bp, pctx=None, capacity=None):
        """x: (B, T, D) -> (B, T, D), plus aux loss."""
        c = self.config
        b, t, d = x.shape
        xs = x.reshape(b * t, d)
        if c.moe_dispatch not in ("einsum", "sort"):
            raise ValueError(
                f"moe_dispatch={c.moe_dispatch!r}: expected 'einsum' or "
                "'sort' (a typo here would silently run the einsum path "
                "while being recorded as a sort A/B)")
        ep = pctx is not None and pctx.expert_parallel
        disp = effective_dispatch(c, pctx)
        if disp == "sort":
            # gather/scatter dispatch: skips the two dense (S,E*C,D)
            # one-hot matmuls (config docstring)
            if pctx is None or not pctx.is_multi_device:
                y, aux = self._moe_mlp_sort(xs, bp, pctx, capacity)
                return y.reshape(b, t, d), aux
            # pure-DP multi-device (round 5): experts are replicated, so
            # each device dispatches its LOCAL token shard with a local
            # argsort (capacity prorated by shard size) — mathematically
            # the same routing, no global sort, no extra communication.
            # The fp8 '#scale' companions MUST cross the shard_map
            # boundary too, or _bw inside the manual region would see no
            # scale and hand the expert einsums raw float8 weights; the
            # _bw sharding constraint itself is skipped in there
            # (pctx=None — the gathers are forced at the boundary).
            from jax.sharding import PartitionSpec as P
            names = [n for base in ("moe.router.w", "moe.fc.w",
                                    "moe.fc.b", "moe.proj.w",
                                    "moe.proj.b")
                     for n in (base, base + "#scale") if n in bp]
            dax = pctx.data_axis
            if capacity is not None:
                # an explicit capacity names a GLOBAL slot budget; applied
                # as-is inside the shard-local sort it would multiply
                # n_shard-fold on a multi-device mesh.  Prorate by the
                # token-shard count (ceil, so tiny decode budgets never
                # hit zero) — same proration the formula-driven default
                # gets for free from the local S
                n_sh = int(pctx.mesh.shape[dax])
                capacity = -(-int(capacity) // n_sh)

            def local(xs_l, *ws):
                y_l, aux_l = self._moe_mlp_sort(
                    xs_l, dict(zip(names, ws)), None, capacity)
                return y_l, jax.lax.pmean(aux_l, dax)

            y, aux = jax.shard_map(
                local, mesh=pctx.mesh,
                in_specs=(P(dax),) + (P(),) * len(names),
                out_specs=(P(dax), P()), check_vma=False,
            )(xs, *[bp[n] for n in names])
            return y.reshape(b, t, d), aux
        dispatch, combine, aux = self._route(
            xs.astype(jnp.float32), bp["moe.router.w"].astype(jnp.float32),
            capacity=capacity,
        )
        dispatch = dispatch.astype(x.dtype)
        # (S,E,C) x (S,D) -> (E,C,D): the all-to-all boundary under EP
        xe = jnp.einsum("sec,sd->ecd", dispatch, xs)
        if ep:
            from jax.sharding import NamedSharding, PartitionSpec as P
            xe = jax.lax.with_sharding_constraint(
                xe, NamedSharding(pctx.mesh, P(pctx.expert_axis, None, None))
            )
        ye = self._expert_ffn(xe, bp, pctx)
        y = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), ye)
        return y.reshape(b, t, d), aux

    def _expert_ffn(self, xe, bp, pctx=None):
        """(E, C, D) -> (E, C, D): the expert MLP body, shared by both
        dispatch mechanisms (pctx threads the TP placement and the fp8
        gather constraint through _bw for BOTH paths)."""
        h = jnp.einsum("ecd,edf->ecf", xe, self._bw(bp, "moe.fc.w", pctx))
        if "moe.fc.b" in bp:
            h = h + bp["moe.fc.b"][:, None]
        h = jax.nn.gelu(h, approximate=True)
        ye = jnp.einsum("ecf,efd->ecd", h, self._bw(bp, "moe.proj.w", pctx))
        if "moe.proj.b" in bp:
            ye = ye + bp["moe.proj.b"][:, None]
        return ye

    def _moe_mlp_sort(self, xs, bp, pctx=None, capacity=None):
        """moe_dispatch="sort" body on a flat (S, D) token panel: gather
        rows per expert slot, run the same (E, C, D) expert einsums,
        scatter-add weighted outputs.  Returns ((S, D), aux) — S is the
        LOCAL shard when called inside the pure-DP shard_map."""
        c = self.config
        s, d = xs.shape
        e = c.n_expert
        src, gate, aux = self._route_sort(
            xs.astype(jnp.float32), bp["moe.router.w"].astype(jnp.float32),
            capacity=capacity,
        )
        cap = src.shape[0] // e
        xpad = jnp.concatenate([xs, jnp.zeros((1, d), xs.dtype)])
        xe = xpad[src].reshape(e, cap, d)        # empty slots -> zero row
        ye = self._expert_ffn(xe, bp, pctx)
        contrib = gate[:, None].astype(ye.dtype) * ye.reshape(e * cap, d)
        y = jnp.zeros((s + 1, d), ye.dtype).at[src].add(contrib)[:s]
        return y.astype(xs.dtype), aux

    def _block(self, x, bp, pctx=None, return_kv=False):
        """Pre-LN block: attention + MoE MLP.  Returns (x, aux)."""
        c = self.config
        b, t, d = x.shape
        dkey = bp.get("dropout_rng")

        h = layernorm(x, bp["ln_1.w"], bp["ln_1.b"])
        qkv = linear(h, self._bw(bp, "attn.qkv.w", pctx), bp.get("attn.qkv.b"))
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):
            return z.reshape(b, t, c.n_head, c.head_dim).swapaxes(1, 2)

        kh, vh = heads(k), heads(v)
        y = sharded_attention(heads(q), kh, vh, c.attn_impl, pctx)
        y = y.swapaxes(1, 2).reshape(b, t, d)
        y = linear(y, self._bw(bp, "attn.proj.w", pctx), bp.get("attn.proj.b"))
        if dkey is not None:
            y = _dropout(y, jax.random.fold_in(dkey, 0), c.dropout)
        x = x + y

        h = layernorm(x, bp["ln_2.w"], bp["ln_2.b"])
        y, aux = self._moe_mlp(h, bp, pctx)
        if dkey is not None:
            y = _dropout(y, jax.random.fold_in(dkey, 1), c.dropout)
        x = x + y
        return ((x, aux), (kh, vh)) if return_kv else (x, aux)

    def _prefill_body(self, x, bp):
        """KV-cache prompt pass: aux loss is a training quantity — dropped
        at inference."""
        (x, _aux), kv = self._block(x, bp, None, return_kv=True)
        return x, kv

    def _block_decode(self, x, bp, ks, vs, l, pos):
        """Cached attention (GPT2Model._attn_decode) + routed experts on
        the single position, with DROP-FREE capacity S*k (the train-time
        cf*k*S/E formula collapses to ~1 slot at S=B and would drop tokens
        the full-sequence path keeps).  NB the uncached path can still drop
        an over-capacity token the decode path keeps — inherent to
        static-capacity GShard routing; equality holds whenever neither
        path overflows."""
        x, ks, vs = self._attn_decode(x, bp, ks, vs, l, pos)
        h = layernorm(x, bp["ln_2.w"], bp["ln_2.b"])
        s = x.shape[0]  # one position: S = B tokens routed together
        y, _aux = self._moe_mlp(
            h, bp, None, capacity=s * self.config.expert_top_k
        )
        return x + y, ks, vs

    def _quant_eligible(self, name, v):
        """Router excluded from the fp8 gather: routing logits need full
        precision for a stable softmax/top-k."""
        return super()._quant_eligible(name, v) and "router" not in name

    def stacked_compute_params(self, params):
        """Like GPT2Model's (incl. the optional fp8 gather), but router
        weights stay float32."""
        out = super().stacked_compute_params(params)
        out["moe.router.w"] = params["h.moe.router.w"]
        return out

    def apply(self, params, idx, targets: Optional[jax.Array] = None,
              pctx=None, position=None, rng=None):
        c = self.config
        x = self.embed(params, idx, pctx)
        stacked = self.stacked_compute_params(params)
        stacked, x = self._dropout_setup(stacked, x, rng)

        if pctx is not None and pctx.pipe_parallel:
            from ..parallel.pipeline import spmd_pipeline

            x, aux_sum = spmd_pipeline(
                self._block_aux_fn(pctx), stacked, x,
                mesh=pctx.mesh, pipe_axis=pctx.pipe_axis,
                data_axis=pctx.data_axis,
                microbatches=pctx.pipe_microbatches or None,
                seq_axis=pctx.seq_axis, with_aux=True,
            )
        else:
            def block(carry, bp):
                x, aux_sum = carry
                x, aux = self._block(x, bp, pctx)
                return (x, aux_sum + aux), None

            if c.remat:
                block = jax.checkpoint(block, policy=self.remat_policy())

            (x, aux_sum), _ = jax.lax.scan(
                block, (x, jnp.zeros((), jnp.float32)), stacked,
                unroll=c.scan_unroll,
            )

        out = self.head(params, x, targets, pctx, position)
        if targets is not None:
            return out + c.aux_loss_weight * aux_sum / c.n_layer
        return out
