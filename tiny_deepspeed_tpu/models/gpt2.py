# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""GPT-2, pure-JAX and TPU-first.

Capability parity with the reference model (example/model.py): GPTConfig
(:15-25), token+position embeddings, pre-LN transformer blocks with fused-QKV
causal attention (:53-85), GELU MLP (:89-101), final layernorm, weight-untied
lm_head, and cross-entropy loss computed inside forward when targets are given
(:139-157).  The `attn_impl` switch ("standard_attention" | "flash_attention")
mirrors reference model.py:25,78-81.

Deliberate TPU-first design deltas (this is a re-design, not a port):

  * Parameters are a FLAT, NAME-KEYED dict (ordered), not nn.Module
    attributes.  Names are stable and sorted insertion order — this is what
    the partitioner ("cache rank map") and the name-keyed optimizers consume,
    replacing torch's named_parameters() iteration.
  * The L transformer blocks are STACKED: each block tensor carries a leading
    (n_layer,) axis and the forward runs `jax.lax.scan` over it.  One traced
    block → O(1) compile time in depth (a 48-layer 1.5B model compiles as
    fast as a 1-layer one), and the stacked axis is a natural target for
    pipeline/ZeRO sharding.
  * Linear weights are (in, out) — see ops/linear.py.
  * Mixed precision is a first-class policy: params live in `param_dtype`
    (float32) and compute runs in `compute_dtype` (bfloat16 on TPU).  The
    reference's AMP is an unchecked TODO (reference README.md:68).
  * Each block is wrapped in `jax.checkpoint` (remat) so the backward
    re-materializes activations instead of storing 2L of them — the TPU way
    to trade MXU FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops import (
    linear,
    layernorm,
    embedding,
    softmax_cross_entropy,
)
from ..ops.attention import sharded_attention


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Model hyperparameters (parity: reference example/model.py:15-25)."""

    block_size: int = 1024
    vocab_size: int = 50304  # padded to a multiple of 128 for the MXU
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    attn_impl: str = "flash_attention"  # or "standard_attention"
    # Reference-parity knobs (reference example/model.py:23-24):
    #  * `bias` gates the four projection biases (attn qkv/proj, mlp fc/
    #    proj — reference nn.Linear(bias=config.bias)); layernorms keep
    #    theirs (the reference uses stock nn.LayerNorm) and lm_head is
    #    always bias-free (reference model.py:137).  The reference DEFAULTS
    #    bias=False; default True here = the actual GPT-2 architecture.
    #  * `dropout` in the reference is a dead knob: config.dropout is never
    #    read, and its attention calls hard-code `dropout_p=False` == 0.0
    #    (reference model.py:79-81) so dropout never fires even in
    #    training.  Implemented CORRECTLY here (embedding + post-attention
    #    + post-MLP residual dropout, inverted scaling); active only when
    #    a PRNG key is passed to `apply(rng=...)` — the engine does so
    #    automatically when dropout > 0, deriving a fresh key from the
    #    optimizer step counter, so eval/generate stay deterministic.
    bias: bool = True
    dropout: float = 0.0
    # wte/lm_head weight tying.  The ACTUAL GPT-2 ties them; the reference
    # unties (model.py:136-138 creates an independent lm_head), so False is
    # the parity default.  Tied drops the (vocab, d) lm_head table —
    # 38.6M params on gpt2-124m — and the gradient flows through both the
    # gather and the projection use of wte.
    tie_weights: bool = False
    # ZeRO++-style quantized weight gather (qwZ, arxiv 2306.10209), the
    # float8 variant: "fp8" stacks the block matmul weights as
    # float8_e4m3 + per-output-channel f32 scales instead of compute-dtype
    # values, so the per-layer all-gather inside the ZeRO-3 scan moves 2x
    # fewer bytes than bf16 (4x vs f32); each block dequantizes after the
    # gather (one multiply, fused by XLA into the matmul).  Scaling/cast
    # runs ONCE per step from the float32 masters, outside the scan and
    # outside remat.  fp8 rather than int8 deliberately: the e4m3 cast is
    # differentiable (FP8-training style), so no straight-through
    # custom-vjp machinery — the cost is that the per-layer dW cotangent
    # crosses the same edge in e4m3 (scaled by the same per-channel
    # absmax), the standard FP8-comm tradeoff — convergence validated vs
    # the unquantized path in tests/test_fp8_gather.py (30-step loss
    # curves within 5%).  EXPERIMENTAL; the byte win is backend-dependent
    # and on XLA CPU it is NEGATIVE (round-3 measurement, PROFILE.md):
    # the collective upcasts f8 to f16 and several remat-backward gathers
    # stay full precision, so the quantized config moves ~1.34x MORE wire
    # bytes than plain compute dtype (collective ledger pinned in
    # tests/test_profiling.py).  Profile on the target backend before
    # relying on it.  None (default) keeps the exact compute-dtype path.
    gather_quant: Optional[str] = None
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat: bool = True
    # which intermediates the block remat may keep instead of recomputing:
    # "nothing" | "dots" | "dots_no_batch" | "all"  (measured on v5e-1,
    # GPT-2 124M B=8: dots_no_batch ~84.0k tok/s vs nothing ~80.3k;
    # "nothing" still minimizes HBM)
    remat_policy: str = "dots_no_batch"
    # token-embedding row-norm cap: each USED row of wte is rescaled to
    # ||row|| <= wte_max_norm before the gather (reference nn.Embedding
    # max_norm, wired through reference ops/embedding.py:67-68; the
    # reference's GPT-2 never sets it, so None is parity).  Functional:
    # the stored table is untouched, grads flow through the rescale.
    wte_max_norm: Optional[float] = None
    # chunked lm_head+loss (never materializes full (B, T, V) logits;
    # ops/softmax_xent.fused_linear_xent).  A MEMORY knob, not a speed knob:
    # measured v5e-1 gpt2-124m B=8 T=1024 it costs ~8% (77.0k vs 83.8k
    # tok/s — backward recomputes the lm_head matmul) while capping live
    # logits at chunk/T of full; enable for long-T / tight-HBM configs
    # where full (B, T, V) logits would not fit.  Falls back automatically
    # under sequence parallelism (chunking would slice the sharded T axis).
    fused_xent: bool = False
    # which fused implementation: "chunked" (the XLA scan above) or
    # "pallas" (ops/xent_pallas.py — logit tiles live only in VMEM,
    # online logsumexp + in-kernel gold gather, FA2-style recompute
    # backward; round 5).  "pallas" is TPU-gated and falls back to the
    # chunked path elsewhere; adoption as default awaits the chip A/B
    # (tpu_batch.sh step 13, VERDICT r4 #8: measure standalone first,
    # adopt only on an end-to-end win).
    fused_xent_impl: str = "chunked"
    # resting dtype of the decode KV cache (generate(use_cache=True) and
    # the serving tier's contiguous prefill).  None keeps compute_dtype;
    # "bf16"/jnp.bfloat16 halves cache HBM on an f32-compute config —
    # decode is cache-bandwidth bound, and `_decode_attention` already
    # consumes the cache in its resting dtype with f32 MXU accumulation.
    # Greedy parity vs the full-forward path is seed-pinned in
    # tests/test_serving.py.  int8/fp8 cache compression lives in the
    # PAGED pool only (serving/pool.py), where the per-vector scales have
    # a place to rest.
    cache_dtype: Any = None
    # lax.scan unroll factor for the layer stack (True/n_layer = fully
    # unrolled).  Unrolling deletes the scan's stacked activation-stash
    # dynamic-slice traffic — the round-4 TPU profile priced that IO plus
    # the slice/update fusions at ~16 ms of a 132 ms gpt2-124m step — and
    # lets XLA schedule across layer boundaries: measured v5e-1 124M
    # B=12 106.5k tok/s / 0.463 matmul MFU vs 92.0k / 0.401 scanned
    # (+16%).  Default stays scanned: one traced block keeps compile time
    # O(1) in depth (SURVEY §3.1 rationale), and under ZeRO-3 the scan is
    # what bounds live gathered weights to one layer — unrolling there
    # lets XLA hoist gathers and regrow full-model HBM.  Engines leave
    # this to the user/bench config; pipeline ignores it (stages scan).
    scan_unroll: Any = 1

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


# cache_dtype knob spellings -> jnp dtypes (dtype objects pass through)
_CACHE_DTYPES = {
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "f16": jnp.float16, "fp16": jnp.float16, "float16": jnp.float16,
    "f32": jnp.float32, "fp32": jnp.float32, "float32": jnp.float32,
}


def resolved_cache_dtype(cfg) -> Any:
    """The decode KV cache's resting dtype: config.cache_dtype (string
    spelling or dtype), defaulting to compute_dtype.  Shared by the
    in-scan decode cache (`_prefill`) and the serving tier's paged pool
    (serving/pool.py) so the knob means the same thing on both."""
    cd = getattr(cfg, "cache_dtype", None)
    if cd is None:
        return cfg.compute_dtype
    if isinstance(cd, str):
        try:
            return _CACHE_DTYPES[cd]
        except KeyError:
            raise ValueError(
                f"cache_dtype {cd!r} not understood; use one of "
                f"{sorted(_CACHE_DTYPES)} or a jnp dtype (int8/fp8 cache "
                f"compression lives in the paged pool: serving/pool.py)"
            ) from None
    return cd


# Named presets covering the BASELINE.md workloads.  "tiny" exists so every
# example entry point smoke-tests in seconds on the virtual CPU mesh
# (`--cpu-devices 8`): XLA-CPU compile of a full 124M step takes minutes
# (round-1 verdict weak #7); float32 compute because CPU bf16 is emulated.
GPT2_PRESETS: Dict[str, GPTConfig] = {
    "tiny": GPTConfig(block_size=256, vocab_size=512, n_layer=2, n_head=2,
                      n_embd=64, compute_dtype=jnp.float32),
    "gpt2-124m": GPTConfig(n_layer=12, n_head=12, n_embd=768),
    "gpt2-350m": GPTConfig(n_layer=24, n_head=16, n_embd=1024),
    "gpt2-774m": GPTConfig(n_layer=36, n_head=20, n_embd=1280),
    "gpt2-1.5b": GPTConfig(n_layer=48, n_head=25, n_embd=1600),
}


def effective_xent_impl(cfg, multi_device: bool = False,
                        seq_sharded: bool = False,
                        tokens: Optional[int] = None) -> str:
    """The loss-head implementation a step with this config/mesh actually
    runs — ONE predicate shared by `GPT2Model.head` and bench.py's A/B
    record (mirroring moe.effective_dispatch), so a measurement can never
    be labeled with a knob value that fell back.

    Returns "unfused" (materialized logits), "chunked" (XLA
    fused_linear_xent ladder), or "pallas" (ops/xent_pallas.py — only on
    a single-device TPU kernel target, and only when `tokens` (= B*T, if
    known) admits a viable VMEM token-block)."""
    if not getattr(cfg, "fused_xent", False) or seq_sharded:
        return "unfused"
    if getattr(cfg, "fused_xent_impl", "chunked") == "pallas":
        from ..ops.dispatch import kernel_target
        from ..ops.xent_pallas import viable_token_block
        if (kernel_target() == "tpu" and not multi_device
                and (tokens is None or viable_token_block(tokens))):
            return "pallas"
    return "chunked"


def _dropout(x, key, rate: float):
    """Inverted dropout: zero with prob `rate`, survivors scaled 1/(1-rate)
    so eval needs no rescaling.  `key` may be a raw (2,) uint32 key row
    (what a stacked `jax.random.split` yields per layer)."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class GPT2Model:
    """Functional GPT-2: `init(key) -> params`, `apply(params, idx, targets)`.

    Replaces the reference's nn.Module (example/model.py:125-157).  There is
    no layer-swap wrapping step (reference zero/utils/wrapper.py:9-36):
    parallel modes change *shardings and the train step*, never the model
    code.
    """

    # apply() implements the GPipe pipeline path (pctx.pipe_parallel);
    # subclasses that override apply() without it must reset this flag
    pipeline_capable = True
    # apply() threads the scheduler seam (parallel/schedule.py sched=)
    # through the layer scan — the grad slot's bucketed release tap,
    # and the composed lowering drives this family's block_fn/embed/head
    # directly; subclasses that override apply() without the sched
    # branch must reset these (MoEGPT does — its scan carries the
    # aux-loss accumulator the scheduler's scan bodies do not thread)
    grad_bucket_capable = True
    # the gather slot (ZeRO-3 prefetched / hpZ weight-gather scan)
    gather_prefetch_capable = True
    # the probe slot (per-layer health: schedule.layer_health_tap rides
    # the stacked scan tree when a "health_probe" row is present)
    layer_health_capable = True
    # paged_prefill/paged_decode read and write the serving tier's paged
    # KV pool (serving/pool.py block tables); families whose decode step
    # cannot batch rows at different positions (MoE's capacity-routed
    # dispatch) must reset this — serving.ServingEngine refuses them
    paged_decode_capable = True

    def __init__(self, config: GPTConfig):
        self.config = config
        self._generate_cache = {}  # (shape, sampling) -> jitted decode

    # -- initialization ----------------------------------------------------

    def param_shapes(self) -> Dict[str, jax.ShapeDtypeStruct]:
        """Shape/dtype pytree without allocating — the TPU equivalent of the
        reference's meta-device init (reference zero1/train.py:25-27)."""
        return jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))

    def init(self, key) -> Dict[str, jax.Array]:
        c = self.config
        d, l, v, t = c.n_embd, c.n_layer, c.vocab_size, c.block_size
        std = 0.02
        # GPT-2 init: N(0, 0.02), residual-projection std scaled by 1/sqrt(2L)
        pstd = std / math.sqrt(2 * l)
        keys = iter(jax.random.split(key, 16))

        def nrm(k, shape, s):
            return (jax.random.normal(k, shape, jnp.float32) * s).astype(
                c.param_dtype
            )

        def zeros(shape):
            return jnp.zeros(shape, c.param_dtype)

        params = {
            "wte": nrm(next(keys), (v, d), std),
            "wpe": nrm(next(keys), (t, d), std),
            "h.ln_1.w": jnp.ones((l, d), c.param_dtype),
            "h.ln_1.b": zeros((l, d)),
            "h.attn.qkv.w": nrm(next(keys), (l, d, 3 * d), std),
            "h.attn.qkv.b": zeros((l, 3 * d)),
            "h.attn.proj.w": nrm(next(keys), (l, d, d), pstd),
            "h.attn.proj.b": zeros((l, d)),
            "h.ln_2.w": jnp.ones((l, d), c.param_dtype),
            "h.ln_2.b": zeros((l, d)),
            "h.mlp.fc.w": nrm(next(keys), (l, d, 4 * d), std),
            "h.mlp.fc.b": zeros((l, 4 * d)),
            "h.mlp.proj.w": nrm(next(keys), (l, 4 * d, d), pstd),
            "h.mlp.proj.b": zeros((l, d)),
            "ln_f.w": jnp.ones((d,), c.param_dtype),
            "ln_f.b": zeros((d,)),
            # weight-untied lm_head, like the reference (model.py:136-138)
            "lm_head.w": nrm(next(keys), (d, v), std),
        }
        if not c.bias:
            # reference bias=False scope: projection linears only
            for name in ("h.attn.qkv.b", "h.attn.proj.b",
                         "h.mlp.fc.b", "h.mlp.proj.b"):
                del params[name]
        if c.tie_weights:
            del params["lm_head.w"]  # head projects through wte.T
        return params

    def tp_rules(self) -> Dict[str, int]:
        """Megatron-style tensor-parallel placement: {param name: dim index
        to shard over the "model" mesh axis}.  Column-parallel qkv/fc (output
        dim), row-parallel attn/mlp proj (input dim — GSPMD inserts the psum
        the row-parallel matmul needs), vocab-parallel lm_head.  Consumed by
        the engine when tensor_parallel > 1; absent entirely from the
        reference (SURVEY §2.20: no TP of any kind)."""
        return {
            "h.attn.qkv.w": 2,
            "h.attn.qkv.b": 1,
            "h.attn.proj.w": 1,
            "h.mlp.fc.w": 2,
            "h.mlp.fc.b": 1,
            "h.mlp.proj.w": 1,
            "lm_head.w": 1,
        }

    def num_params(self, params=None) -> int:
        shapes = params if params is not None else self.param_shapes()
        return sum(int(math.prod(x.shape)) for x in shapes.values())

    # -- forward -----------------------------------------------------------

    def _block(self, x, bp, pctx=None, return_kv=False):
        """One pre-LN transformer block. x: (B, T, D) in compute_dtype;
        bp: this block's params, already in compute_dtype (pre-cast once in
        `apply` — casting per-layer inside the scan re-reads the float32
        master params three times per step: fwd, remat re-fwd, bwd).
        return_kv additionally returns this layer's (k, v) head tensors —
        the KV-cache prefill hook (`_prefill`)."""
        c = self.config
        b, t, d = x.shape
        # dropout rides the stacked tree as a per-layer PRNG key; its
        # presence (static at trace time) is the train/eval switch
        dkey = bp.get("dropout_rng")

        h = layernorm(x, bp["ln_1.w"], bp["ln_1.b"])
        qkv = linear(h, self._bw(bp, "attn.qkv.w", pctx), bp.get("attn.qkv.b"))
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):  # (B, T, D) -> (B, H, T, Dh)
            return z.reshape(b, t, c.n_head, c.head_dim).swapaxes(1, 2)

        kh, vh = heads(k), heads(v)
        y = sharded_attention(heads(q), kh, vh, c.attn_impl, pctx)
        y = y.swapaxes(1, 2).reshape(b, t, d)
        y = linear(y, self._bw(bp, "attn.proj.w", pctx), bp.get("attn.proj.b"))
        if dkey is not None:
            y = _dropout(y, jax.random.fold_in(dkey, 0), c.dropout)
        x = x + y

        h = layernorm(x, bp["ln_2.w"], bp["ln_2.b"])
        h = linear(h, self._bw(bp, "mlp.fc.w", pctx), bp.get("mlp.fc.b"))
        h = jax.nn.gelu(h, approximate=True)
        h = linear(h, self._bw(bp, "mlp.proj.w", pctx), bp.get("mlp.proj.b"))
        if dkey is not None:
            h = _dropout(h, jax.random.fold_in(dkey, 1), c.dropout)
        x = x + h
        return (x, (kh, vh)) if return_kv else x

    # -- KV-cache decode ---------------------------------------------------
    #
    # generate(use_cache=False) re-runs the FULL (B, block_size) forward per
    # sampled token: O(L * T^2) attention per token.  The cached path runs
    # the prompt once ("prefill", which also emits every layer's K/V head
    # tensors), then each new token is one (B, 1, D) pass attending to the
    # cache — O(L * T) per token, the standard inference structure.  The
    # reference never needed either: its model only trains (SURVEY §2.1).

    def _decode_attention(self, q, ck, cv, pos):
        """q: (B, Hq, 1, Dh); ck/cv: (B, Hkv, T, Dh) caches; pos: the
        query's position (cache filled through pos) — a scalar, or a (B,)
        vector when each row sits at its own position (the serving tier's
        paged decode batches requests of different lengths).  Full-length
        masked attention — slots past pos are padding, masked out.  GQA
        (Hq > Hkv) groups query heads per KV head instead of materializing
        a repeated cache.

        Decode is HBM-bandwidth bound, so the dots consume the cache in
        its RESTING dtype (config.cache_dtype, default compute_dtype)
        with f32 MXU accumulation — the previous `.astype(f32)` on ck/cv
        materialized two full f32 cache copies per token (~2x the cache
        bytes; round-5 decode pass).  Scores, mask and softmax stay f32."""
        b, hq, _, dh = q.shape
        hkv = ck.shape[1]
        scale = 1.0 / math.sqrt(dh)
        out_dtype = q.dtype  # restore the ACTIVATION dtype on return,
        q = q.astype(ck.dtype)  # not the resting cache dtype
        pos = jnp.asarray(pos)
        mask = jnp.arange(ck.shape[2]) <= (
            pos[:, None] if pos.ndim else pos
        )  # (B, T) per-row, or (T,) shared
        m4 = (mask[None, None, None] if mask.ndim == 1
              else mask[:, None, None, :])
        if hq != hkv:
            g = hq // hkv
            att = jnp.einsum(
                "bkgd,bktd->bkgt", q.reshape(b, hkv, g, dh), ck,
                preferred_element_type=jnp.float32) * scale
            att = jnp.where(m4, att, -jnp.inf)
            att = jax.nn.softmax(att, axis=-1)
            y = jnp.einsum("bkgt,bktd->bkgd", att.astype(cv.dtype), cv,
                           preferred_element_type=jnp.float32)
            y = y.reshape(b, hq, 1, dh)
        else:
            att = jnp.einsum("bhqd,bhtd->bhqt", q, ck,
                             preferred_element_type=jnp.float32) * scale
            att = jnp.where(m4, att, -jnp.inf)
            att = jax.nn.softmax(att, axis=-1)
            y = jnp.einsum("bhqt,bhtd->bhqd", att.astype(cv.dtype), cv,
                           preferred_element_type=jnp.float32)
        return y.astype(out_dtype)

    def _attn_decode(self, x, bp, ks, vs, l, pos):
        """Attention half of one decode step on the STACKED (L, B, Hkv,
        T, Dh) caches: write this position's K/V — a (1, B, Hkv, 1, Dh)
        sliver — in place at (l, pos), read layer l's panel, attend,
        residual-add.  x: (B, 1, D).  The caches ride the layer scan's
        CARRY (not xs/ys — see _decode_blocks), so the write aliases the
        buffer instead of restacking it."""
        c = self.config
        b = x.shape[0]
        h = layernorm(x, bp["ln_1.w"], bp["ln_1.b"])
        qkv = linear(h, self._bw(bp, "attn.qkv.w"), bp.get("attn.qkv.b"))
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads1(z):
            return z.reshape(b, 1, c.n_head, c.head_dim).swapaxes(1, 2)

        ks = jax.lax.dynamic_update_slice(
            ks, heads1(k).astype(ks.dtype)[None], (l, 0, 0, pos, 0)
        )
        vs = jax.lax.dynamic_update_slice(
            vs, heads1(v).astype(vs.dtype)[None], (l, 0, 0, pos, 0)
        )
        ck = jax.lax.dynamic_index_in_dim(ks, l, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(vs, l, 0, keepdims=False)
        y = self._decode_attention(heads1(q), ck, cv, pos)
        y = y.swapaxes(1, 2).reshape(b, 1, c.n_embd)
        y = linear(y, self._bw(bp, "attn.proj.w"), bp.get("attn.proj.b"))
        return x + y, ks, vs

    def _mlp_decode(self, x, bp):
        """MLP half of one decode step (norm + MLP + residual) — shared
        between the contiguous-cache and paged decode paths."""
        h = layernorm(x, bp["ln_2.w"], bp["ln_2.b"])
        h = linear(h, self._bw(bp, "mlp.fc.w"), bp.get("mlp.fc.b"))
        h = jax.nn.gelu(h, approximate=True)
        h = linear(h, self._bw(bp, "mlp.proj.w"), bp.get("mlp.proj.b"))
        return x + h

    def _block_decode(self, x, bp, ks, vs, l, pos):
        """One block, one token: cached attention + MLP."""
        x, ks, vs = self._attn_decode(x, bp, ks, vs, l, pos)
        return self._mlp_decode(x, bp), ks, vs

    def _prefill_body(self, x, bp):
        """Scan body for the prompt pass: (x, (k, v)).  Families whose
        _block returns extra values (MoE aux) override this to discard
        them."""
        return self._block(x, bp, None, return_kv=True)

    def _prefill(self, params, idx, cache_len, stacked=None):
        """Run the prompt, returning final-position logits (B, V) float32
        plus (L, B, Hkv, cache_len, Dh) K/V caches (prompt prefix filled,
        rest zeros).  The caches REST in resolved_cache_dtype(config) —
        compute_dtype unless the cache_dtype knob narrows it (decode is
        cache-bandwidth bound; `_decode_attention` consumes the resting
        dtype directly with f32 accumulation, so a narrower cache halves
        HBM traffic without touching activation dtypes)."""
        x = self.embed(params, idx)
        if stacked is None:
            stacked = self.stacked_compute_params(params)
        x, (ks, vs) = jax.lax.scan(self._prefill_body, x, stacked,
                                   unroll=self.config.scan_unroll)
        cdt = resolved_cache_dtype(self.config)
        ks, vs = ks.astype(cdt), vs.astype(cdt)
        pad = ((0, 0), (0, 0), (0, 0), (0, cache_len - idx.shape[1]), (0, 0))
        return self.head(params, x)[:, 0], jnp.pad(ks, pad), jnp.pad(vs, pad)

    def _decode_blocks(self, stacked, x, ks, vs, pos):
        """Layer loop for one decode token.  The caches ride the CARRY
        and each layer writes its (1, B, H, 1, Dh) sliver in place —
        the previous formulation passed them as scan xs/ys, which
        restacked (read + wrote) the ENTIRE (L, B, H, T, Dh) cache pair
        every token (~226 MB/token at the 124M decode bench shape, pure
        copy; round-5 decode pass)."""
        n_layer = jax.tree.leaves(stacked)[0].shape[0]

        def body(carry, l):
            x, ks, vs = carry
            bp = jax.tree.map(
                lambda s: jax.lax.dynamic_index_in_dim(
                    s, l, 0, keepdims=False), stacked)
            x, ks, vs = self._block_decode(x, bp, ks, vs, l, pos)
            return (x, ks, vs), None

        (x, ks, vs), _ = jax.lax.scan(
            body, (x, ks, vs), jnp.arange(n_layer),
            unroll=self.config.scan_unroll)
        return x, ks, vs

    def _embed_decode(self, params, tok, pos):
        """One token per row -> (B, 1, D).  tok: (B,) ints; pos: scalar
        (every row at the same position — `generate`) or (B,) vector
        (each row at its own position — the serving tier's paged decode,
        where concurrent requests sit at different lengths)."""
        x = self.embed_tokens(params, tok[:, None])
        if jnp.ndim(pos) == 0:
            wp = jax.lax.dynamic_slice_in_dim(params["wpe"], pos, 1, 0)[None]
        else:
            wp = params["wpe"][pos][:, None]
        return x + wp.astype(x.dtype)

    @staticmethod
    def _sample(logit, key, temperature, top_k):
        """(B, V) float32 logits -> (B,) int32 next tokens — delegates to
        the ONE sampling core (models/sampling.py) shared with the
        serving tier, so a sampling change lands in every decode surface
        at once."""
        from .sampling import sample_logits
        return sample_logits(logit, key, temperature, top_k)

    def _generate_impl_cached(self, params, idx, key, *, t0, max_new_tokens,
                              temperature, top_k):
        total = t0 + max_new_tokens
        b = idx.shape[0]
        buf = jnp.zeros((b, total), jnp.int32)
        buf = jax.lax.dynamic_update_slice(buf, idx.astype(jnp.int32), (0, 0))
        if max_new_tokens == 0:
            return buf
        stacked = self.stacked_compute_params(params)
        logits, ks, vs = self._prefill(params, idx, total, stacked)

        def body(i, carry):
            buf, ks, vs, logits, key = carry
            key, sub = jax.random.split(key)
            nxt = self._sample(logits, sub, temperature, top_k)
            buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, i))
            x = self._embed_decode(params, nxt, i)
            x, ks, vs = self._decode_blocks(stacked, x, ks, vs, i)
            logits = self.head(params, x)[:, 0]
            return buf, ks, vs, logits, key

        # N-1 decode iterations; the final token needs only a sample, not
        # another L-layer pass whose logits nobody reads
        buf, ks, vs, logits, key = jax.lax.fori_loop(
            t0, total - 1, body, (buf, ks, vs, logits, key)
        )
        key, sub = jax.random.split(key)
        last = self._sample(logits, sub, temperature, top_k)
        return jax.lax.dynamic_update_slice(buf, last[:, None], (0, total - 1))

    # -- paged KV-cache decode (the serving tier) --------------------------
    #
    # Same math as the contiguous decode above, but the cache lives in a
    # SHARED preallocated block pool (serving/pool.py): each slot's K/V
    # panel is gathered through its block table instead of sliced from a
    # per-request max-length buffer, and every slot sits at its OWN
    # position (vector `pos`).  The attention itself is the existing GQA
    # `_decode_attention`; only the cache read/write changes.

    def _paged_attention(self, q, view, l, page, span_kv=None):
        """ONE dispatch seam for pool-panel attention, shared by the
        paged decode and spec-verify/suffix-prefill paths of every
        family: the Pallas fused gather+attention kernel when the gate
        says so (ops/paged_attn_pallas.use_paged_kernel — TPU targets,
        or forced via ServeConfig.paged_kernel), else the XLA reference
        (materialized `paged_panel` + `_decode_attention` /
        `_span_attention`).  q (S, Hq, K1, Dh); span_kv = (sk, sv) span
        K/V switches to the span-verify mask."""
        from ..ops.paged_attn_pallas import paged_attention, use_paged_kernel
        if use_paged_kernel():
            return paged_attention(q, view, page, l, span_kv=span_kv)
        from ..serving.pool import paged_panel
        ck, cv = paged_panel(view, l, page, self.config.compute_dtype)
        if span_kv is None:
            return self._decode_attention(q, ck, cv, page.pos)
        sk, sv = span_kv
        return self._span_attention(q, ck, cv, sk, sv, page.pos)

    def _paged_attn_decode(self, x, bp, view, l, page):
        """Attention half of one paged decode step.  x: (S, 1, D); view:
        serving.pool.KVPoolView (the pool arrays, riding the layer-scan
        carry so writes alias); page: serving.pool.PageRef (block tables
        + per-slot write coordinates, loop-invariant)."""
        c = self.config
        s = x.shape[0]
        h = layernorm(x, bp["ln_1.w"], bp["ln_1.b"])
        qkv = linear(h, self._bw(bp, "attn.qkv.w"), bp.get("attn.qkv.b"))
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads1(z):
            return z.reshape(s, 1, c.n_head, c.head_dim).swapaxes(1, 2)

        from ..serving.pool import paged_append
        view = paged_append(
            view, heads1(k)[:, :, 0], heads1(v)[:, :, 0], l, page
        )
        y = self._paged_attention(heads1(q), view, l, page)
        y = y.swapaxes(1, 2).reshape(s, 1, c.n_embd)
        y = linear(y, self._bw(bp, "attn.proj.w"), bp.get("attn.proj.b"))
        return x + y, view

    def _paged_block_decode(self, x, bp, view, l, page):
        """One block, one token per slot, cache in the paged pool."""
        x, view = self._paged_attn_decode(x, bp, view, l, page)
        return self._mlp_decode(x, bp), view

    def paged_decode(self, stacked, x, view, page):
        """Layer loop for one paged decode token — the pool view rides
        the CARRY (like `_decode_blocks`' contiguous caches) so each
        layer's block write aliases the pool instead of restacking it."""
        n_layer = jax.tree.leaves(stacked)[0].shape[0]

        def body(carry, l):
            x, view = carry
            bp = jax.tree.map(
                lambda t: jax.lax.dynamic_index_in_dim(
                    t, l, 0, keepdims=False), stacked)
            x, view = self._paged_block_decode(x, bp, view, l, page)
            return (x, view), None

        (x, view), _ = jax.lax.scan(
            body, (x, view), jnp.arange(n_layer),
            unroll=self.config.scan_unroll)
        return x, view

    # -- speculative verification (serving/spec.py) ------------------------
    #
    # One target pass scores a whole DRAFT SPAN per slot — the committed
    # head token plus up to K drafter proposals at positions
    # pos..pos+K — instead of one token per tick.  The span's K/V never
    # touch the pool here: the committed prefix is read through the
    # block tables (positions < pos), the span attends to itself through
    # a windowed causal mask, and serving/pool.paged_append_span commits
    # only the ACCEPTED prefix afterwards (rejected-draft K/V route to
    # scratch).  The attention math is `_decode_attention` extended to
    # K1 query positions; everything else reuses the paged machinery.

    def _embed_decode_span(self, params, toks, positions):
        """(S, K1) tokens at (S, K1) absolute positions -> (S, K1, D)
        compute-dtype activations (the span analogue of
        `_embed_decode`'s vector-position path)."""
        x = self.embed_tokens(params, toks)
        wp = params["wpe"][positions]  # (S, K1, D), OOB rows clamped
        return x + wp.astype(x.dtype)

    def _span_attention(self, q, ck, cv, sk, sv, pos0):
        """Windowed-causal attention over committed cache + draft span.
        q: (S, Hq, K1, Dh) span queries; ck/cv: (S, KVH, T, Dh) pool
        panels holding the COMMITTED prefix (positions < pos0 valid);
        sk/sv: (S, KVH, K1, Dh) the span's own K/V (offset j at absolute
        position pos0+j).  Query j sees pool positions < pos0[s] plus
        span offsets <= j — exactly the causal mask of positions
        <= pos0+j, split across the two sources.  GQA groups query heads
        per KV head like `_decode_attention`; scores/softmax in f32."""
        s, hq, k1, dh = q.shape
        hkv = ck.shape[1]
        t = ck.shape[2]
        scale = 1.0 / math.sqrt(dh)
        out_dtype = q.dtype
        q = q.astype(ck.dtype)
        kf = jnp.concatenate([ck, sk.astype(ck.dtype)], axis=2)
        vf = jnp.concatenate([cv, sv.astype(cv.dtype)], axis=2)
        pool_mask = jnp.broadcast_to(
            (jnp.arange(t)[None, None, :] < pos0[:, None, None])[:, None],
            (s, 1, k1, t),
        )
        span_mask = jnp.broadcast_to(
            jnp.tril(jnp.ones((k1, k1), bool))[None, None], (s, 1, k1, k1)
        )
        mask = jnp.concatenate([pool_mask, span_mask], axis=-1)
        if hq != hkv:
            g = hq // hkv
            att = jnp.einsum(
                "skgqd,sktd->skgqt", q.reshape(s, hkv, g, k1, dh), kf,
                preferred_element_type=jnp.float32) * scale
            att = jnp.where(mask[:, :, None], att, -jnp.inf)
            att = jax.nn.softmax(att, axis=-1)
            y = jnp.einsum("skgqt,sktd->skgqd", att.astype(vf.dtype), vf,
                           preferred_element_type=jnp.float32)
            y = y.reshape(s, hq, k1, dh)
        else:
            att = jnp.einsum("shqd,shtd->shqt", q, kf,
                             preferred_element_type=jnp.float32) * scale
            att = jnp.where(mask, att, -jnp.inf)
            att = jax.nn.softmax(att, axis=-1)
            y = jnp.einsum("shqt,shtd->shqd", att.astype(vf.dtype), vf,
                           preferred_element_type=jnp.float32)
        return y.astype(out_dtype)

    def _paged_verify_attn(self, x, bp, view, l, page):
        """Attention half of one verify step: x (S, K1, D); the pool
        view is READ-ONLY (committed panel via the block tables) — the
        span's K/V return as this layer's scan ys for the post-
        acceptance commit."""
        c = self.config
        s, k1, _ = x.shape
        h = layernorm(x, bp["ln_1.w"], bp["ln_1.b"])
        qkv = linear(h, self._bw(bp, "attn.qkv.w"), bp.get("attn.qkv.b"))
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):
            return z.reshape(s, k1, c.n_head, c.head_dim).swapaxes(1, 2)

        kh, vh = heads(k), heads(v)
        y = self._paged_attention(heads(q), view, l, page,
                                  span_kv=(kh, vh))
        y = y.swapaxes(1, 2).reshape(s, k1, c.n_embd)
        y = linear(y, self._bw(bp, "attn.proj.w"), bp.get("attn.proj.b"))
        return x + y, (kh, vh)

    def _paged_verify_block(self, x, bp, view, l, page):
        x, kv = self._paged_verify_attn(x, bp, view, l, page)
        return self._mlp_decode(x, bp), kv

    def paged_verify(self, stacked, x, view, page):
        """Layer loop for one speculative verify: x (S, K1, D) span
        activations.  The view is never written (it rides the closure,
        not the carry); each layer's span K/V stack as scan ys —
        (L, S, KVH, K1, Dh) per side — for `paged_append_span` to commit
        the accepted prefix."""
        n_layer = jax.tree.leaves(stacked)[0].shape[0]

        def body(x, l):
            bp = jax.tree.map(
                lambda t: jax.lax.dynamic_index_in_dim(
                    t, l, 0, keepdims=False), stacked)
            x, kv = self._paged_verify_block(x, bp, view, l, page)
            return x, kv

        x, (sks, svs) = jax.lax.scan(
            body, x, jnp.arange(n_layer),
            unroll=self.config.scan_unroll)
        return x, sks, svs

    def head_span(self, params, x):
        """Final norm + lm_head at EVERY position of x (S, K1, D) ->
        (S, K1, V) f32 — the verify step needs the target distribution
        at all K1 span positions, not just the last (the `position`
        slice `head` takes on the single-token path)."""
        x = self.final_norm(params, x)
        return linear(x, self._lm_head_w(params), None).astype(jnp.float32)

    def paged_prefill(self, params, idx, last_pos, block_ids, view,
                      block_tokens: int, stacked=None):
        """Prompt pass for ONE request into the paged pool: idx (1, P)
        bucket-padded prompt, last_pos (traced) the true last prompt
        position, block_ids (P/block_tokens,) the physical blocks this
        request owns (padding-bucket tail entries point at the scratch
        block).  Returns (last-position logits (1, V) f32, view with the
        prompt's K/V scattered).  Reuses the training forward via the
        `return_kv` prefill hook, so family overrides (Llama RoPE/GQA)
        inherit it.  Pass the precomputed `stacked` compute-dtype tree
        when params are frozen (the serving engine does) — recomputing
        it per admission re-reads the full master param tree every
        prefill."""
        x = self.embed(params, idx)
        if stacked is None:
            stacked = self.stacked_compute_params(params)
        x, (ks, vs) = jax.lax.scan(self._prefill_body, x, stacked,
                                   unroll=self.config.scan_unroll)
        from ..serving.pool import paged_scatter
        view = paged_scatter(view, ks, vs, block_ids, block_tokens)
        return self.head(params, x, position=last_pos)[:, 0], view

    def embed_tokens(self, params, idx):
        """wte gather (+ optional row-norm cap) -> (B, T, D) compute dtype.
        Shared across families; raises on over-length sequences."""
        c = self.config
        t = idx.shape[1]
        if t > c.block_size:
            raise ValueError(
                f"sequence length {t} > block_size {c.block_size}"
            )  # reference asserts the same (model.py:142)
        tok = embedding(idx, params["wte"])
        if c.wte_max_norm is not None:
            # cap the GATHERED rows, not the whole (vocab, d) table — same
            # values (renorm is row-wise), but O(B*T*d) instead of
            # O(vocab*d) per forward (and per remat re-forward)
            from ..ops.embedding import renorm_weight
            tok = renorm_weight(tok, c.wte_max_norm)
        return tok.astype(c.compute_dtype)

    @staticmethod
    def _constrain_activations(x, pctx):
        if pctx is not None and pctx.is_multi_device:
            from jax.sharding import NamedSharding, PartitionSpec as P
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(
                    pctx.mesh, P(pctx.data_axis, pctx.seq_axis, None)
                ),
            )
        return x

    def embed(self, params, idx, pctx=None):
        """Token + position embedding -> (B, T, D) in compute dtype."""
        t = idx.shape[1]
        tok = self.embed_tokens(params, idx)
        pos = params["wpe"][:t].astype(tok.dtype)
        return self._constrain_activations(tok + pos[None], pctx)

    def _quant_eligible(self, name: str, v) -> bool:
        """Which stacked leaves the fp8 gather applies to: the block matmul
        weights (ndim >= 3 rules out layernorm w/b and all biases)."""
        return (self.config.gather_quant == "fp8"
                and name.endswith(".w") and v.ndim >= 3)

    def stacked_compute_params(self, params):
        """The per-block scan xs: "h.*" tensors cast to compute dtype ONCE
        per step — per-layer casts inside the scan would re-read the float32
        masters three times per step (fwd, remat re-fwd, bwd).  Under ZeRO-3
        this also halves the bytes each per-layer all-gather moves.

        With config.gather_quant="fp8", eligible weights become
        float8_e4m3 + a per-output-channel f32 scale (key + "#scale") —
        consumed through `_bw`, which dequantizes after the gather.  The
        scale is STOP-GRADIENTED (straight-through estimator): the exact
        vjp of the absmax/quotient round trip is quantization-sawtooth
        noise, and carrying it cost ~4.6 MB/step of scale-cotangent
        all-reduce on the TPU-partitioned HLO (round-5 measurement,
        PROFILE.md finding 5) — with STE the weight cotangent passes
        straight through the dequant multiply and the scale moves no
        backward bytes."""
        cd = self.config.compute_dtype
        out = {}
        for k, v in params.items():
            if not k.startswith("h."):
                continue
            name = k[len("h."):]
            if self._quant_eligible(name, v):
                # per-(layer, out-channel) absmax scale; e4m3 max = 448
                s = jnp.max(
                    jnp.abs(v.astype(jnp.float32)),
                    axis=tuple(range(1, v.ndim - 1)), keepdims=True,
                ) / 448.0 + 1e-12
                s = jax.lax.stop_gradient(s)
                out[name] = (v / s).astype(jnp.float8_e4m3fn)
                out[name + "#scale"] = s.astype(jnp.float32)
            else:
                out[name] = v.astype(cd)
        return out

    def _bw(self, bp, name: str, pctx=None):
        """Block weight from the stacked tree, dequantized when the fp8
        gather stacked it as (e4m3, scale).

        The sharding constraint pins the PRE-dequant f8 tensor to its
        gathered layout (tp/ep placements, ZeRO data axis replicated) so
        GSPMD's per-layer all-gather moves f8 bytes; without it the
        partitioner computes the dequant multiply shard-side and gathers
        full precision (observed in the compiled HLO).  Skipped inside the
        pipeline's manual region, where constraints cannot name manual
        axes."""
        w = bp[name]
        s = bp.get(name + "#scale")
        if s is None:
            return w
        if (pctx is not None and pctx.is_multi_device
                and not pctx.pipe_parallel
                and pctx.stacked_specs is not None
                and name in pctx.stacked_specs):
            from jax.sharding import NamedSharding
            w = jax.lax.with_sharding_constraint(
                w, NamedSharding(pctx.mesh, pctx.stacked_specs[name])
            )
        cd = self.config.compute_dtype
        return w.astype(cd) * s.astype(cd)

    def remat_policy(self):
        return {
            "nothing": jax.checkpoint_policies.nothing_saveable,
            "dots": jax.checkpoint_policies.dots_saveable,
            "dots_no_batch":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "all": jax.checkpoint_policies.everything_saveable,
        }[self.config.remat_policy]

    def _dropout_setup(self, stacked, x, rng):
        """Embedding dropout on `x` + one PRNG key per layer into the
        stacked scan tree (consumed by `_block` via bp["dropout_rng"]).
        No-op (train==eval) when rng is None or config.dropout == 0.
        Shared by every model family's apply()."""
        c = self.config
        if rng is None or not c.dropout:
            return stacked, x
        keys = jax.random.split(rng, c.n_layer + 1)
        x = _dropout(x, keys[0], c.dropout)
        return dict(stacked, dropout_rng=keys[1:]), x

    def block_fn(self, pctx=None):
        """(x, block_params) -> x, with the configured remat policy applied.
        A "health_probe" row in bp (engine telemetry layers mode) taps the
        block output through the per-layer health probe — here rather than
        in _block so LlamaModel's _block override inherits it."""
        def block(x, bp):
            y = self._block(x, bp, pctx)
            if "health_probe" in bp:
                from ..parallel.schedule import layer_health_tap
                y = layer_health_tap(y, bp["health_probe"])
            return y

        if self.config.remat:
            block = jax.checkpoint(block, policy=self.remat_policy())
        return block

    def final_norm(self, params, x):
        """Pre-head normalization — the one hook model families override
        (LlamaModel swaps in rmsnorm); the head/loss policy below stays in
        exactly one place."""
        cd = self.config.compute_dtype
        return layernorm(
            x, params["ln_f.w"].astype(cd), params["ln_f.b"].astype(cd)
        )

    def _lm_head_w(self, params):
        """(d, vocab) projection weight — wte.T when tied (the transpose
        folds into the matmul's dimension numbers, no copy)."""
        c = self.config
        w = params["wte"].T if c.tie_weights else params["lm_head.w"]
        return w.astype(c.compute_dtype)

    def head(self, params, x, targets: Optional[jax.Array] = None,
             pctx=None, position=None):
        """Final norm + lm_head (+ loss when targets given)."""
        c = self.config
        x = self.final_norm(params, x)
        w = self._lm_head_w(params)

        if targets is not None:
            # ONE shared predicate (effective_xent_impl) decides the head
            # implementation for both this gate and bench.py's A/B record
            impl = effective_xent_impl(
                c,
                multi_device=pctx is not None and pctx.is_multi_device,
                seq_sharded=pctx is not None and pctx.seq_parallel,
                tokens=x.shape[0] * x.shape[1],
            )
            if impl == "pallas":
                # single-device only for now: the custom call would
                # force GSPMD to gather the vocab-sharded w under tp
                from ..ops.xent_pallas import pallas_fused_xent
                return pallas_fused_xent(x, w, targets)
            if impl == "chunked":
                from ..ops.softmax_xent import fused_linear_xent
                return fused_linear_xent(x, w, targets)
            logits = linear(x, w, None)
            return softmax_cross_entropy(logits, targets)
        # inference path: one position only (cheap lm_head) — `position`
        # (static or traced int) selects it, default the last
        if position is None:
            x = x[:, -1:]
        else:
            x = jax.lax.dynamic_slice_in_dim(x, position, 1, axis=1)
        logits = linear(x, w, None)
        return logits.astype(jnp.float32)

    def apply(self, params, idx, targets: Optional[jax.Array] = None,
              pctx=None, position=None, rng=None, sched=None):
        """Forward pass.  Returns mean loss if targets given, else logits —
        same contract as reference GPT2Model.forward (model.py:139-157).

        `pctx` (ParallelContext) makes the forward mesh-aware: activations
        shard (batch over "data", tokens over "seq" when sequence-parallel)
        and attention dispatches to the sharded kernels.

        `rng` (train-time only) enables dropout when config.dropout > 0:
        one key per layer rides the stacked scan tree, so the same masks
        are recomputed bit-exactly by the remat backward.

        `sched` is THE scheduler seam (parallel/schedule.py): an executor
        with `.scan(block, stacked, x, unroll=)` that replaces the plain
        layer scan — the probe row rider (ProbeScan), the bucketed
        grad-release tap (GradBucketTap), or the prefetched weight-gather
        scan (GatherPrefetchScan).  The engine builds it from the
        validated slot Schedule; None (default) keeps the exact
        single-scan program.  (The composed multi-slot lowering drives
        its own scan via schedule.composed_step and never passes
        sched= here.)"""
        x = self.embed(params, idx, pctx)
        stacked = self.stacked_compute_params(params)
        stacked, x = self._dropout_setup(stacked, x, rng)
        block = self.block_fn(pctx)

        if sched is not None:
            if pctx is not None and pctx.pipe_parallel:
                raise ValueError(
                    "sched= (the in-scan collective scheduler) does not "
                    "compose with the pipeline forward"
                )
            x = sched.scan(block, stacked, x,
                           unroll=self.config.scan_unroll)
            return self.head(params, x, targets, pctx, position)

        if pctx is not None and pctx.pipe_parallel:
            # GPipe-style SPMD pipeline over the "pipe" axis: each stage owns
            # n_layer/S stacked layers, microbatches hop stage->stage via
            # ppermute (parallel/pipeline.py; absent from the reference).
            from ..parallel.pipeline import spmd_pipeline
            x = spmd_pipeline(
                block, stacked, x,
                mesh=pctx.mesh, pipe_axis=pctx.pipe_axis,
                data_axis=pctx.data_axis,
                microbatches=pctx.pipe_microbatches or None,
                seq_axis=pctx.seq_axis,
            )
        else:
            def scan_body(x, bp):
                return block(x, bp), None

            x, _ = jax.lax.scan(scan_body, x, stacked,
                                unroll=self.config.scan_unroll)
        return self.head(params, x, targets, pctx, position)

    def __call__(self, params, idx, targets=None, pctx=None, rng=None):
        return self.apply(params, idx, targets, pctx, rng=rng)

    # 1F1B needs the loss INSIDE the pipeline (per-microbatch head at the
    # last stage), so it cannot ride `apply` + autodiff like GPipe does;
    # engines with pipeline_schedule="1f1b" call this instead.
    supports_1f1b = True

    def _pipeline_1f1b_block(self, pctx):
        """(block_fn, aux_weight, with_aux) for the 1F1B schedule — the
        hook MoEGPT overrides to thread its load-balance aux loss."""
        return self.block_fn(pctx), 0.0, False

    def head_param_names(self):
        """Params the head (final norm + lm_head) differentiates — the
        1F1B pipeline accumulates their grads at the last stage."""
        c = self.config
        # filtered against the actual param dict at use (llama has no ln_f.b)
        return ["ln_f.w", "ln_f.b",
                "wte" if c.tie_weights else "lm_head.w"]

    def loss_and_grad_1f1b(self, params, idx, targets, pctx,
                           loss_seed=1.0, rng=None):
        """(scaled loss, grads) via the 1F1B pipeline schedule
        (parallel/pipeline.py::spmd_pipeline_1f1b) — same contract as
        `jax.value_and_grad(lambda p: loss_seed * apply(p, ...))(params)`
        but with in-flight activations bounded at O(stages) instead of
        O(microbatches).  The pipeline hands back cotangents at its three
        seams (stacked block params, head params, embedded activations);
        explicit vjps push them to the master params and the pieces sum.

        `rng` enables dropout: per-layer keys ride the pipeline outside
        the differentiated args, folded per microbatch (independent masks
        per microbatch, bit-exact backward recompute); the embedding
        dropout joins the embed vjp here."""
        # gather_quant="fp8" composes: the f8 stacked leaves' cotangents
        # accumulate in f32 across ticks and cast to e4m3 once at the
        # pipeline boundary — the same one-crossing precision profile as
        # the autodiff (GPipe/plain) fp8 path, loss-curve validated there
        if pctx is None or pctx.pipe_axis is None:
            raise ValueError("loss_and_grad_1f1b needs a pipeline pctx")
        from ..parallel.pipeline import spmd_pipeline_1f1b

        block, aux_w, with_aux = self._pipeline_1f1b_block(pctx)
        drop_keys = None
        c = self.config
        if rng is not None and c.dropout:
            keys = jax.random.split(rng, c.n_layer + 1)
            drop_keys = keys[1:]

            def embed_fn(p):
                return _dropout(self.embed(p, idx, pctx), keys[0],
                                c.dropout)
        else:
            def embed_fn(p):
                return self.embed(p, idx, pctx)
        x, embed_vjp = jax.vjp(embed_fn, params)
        stacked, stacked_vjp = jax.vjp(self.stacked_compute_params, params)
        head_names = [n for n in self.head_param_names() if n in params]
        head_params = {n: params[n] for n in head_names}

        def head_fn(hp, y, tg):
            # one-hot CE, not the gather/fused paths: this head runs inside
            # the pipeline's partial-manual region where the take_along_axis
            # gather on (possibly vocab-sharded) logits CHECK-crashes the
            # SPMD partitioner (ops/softmax_xent.py::softmax_cross_entropy_
            # onehot); per-microbatch logits keep the memory bounded anyway
            from ..ops.softmax_xent import softmax_cross_entropy_onehot
            from ..ops.linear import linear
            h = self.final_norm(hp, y)
            return softmax_cross_entropy_onehot(
                linear(h, self._lm_head_w(hp), None), tg
            )

        loss, dstacked, dhead, dx = spmd_pipeline_1f1b(
            block, head_fn, stacked, head_params,
            x, targets,
            mesh=pctx.mesh,
            pipe_axis=pctx.pipe_axis or "pipe",
            data_axis=pctx.data_axis,
            microbatches=pctx.pipe_microbatches or None,
            loss_seed=loss_seed,
            with_aux=with_aux, aux_weight=aux_w,
            rng_stacked=drop_keys,
            seq_axis=pctx.seq_axis,
        )
        g_embed = embed_vjp(dx.astype(x.dtype))[0]
        g_stack = stacked_vjp(dstacked)[0]
        grads = jax.tree.map(
            lambda a, b: a.astype(jnp.float32) + b.astype(jnp.float32),
            g_embed, g_stack,
        )
        for n, g in dhead.items():
            grads[n] = grads[n] + g.astype(jnp.float32)
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
        return loss, grads

    # Table-driven schedules (interleaved virtual stages / zero-bubble
    # B/W split) reuse the 1F1B seams but need an aux-free block: MoEGPT
    # opts out (its load-balance aux would need to ride every F *and* be
    # replayed in W's re-linearization).
    supports_pipe_table = True

    def loss_and_grad_pipe(self, params, idx, targets, pctx, program,
                           loss_seed=1.0, rng=None):
        """(scaled loss, grads) via a static pipeline tick table
        (parallel/pipeline.py::spmd_pipeline_table) — interleaved and
        zero-bubble schedules.  Same contract and seam composition as
        `loss_and_grad_1f1b`: the pipeline hands back cotangents at the
        stacked/head/embed seams and explicit vjps push them to the
        master params."""
        if pctx is None or pctx.pipe_axis is None:
            raise ValueError("loss_and_grad_pipe needs a pipeline pctx")
        from ..parallel.pipeline import spmd_pipeline_table

        block, aux_w, with_aux = self._pipeline_1f1b_block(pctx)
        if with_aux or aux_w:
            raise ValueError("table schedules do not thread aux losses; "
                             "use pipeline_schedule='1f1b'")
        drop_keys = None
        c = self.config
        if rng is not None and c.dropout:
            keys = jax.random.split(rng, c.n_layer + 1)
            drop_keys = keys[1:]

            def embed_fn(p):
                return _dropout(self.embed(p, idx, pctx), keys[0],
                                c.dropout)
        else:
            def embed_fn(p):
                return self.embed(p, idx, pctx)
        x, embed_vjp = jax.vjp(embed_fn, params)
        stacked, stacked_vjp = jax.vjp(self.stacked_compute_params, params)
        head_names = [n for n in self.head_param_names() if n in params]
        head_params = {n: params[n] for n in head_names}

        def head_fn(hp, y, tg):
            # one-hot CE for the same partial-manual reason as 1F1B
            from ..ops.softmax_xent import softmax_cross_entropy_onehot
            from ..ops.linear import linear
            h = self.final_norm(hp, y)
            return softmax_cross_entropy_onehot(
                linear(h, self._lm_head_w(hp), None), tg
            )

        loss, dstacked, dhead, dx = spmd_pipeline_table(
            block, head_fn, stacked, head_params,
            x, targets,
            mesh=pctx.mesh,
            program=program,
            pipe_axis=pctx.pipe_axis or "pipe",
            data_axis=pctx.data_axis,
            loss_seed=loss_seed,
            rng_stacked=drop_keys,
        )
        g_embed = embed_vjp(dx.astype(x.dtype))[0]
        g_stack = stacked_vjp(dstacked)[0]
        grads = jax.tree.map(
            lambda a, b: a.astype(jnp.float32) + b.astype(jnp.float32),
            g_embed, g_stack,
        )
        for n, g in dhead.items():
            grads[n] = grads[n] + g.astype(jnp.float32)
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
        return loss, grads

    def generate(self, params, idx, max_new_tokens: int, *,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 key=None, use_cache: bool = True):
        """Autoregressive sampling: (B, T0) prompt -> (B, T0+max_new_tokens).

        The reference has no sampling loop (its model only trains); this is
        the capability users expect from a GPT training framework.  TPU-first
        shape discipline: the token buffer is a FIXED-shape array updated in
        place and the decode loop is a `lax.fori_loop` inside one cached jit
        (keyed on shapes + sampling settings, so repeat calls don't
        retrace).  use_cache=True (default) decodes with a per-layer KV
        cache: prompt prefill + one (B, 1, D) pass per token, O(L*T) not
        O(L*T^2) — greedy outputs are bit-checked equal to the uncached
        full-forward path (tests/test_model.py; for MoE the equality holds
        whenever expert capacity overflows in neither path — the
        full-sequence path's static capacity can drop tokens the drop-free
        decode keeps, models/moe.py).  temperature=0 gives
        greedy decoding and needs no key; stochastic sampling requires an
        explicit PRNG key (no silent fixed seed).
        """
        c = self.config
        b, t0 = idx.shape
        if t0 + max_new_tokens > c.block_size:
            raise ValueError(
                f"prompt {t0} + new {max_new_tokens} tokens > "
                f"block_size {c.block_size}"
            )
        if key is None:
            if temperature != 0.0:
                raise ValueError(
                    "stochastic sampling (temperature != 0) requires an "
                    "explicit PRNG key; pass key=jax.random.PRNGKey(...) "
                    "or use temperature=0.0 for greedy decoding"
                )
            key = jax.random.PRNGKey(0)  # unused by the greedy path

        cache_key = (b, t0, max_new_tokens, temperature, top_k, use_cache)
        fn = self._generate_cache.get(cache_key)
        if fn is None:
            # bounded LRU: each entry pins a jitted executable on the model
            # instance; unbounded growth across distinct shape/sampling
            # combinations would leak compiled programs (ADVICE r1)
            if len(self._generate_cache) >= 32:
                self._generate_cache.pop(next(iter(self._generate_cache)))
            impl = (self._generate_impl_cached if use_cache
                    else self._generate_impl)
            fn = jax.jit(
                partial(
                    impl, t0=t0,
                    max_new_tokens=max_new_tokens,
                    temperature=temperature, top_k=top_k,
                )
            )
            self._generate_cache[cache_key] = fn
        else:
            self._generate_cache[cache_key] = self._generate_cache.pop(
                cache_key
            )  # mark most-recently-used
        return fn(params, idx, key)

    def _generate_impl(self, params, idx, key, *, t0, max_new_tokens,
                       temperature, top_k):
        c = self.config
        b = idx.shape[0]
        buf = jnp.zeros((b, c.block_size), jnp.int32)
        buf = jax.lax.dynamic_update_slice(buf, idx.astype(jnp.int32), (0, 0))

        def body(i, carry):
            buf, key = carry
            logit = self.apply(params, buf, position=i - 1)[:, 0]  # (B, V)
            key, sub = jax.random.split(key)
            nxt = self._sample(logit, sub, temperature, top_k)
            buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, i))
            return buf, key

        buf, _ = jax.lax.fori_loop(t0, t0 + max_new_tokens, body, (buf, key))
        return buf[:, : t0 + max_new_tokens]
