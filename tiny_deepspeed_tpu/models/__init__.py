# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Model zoo: GPT-2 family (parity with reference example/model.py) plus the
MoE and Llama families (beyond the reference, SURVEY §2.20) — all built on
the same op layer, stacked-block scan, and engine surface."""

from .gpt2 import GPTConfig, GPT2Model, GPT2_PRESETS
from .moe import MoEConfig, MoEGPT, MOE_PRESETS
from .llama import LlamaConfig, LlamaModel, LLAMA_PRESETS

# one flat preset namespace across families (tiny / gpt2-* / llama-* / moe-*)
ALL_PRESETS = {**GPT2_PRESETS, **LLAMA_PRESETS, **MOE_PRESETS}


def build_model(name_or_cfg):
    """Model instance from a preset name or config; the family is inferred
    from the config type (single construction point for every entry
    surface: examples, bench, generate)."""
    cfg = (ALL_PRESETS[name_or_cfg] if isinstance(name_or_cfg, str)
           else name_or_cfg)
    if isinstance(cfg, LlamaConfig):
        return LlamaModel(cfg)
    if isinstance(cfg, MoEConfig):
        return MoEGPT(cfg)
    return GPT2Model(cfg)


__all__ = [
    "GPTConfig", "GPT2Model", "GPT2_PRESETS",
    "MoEConfig", "MoEGPT", "MOE_PRESETS",
    "LlamaConfig", "LlamaModel", "LLAMA_PRESETS",
    "ALL_PRESETS", "build_model",
]
