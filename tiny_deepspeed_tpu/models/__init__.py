"""Model zoo: GPT-2 family (parity with reference example/model.py)."""

from .gpt2 import GPTConfig, GPT2Model, GPT2_PRESETS

__all__ = ["GPTConfig", "GPT2Model", "GPT2_PRESETS"]
