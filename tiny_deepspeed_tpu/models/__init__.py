# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Model zoo: GPT-2 family (parity with reference example/model.py) plus the
MoE family (expert parallelism — beyond the reference, SURVEY §2.20)."""

from .gpt2 import GPTConfig, GPT2Model, GPT2_PRESETS
from .moe import MoEConfig, MoEGPT

__all__ = ["GPTConfig", "GPT2Model", "GPT2_PRESETS", "MoEConfig", "MoEGPT"]
