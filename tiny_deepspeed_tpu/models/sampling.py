# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""The ONE sampling core: final-position logits -> next tokens.

Both decode surfaces consume this — `GPT2Model.generate`'s fori-loop body
and the serving tier's continuous-batching decode step
(serving/engine.py) — so a sampling change (a new top-p knob, a
temperature fix) lands in every path at once instead of drifting between
the one-shot script and the server.  Kept dependency-free (jax only) so
`tiny_deepspeed_tpu.serving` can import it without touching model code.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(logit, key, temperature: float,
                  top_k: Optional[int] = None):
    """(B, V) float32 logits -> (B,) int32 next tokens.

    temperature == 0.0 is greedy argmax (key unused); otherwise
    categorical over logits/temperature, restricted to the top_k logits
    when top_k is given.  `temperature`/`top_k` are static (compiled
    into the program) — both call sites key their jit caches on them."""
    logit = _top_k_filter(logit, top_k)
    if temperature == 0.0:
        return jnp.argmax(logit, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logit / temperature
    ).astype(jnp.int32)


def request_position_key(base_key, seed, position):
    """The serving tier's deterministic sampling key: fold (per-request
    seed, token position) into the engine's base key.  Because the key
    depends ONLY on which request and which output position — never on
    the scheduler tick, batch composition, or how many times the request
    was preempted/restarted — a temperature > 0 request resumed from
    prompt + produced prefix re-samples the SAME continuation the
    uninterrupted run would have (categorical is Gumbel argmax, so it
    shares greedy argmax's robustness to the prefill-vs-decode numeric
    path difference).  `seed`/`position` may be traced scalars."""
    return jax.random.fold_in(jax.random.fold_in(base_key, seed), position)


def sample_logits_at(logit, base_key, seed, position, temperature: float,
                     top_k: Optional[int] = None):
    """(B, V) logits sampled under the (seed, position) request key —
    the ONE dispatch both serving surfaces ride (prefill directly,
    decode row-wise through `sample_logits_per_slot`), so the greedy
    short-circuit and the key derivation can never drift between the
    two paths the determinism guarantee compares."""
    if temperature == 0.0:
        return sample_logits(logit, None, 0.0, top_k)
    key = request_position_key(base_key, seed, position)
    return sample_logits(logit, key, temperature, top_k)


def sample_logits_per_slot(logit, base_key, seeds, positions,
                           temperature: float,
                           top_k: Optional[int] = None):
    """Per-slot sampling for the serving decode step: row i of the
    (S, V) logits samples under request_position_key(base_key, seeds[i],
    positions[i]).  Delegates row-wise to `sample_logits_at` via vmap;
    temperature == 0.0 short-circuits to the identical greedy argmax
    (keys never materialize — the compiled greedy program is
    unchanged)."""
    if temperature == 0.0:
        return sample_logits(logit, None, 0.0, top_k)

    def one(row, seed, pos):
        return sample_logits_at(row[None], base_key, seed, pos,
                                temperature, top_k)[0]

    return jax.vmap(one)(logit, seeds, positions)


def _top_k_filter(logit, top_k: Optional[int]):
    """-inf everything below each row's k-th logit — THE support
    restriction (`sample_logits` delegates here), on arbitrary leading
    axes."""
    if top_k is None:
        return logit
    kth = jax.lax.top_k(logit, top_k)[0][..., -1:]
    return jnp.where(logit < kth, -jnp.inf, logit)


def _accept_or_residual(p_row, prop, key):
    """ONE position's committed token under the speculative
    accept-or-residual rule: given the target's (V,) probability row
    `p_row`, the drafter's point-mass proposal `prop`, and the
    position's key, commit `prop` iff u < p(prop) (u ~ U[0,1) from
    `key`), else draw from the renormalized residual (p with `prop`
    zeroed) under fold_in(key, 1).  The marginal is exactly `p_row`
    either way.  This is the SINGLE implementation both commit sites
    ride — `spec_prefill_commit` and `spec_accept_per_slot`'s final
    token — because the determinism guarantee is precisely that the
    same (p, proposal, key) commits the same token no matter which
    program reaches the position first; two hand-rolled copies would
    make that invariant disciplinary instead of structural."""
    u = jax.random.uniform(key)
    pd = p_row[prop]
    onehot = jax.nn.one_hot(prop, p_row.shape[-1], dtype=jnp.bool_)
    r = jnp.where(onehot, 0.0, p_row)
    resid = jax.random.categorical(
        jax.random.fold_in(key, 1), jnp.log(r))
    return jnp.where(u < pd, prop, resid).astype(jnp.int32)


def spec_prefill_commit(logit, prop, base_key, seed, position,
                        temperature: float,
                        top_k: Optional[int] = None):
    """First-token commit for a SPECULATIVE engine's prefill: apply the
    SAME per-position accept-or-residual rule the verify core uses
    (`spec_accept_per_slot`), against the drafter's proposal `prop` for
    this position.  A spec engine must commit position i through ONE
    rule no matter which program reaches it first — a preemption
    re-admission lands position i on the prefill path while the
    undisturbed run committed it mid-verify, and mixing plain
    categorical sampling here with accept-or-residual there would break
    the temperature>0 determinism guarantee even though both draw from
    the exact target distribution.  Greedy short-circuits to the
    identical argmax (`prop` prunes out of the compiled program)."""
    if temperature == 0.0:
        return sample_logits(logit, None, 0.0, top_k)
    p = jax.nn.softmax(_top_k_filter(logit, top_k) / temperature,
                       axis=-1)  # (B, V), B == 1 (per-request prefill)
    key = request_position_key(base_key, seed, position)
    return _accept_or_residual(p[0], prop, key)[None]


def spec_accept_per_slot(logits, span, extra, base_key, seeds, nprod,
                         temperature: float,
                         top_k: Optional[int] = None):
    """Speculative-decoding acceptance core (Leviathan et al.,
    arXiv:2211.17192), for the serving verify step.

    `logits`: (S, K+1, V) f32 — the TARGET model scored at every span
    position; span offset j's logits are the target distribution for
    the token at output index nprod+j.  `span`: (S, K+1) int32 =
    [last committed token, d_1 .. d_K] — the drafter's K verifiable
    proposals behind the committed head; `extra`: (S,) int32 = the
    drafter's (K+1)-th proposal, consumed only by the bonus position's
    sampling rule (below).  Returns (accepted, final): `accepted` (S,)
    int32 in [0, K] is how many leading drafts commit; `final` (S,)
    int32 is the ONE extra committed token, so each verify commits
    accepted+1 tokens.

    temperature == 0.0 short-circuits to TOKEN EQUALITY against the
    target argmax (no keys materialize): the committed sequence is the
    target's greedy sequence regardless of what the drafter proposed,
    which is what makes greedy speculative output bit-identical to
    `generate`.

    temperature > 0 applies, at EVERY span position, one deterministic
    accept-or-residual rule for a point-mass proposal (both drafters
    propose deterministically, so q is a delta at d): with
    u ~ U[0,1) keyed by request_position_key(seed, output index),
    commit d iff u < p(d), else draw from the renormalized residual
    (p with d zeroed) under fold(key, 1).  The marginal is EXACTLY the
    target distribution either way (p(d) + (1-p(d)) * 0 for d;
    (1-p(d)) * p(x)/(1-p(d)) for x != d) — the rule is a
    reparameterization of sampling from p, which is why the BONUS
    position (all K drafts accepted) runs the same rule against
    `extra` instead of sampling p directly: as long as the drafter's
    proposal for a position is a pure function of the committed prefix
    (both drafters are autoregressively consistent), the committed
    token at output index i is the same whether i lands mid-span, at a
    rejection point, or at a bonus — so preemption, warm restart, and
    journal recovery replays (whose spans REALIGN against the
    undisturbed run's) still commit identical tokens."""
    k = span.shape[1] - 1
    if temperature == 0.0:
        tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S, K+1)
        match = (tgt[:, :k] == span[:, 1:]).astype(jnp.int32)
        acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        final = jnp.take_along_axis(tgt, acc[:, None], axis=1)[:, 0]
        return acc.astype(jnp.int32), final

    filt = _top_k_filter(logits, top_k) / temperature
    p = jax.nn.softmax(filt, axis=-1)  # (S, K+1, V) f32
    # the proposal at span offset j is span[j+1] (j < K) or extra (K)
    props = jnp.concatenate(
        [span[:, 1:], extra[:, None]], axis=1).astype(jnp.int32)
    pd = jnp.take_along_axis(
        p[:, :k], props[:, :k, None], axis=-1)[..., 0]  # (S, K)
    positions = nprod[:, None] + jnp.arange(k)[None, :]

    def u_one(seed, pos):
        return jax.random.uniform(
            request_position_key(base_key, seed, pos))

    u = jax.vmap(jax.vmap(u_one, in_axes=(None, 0)))(
        seeds, positions)  # (S, K)
    acc = jnp.sum(
        jnp.cumprod((u < pd).astype(jnp.int32), axis=1), axis=1)
    # the final committed token at output index nprod+acc runs the ONE
    # accept-or-residual rule against the proposal there: a rejection
    # point re-fails its accept test (same key, same p(d)) and takes
    # the residual; the bonus position accepts or residual-draws
    # against `extra` — either way the committed token is the same
    # pure function of (prefix, seed, index) every replay computes
    d_at = jnp.take_along_axis(props, acc[:, None], axis=1)[:, 0]
    p_at = jnp.take_along_axis(
        p, acc[:, None, None], axis=1)[:, 0]  # (S, V)

    def final_one(seed, pos, row, prop):
        return _accept_or_residual(
            row, prop, request_position_key(base_key, seed, pos))

    final = jax.vmap(final_one)(seeds, nprod + acc, p_at, d_at)
    return acc.astype(jnp.int32), final
