# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""The ONE sampling core: final-position logits -> next tokens.

Both decode surfaces consume this — `GPT2Model.generate`'s fori-loop body
and the serving tier's continuous-batching decode step
(serving/engine.py) — so a sampling change (a new top-p knob, a
temperature fix) lands in every path at once instead of drifting between
the one-shot script and the server.  Kept dependency-free (jax only) so
`tiny_deepspeed_tpu.serving` can import it without touching model code.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(logit, key, temperature: float,
                  top_k: Optional[int] = None):
    """(B, V) float32 logits -> (B,) int32 next tokens.

    temperature == 0.0 is greedy argmax (key unused); otherwise
    categorical over logits/temperature, restricted to the top_k logits
    when top_k is given.  `temperature`/`top_k` are static (compiled
    into the program) — both call sites key their jit caches on them."""
    if top_k is not None:
        kth = jax.lax.top_k(logit, top_k)[0][:, -1:]
        logit = jnp.where(logit < kth, -jnp.inf, logit)
    if temperature == 0.0:
        return jnp.argmax(logit, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logit / temperature
    ).astype(jnp.int32)
