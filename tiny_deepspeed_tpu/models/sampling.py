# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""The ONE sampling core: final-position logits -> next tokens.

Both decode surfaces consume this — `GPT2Model.generate`'s fori-loop body
and the serving tier's continuous-batching decode step
(serving/engine.py) — so a sampling change (a new top-p knob, a
temperature fix) lands in every path at once instead of drifting between
the one-shot script and the server.  Kept dependency-free (jax only) so
`tiny_deepspeed_tpu.serving` can import it without touching model code.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(logit, key, temperature: float,
                  top_k: Optional[int] = None):
    """(B, V) float32 logits -> (B,) int32 next tokens.

    temperature == 0.0 is greedy argmax (key unused); otherwise
    categorical over logits/temperature, restricted to the top_k logits
    when top_k is given.  `temperature`/`top_k` are static (compiled
    into the program) — both call sites key their jit caches on them."""
    if top_k is not None:
        kth = jax.lax.top_k(logit, top_k)[0][:, -1:]
        logit = jnp.where(logit < kth, -jnp.inf, logit)
    if temperature == 0.0:
        return jnp.argmax(logit, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logit / temperature
    ).astype(jnp.int32)


def request_position_key(base_key, seed, position):
    """The serving tier's deterministic sampling key: fold (per-request
    seed, token position) into the engine's base key.  Because the key
    depends ONLY on which request and which output position — never on
    the scheduler tick, batch composition, or how many times the request
    was preempted/restarted — a temperature > 0 request resumed from
    prompt + produced prefix re-samples the SAME continuation the
    uninterrupted run would have (categorical is Gumbel argmax, so it
    shares greedy argmax's robustness to the prefill-vs-decode numeric
    path difference).  `seed`/`position` may be traced scalars."""
    return jax.random.fold_in(jax.random.fold_in(base_key, seed), position)


def sample_logits_at(logit, base_key, seed, position, temperature: float,
                     top_k: Optional[int] = None):
    """(B, V) logits sampled under the (seed, position) request key —
    the ONE dispatch both serving surfaces ride (prefill directly,
    decode row-wise through `sample_logits_per_slot`), so the greedy
    short-circuit and the key derivation can never drift between the
    two paths the determinism guarantee compares."""
    if temperature == 0.0:
        return sample_logits(logit, None, 0.0, top_k)
    key = request_position_key(base_key, seed, position)
    return sample_logits(logit, key, temperature, top_k)


def sample_logits_per_slot(logit, base_key, seeds, positions,
                           temperature: float,
                           top_k: Optional[int] = None):
    """Per-slot sampling for the serving decode step: row i of the
    (S, V) logits samples under request_position_key(base_key, seeds[i],
    positions[i]).  Delegates row-wise to `sample_logits_at` via vmap;
    temperature == 0.0 short-circuits to the identical greedy argmax
    (keys never materialize — the compiled greedy program is
    unchanged)."""
    if temperature == 0.0:
        return sample_logits(logit, None, 0.0, top_k)

    def one(row, seed, pos):
        return sample_logits_at(row[None], base_key, seed, pos,
                                temperature, top_k)[0]

    return jax.vmap(one)(logit, seeds, positions)
