# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Llama-family model: RMSNorm + RoPE + SwiGLU + grouped-query attention.

No reference counterpart (the reference's only model is the nanoGPT-style
GPT-2, reference example/model.py) — this is the second model family proving
the framework generalizes: it reuses the op layer (ops/linear, ops/rmsnorm,
ops/attention), the stacked-block `lax.scan`, every ZeRO stage, tensor/
sequence/pipeline parallelism, checkpointing, and `generate()` without any
engine changes.

TPU-first notes:
  * RoPE is computed in float32 and applied to q/k only; positions are
    GLOBAL indices — under seq x pipe (both axes manual in the pipeline
    region) the local shard offsets by axis_index(seq) * T_local.
  * GQA: n_kv_head <= n_head; K/V enter attention at kv_heads — the FA2
    kernel consumes them grouped (ops/flash_fa2.py indexes kv panels by
    query_head // group), so K/V HBM traffic stays at kv_heads; non-flash
    paths expand in ops/attention.py (free under GSPMD head sharding).
  * SwiGLU hidden defaults to the Llama convention round(8/3 * d) padded up
    to a multiple of 128 so the MXU tiles cleanly.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..ops import linear
from ..ops.rmsnorm import rmsnorm
from ..ops.attention import sharded_attention
from .gpt2 import GPTConfig, GPT2Model, _dropout


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


@dataclasses.dataclass(frozen=True)
class LlamaConfig(GPTConfig):
    """GPTConfig fields reused (block_size, vocab_size, n_layer, n_head,
    n_embd, attn_impl, dtypes, remat, fused_xent, dropout) + Llama knobs.

    The inherited `bias` field is IGNORED: the Llama architecture is
    bias-free by definition (every projection below passes bias=None).
    `dropout` works exactly as in GPT2Model (post-attention + post-MLP
    residual dropout + embedding dropout, keyed per step by the engine)."""

    n_kv_head: Optional[int] = None     # None -> n_head (MHA)
    rope_theta: float = 10000.0
    ffn_hidden: Optional[int] = None    # None -> round_up(8/3 * d, 128)

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head

    @property
    def ffn(self) -> int:
        return self.ffn_hidden or _round_up(int(8 * self.n_embd / 3), 128)


LLAMA_PRESETS: Dict[str, LlamaConfig] = {
    "llama-tiny": LlamaConfig(block_size=256, vocab_size=512, n_layer=2,
                              n_head=4, n_kv_head=2, n_embd=64,
                              compute_dtype=jnp.float32),
    "llama-160m": LlamaConfig(block_size=1024, vocab_size=50304, n_layer=12,
                              n_head=12, n_kv_head=4, n_embd=768),
    "llama-1b": LlamaConfig(block_size=2048, vocab_size=50304, n_layer=22,
                            n_head=32, n_kv_head=8, n_embd=2048),
}


def rope(x, positions, theta: float):
    """Rotary position embedding on (B, H, T, Dh); positions (T,) ints."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = theta ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )  # (half,)
    ang = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # (T, half)
    cos = jnp.cos(ang)[None, None]
    sin = jnp.sin(ang)[None, None]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def rope_at(x, positions, theta: float):
    """RoPE for a one-token-per-row batch: x (B, H, 1, Dh), positions (B,)
    — each row rotated at its OWN position (the serving tier's paged
    decode, where concurrent requests sit at different lengths).  The
    one-position special case of `rope_span` — delegating keeps the
    rotation math in a single body, so a row at position p gets
    bit-identical treatment on every path."""
    return rope_span(x, positions[:, None], theta)


def rope_span(x, positions, theta: float):
    """RoPE for a draft-span batch: x (S, H, K1, Dh), positions (S, K1)
    — row s's span position j rotated at positions[s, j] (the serving
    tier's speculative verify, where each slot's span starts at its own
    committed head).  Same elementwise math as `rope`/`rope_at`, so a
    token at absolute position p gets bit-identical treatment on every
    path."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs  # (S, K1, half)
    cos = jnp.cos(ang)[:, None]  # (S, 1, K1, half)
    sin = jnp.sin(ang)[:, None]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


class LlamaModel(GPT2Model):
    """Same functional contract as GPT2Model: init / apply / generate."""

    pipeline_capable = True
    # inherits apply() and with it the bucketed grad-release tap AND the
    # ZeRO-3 prefetched weight-gather scan — restated so a future apply()
    # override can't silently claim capabilities it dropped
    grad_bucket_capable = True
    gather_prefetch_capable = True
    layer_health_capable = True
    # paged decode: _paged_attn_decode below (RoPE at per-slot positions)
    paged_decode_capable = True

    def __init__(self, config: LlamaConfig):
        super().__init__(config)

    # -- params ------------------------------------------------------------

    def init(self, key) -> Dict[str, jax.Array]:
        c = self.config
        d, l, v = c.n_embd, c.n_layer, c.vocab_size
        hd = c.head_dim
        kvd = c.kv_heads * hd
        f = c.ffn
        std = 0.02
        pstd = std / math.sqrt(2 * l)
        keys = iter(jax.random.split(key, 12))

        def nrm(k, shape, s):
            return (jax.random.normal(k, shape, jnp.float32) * s).astype(
                c.param_dtype
            )

        params = {
            "wte": nrm(next(keys), (v, d), std),
            "h.ln_1.w": jnp.ones((l, d), c.param_dtype),
            "h.attn.q.w": nrm(next(keys), (l, d, d), std),
            "h.attn.k.w": nrm(next(keys), (l, d, kvd), std),
            "h.attn.v.w": nrm(next(keys), (l, d, kvd), std),
            "h.attn.o.w": nrm(next(keys), (l, d, d), pstd),
            "h.ln_2.w": jnp.ones((l, d), c.param_dtype),
            "h.mlp.gate.w": nrm(next(keys), (l, d, f), std),
            "h.mlp.up.w": nrm(next(keys), (l, d, f), std),
            "h.mlp.down.w": nrm(next(keys), (l, f, d), pstd),
            "ln_f.w": jnp.ones((d,), c.param_dtype),
            "lm_head.w": nrm(next(keys), (d, v), std),
        }
        if c.tie_weights:
            del params["lm_head.w"]
        return params

    def tp_rules(self) -> Dict[str, int]:
        """Column-parallel q/k/v/gate/up, row-parallel o/down, vocab-parallel
        lm_head (needs n_head % tp == 0 and kv_heads % tp == 0)."""
        return {
            "h.attn.q.w": 2,
            "h.attn.k.w": 2,
            "h.attn.v.w": 2,
            "h.attn.o.w": 1,
            "h.mlp.gate.w": 2,
            "h.mlp.up.w": 2,
            "h.mlp.down.w": 1,
            "lm_head.w": 1,
        }

    # -- forward -----------------------------------------------------------

    def embed(self, params, idx, pctx=None):
        """Token embedding only — positions enter via RoPE in each block
        (no wpe table)."""
        return self._constrain_activations(
            self.embed_tokens(params, idx), pctx
        )

    def _positions(self, t_local, pctx):
        pos = jnp.arange(t_local, dtype=jnp.int32)
        if (pctx is not None and pctx.seq_parallel and pctx.pipe_parallel):
            # inside the pipeline's manual-{pipe, seq} region the block sees
            # a LOCAL T shard; offset to global positions
            pos = pos + jax.lax.axis_index(pctx.seq_axis) * t_local
        return pos

    def _block(self, x, bp, pctx=None, return_kv=False):
        c = self.config
        b, t, d = x.shape
        hd = c.head_dim
        nq, nkv = c.n_head, c.kv_heads

        h = rmsnorm(x, bp["ln_1.w"])
        q = linear(h, self._bw(bp, "attn.q.w", pctx), None)
        k = linear(h, self._bw(bp, "attn.k.w", pctx), None)
        v = linear(h, self._bw(bp, "attn.v.w", pctx), None)
        q = q.reshape(b, t, nq, hd).swapaxes(1, 2)
        k = k.reshape(b, t, nkv, hd).swapaxes(1, 2)
        v = v.reshape(b, t, nkv, hd).swapaxes(1, 2)

        pos = self._positions(t, pctx)
        q = rope(q, pos, c.rope_theta)
        k = rope(k, pos, c.rope_theta)
        kv = (k, v)  # cached UNREPEATED (post-rope): decode groups q heads
        # GQA: K/V go in at nkv heads — sharded_attention keeps them
        # grouped into the FA2 kernel on the flash paths (K/V HBM traffic
        # stays at kv_heads) and expands only where a path needs equal
        # head counts (ops/attention.py)
        y = sharded_attention(q, k, v, c.attn_impl, pctx)
        y = y.swapaxes(1, 2).reshape(b, t, d)
        y = linear(y, self._bw(bp, "attn.o.w", pctx), None)
        dkey = bp.get("dropout_rng")
        if dkey is not None:
            y = _dropout(y, jax.random.fold_in(dkey, 0), c.dropout)
        x = x + y

        h = rmsnorm(x, bp["ln_2.w"])
        gate = jax.nn.silu(linear(h, self._bw(bp, "mlp.gate.w", pctx), None))
        up = linear(h, self._bw(bp, "mlp.up.w", pctx), None)
        y = linear(gate * up, self._bw(bp, "mlp.down.w", pctx), None)
        if dkey is not None:
            y = _dropout(y, jax.random.fold_in(dkey, 1), c.dropout)
        x = x + y
        return (x, kv) if return_kv else x

    # -- KV-cache decode (GPT2Model machinery; Llama attention/MLP) --------

    def _attn_decode(self, x, bp, ks, vs, l, pos):
        """Stacked-cache contract (GPT2Model._attn_decode): write this
        position's K/V sliver in place at (l, pos), read layer l's
        panel, attend (grouped — the cache rests at kv_heads)."""
        c = self.config
        b = x.shape[0]
        hd = c.head_dim
        h = rmsnorm(x, bp["ln_1.w"])
        q = linear(h, self._bw(bp, "attn.q.w"), None)
        k = linear(h, self._bw(bp, "attn.k.w"), None)
        v = linear(h, self._bw(bp, "attn.v.w"), None)
        q = q.reshape(b, 1, c.n_head, hd).swapaxes(1, 2)
        k = k.reshape(b, 1, c.kv_heads, hd).swapaxes(1, 2)
        v = v.reshape(b, 1, c.kv_heads, hd).swapaxes(1, 2)
        p1 = jnp.reshape(pos, (1,))
        q = rope(q, p1, c.rope_theta)
        k = rope(k, p1, c.rope_theta)
        ks = jax.lax.dynamic_update_slice(
            ks, k.astype(ks.dtype)[None], (l, 0, 0, pos, 0)
        )
        vs = jax.lax.dynamic_update_slice(
            vs, v.astype(vs.dtype)[None], (l, 0, 0, pos, 0)
        )
        ck = jax.lax.dynamic_index_in_dim(ks, l, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(vs, l, 0, keepdims=False)
        y = self._decode_attention(q, ck, cv, pos)
        y = y.swapaxes(1, 2).reshape(b, 1, c.n_embd)
        return x + linear(y, self._bw(bp, "attn.o.w"), None), ks, vs

    def _mlp_decode(self, x, bp):
        h = rmsnorm(x, bp["ln_2.w"])
        gate = jax.nn.silu(linear(h, self._bw(bp, "mlp.gate.w"), None))
        up = linear(h, self._bw(bp, "mlp.up.w"), None)
        return x + linear(gate * up, self._bw(bp, "mlp.down.w"), None)

    def _block_decode(self, x, bp, ks, vs, l, pos):
        x, ks, vs = self._attn_decode(x, bp, ks, vs, l, pos)
        return self._mlp_decode(x, bp), ks, vs

    def _paged_attn_decode(self, x, bp, view, l, page):
        """Paged-pool decode attention (GPT2Model contract): separate
        q/k/v projections, per-row RoPE at each slot's own position,
        grouped attention over the gathered block panel."""
        c = self.config
        b = x.shape[0]
        hd = c.head_dim
        h = rmsnorm(x, bp["ln_1.w"])
        q = linear(h, self._bw(bp, "attn.q.w"), None)
        k = linear(h, self._bw(bp, "attn.k.w"), None)
        v = linear(h, self._bw(bp, "attn.v.w"), None)
        q = q.reshape(b, 1, c.n_head, hd).swapaxes(1, 2)
        k = k.reshape(b, 1, c.kv_heads, hd).swapaxes(1, 2)
        v = v.reshape(b, 1, c.kv_heads, hd).swapaxes(1, 2)
        q = rope_at(q, page.pos, c.rope_theta)
        k = rope_at(k, page.pos, c.rope_theta)
        from ..serving.pool import paged_append
        view = paged_append(view, k[:, :, 0], v[:, :, 0], l, page)
        y = self._paged_attention(q, view, l, page)
        y = y.swapaxes(1, 2).reshape(b, 1, c.n_embd)
        return x + linear(y, self._bw(bp, "attn.o.w"), None), view

    def _paged_verify_attn(self, x, bp, view, l, page):
        """Speculative-verify attention (GPT2Model contract): separate
        q/k/v projections, RoPE at each span token's absolute position,
        grouped attention over committed panel + windowed span."""
        c = self.config
        s, k1, _ = x.shape
        hd = c.head_dim
        h = rmsnorm(x, bp["ln_1.w"])
        q = linear(h, self._bw(bp, "attn.q.w"), None)
        k = linear(h, self._bw(bp, "attn.k.w"), None)
        v = linear(h, self._bw(bp, "attn.v.w"), None)
        q = q.reshape(s, k1, c.n_head, hd).swapaxes(1, 2)
        k = k.reshape(s, k1, c.kv_heads, hd).swapaxes(1, 2)
        v = v.reshape(s, k1, c.kv_heads, hd).swapaxes(1, 2)
        positions = page.pos[:, None] + jnp.arange(k1)[None, :]
        q = rope_span(q, positions, c.rope_theta)
        k = rope_span(k, positions, c.rope_theta)
        y = self._paged_attention(q, view, l, page, span_kv=(k, v))
        y = y.swapaxes(1, 2).reshape(s, k1, c.n_embd)
        return x + linear(y, self._bw(bp, "attn.o.w"), None), (k, v)

    def _embed_decode(self, params, tok, pos):
        """No wpe table — position enters via RoPE inside each block."""
        del pos
        return self.embed_tokens(params, tok[:, None])

    def _embed_decode_span(self, params, toks, positions):
        """No wpe table — span positions enter via `rope_span` inside
        each verify block."""
        del positions
        return self.embed_tokens(params, toks)

    def final_norm(self, params, x):
        """RMSNorm pre-head (GPT2Model.head's one overridable hook — the
        lm_head/fused-xent/position-slice policy stays in gpt2.py)."""
        return rmsnorm(x, params["ln_f.w"].astype(self.config.compute_dtype))
