# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Production serving tier: continuous batching over a paged KV cache.

Import-side contract: importing this package (or building a
ServingEngine) changes NOTHING about training — the training step's HLO
is byte-identical with serving imported but unused, pinned in
tests/test_serving.py alongside the telemetry=off convention.

  * `pool`    — paged KV block pool + block tables, int8/fp8 cache blocks
  * `engine`  — ServingEngine: prefill/decode phase split, admission,
                eviction, preemption, SLO shedding/expiry, warm
                restart, telemetry
  * `guard`   — decode-health guard: per-slot non-finite quarantine +
                the warm-restart watchdog
  * `journal` — crash-recoverable request journal (write-ahead log
                behind ServingEngine.recover)
  * `driver`  — synthetic Poisson-arrivals load driver + the serial
                `generate()` baseline (bench + tests share it)
  * `spec`    — speculative decoding: one shape-stable verify program
                scoring k+1 draft-span positions per slot per tick
  * `drafter` — draft proposers behind one interface: model-free
                prompt-lookup ("ngram") and a small same-family draft
                model ("model:<preset>" / "model:self")
  * `prefix`  — shared-prefix KV reuse: refcounted radix tree of
                committed full blocks; admission aliases matched
                blocks copy-on-write and prefills only the suffix
  * `tenancy` — multi-tenant admission: weighted-fair stride
                scheduling, per-tenant token budgets, SLO classes,
                and door watermarks
"""

from .drafter import ModelDrafter, NgramDrafter, make_drafter
from .engine import Request, ServeConfig, ServingEngine
from .guard import DecodeHealthGuard
from .journal import RequestJournal, ServingKilled
from .pool import KVPoolView, PagedKVPool, PageRef
from .prefix import PrefixCache
from .spec import SpecDecoder
from .tenancy import TenantPolicy, TenantQueue, parse_tenant_spec

__all__ = [
    "Request", "ServeConfig", "ServingEngine",
    "DecodeHealthGuard", "RequestJournal", "ServingKilled",
    "KVPoolView", "PagedKVPool", "PageRef",
    "SpecDecoder", "NgramDrafter", "ModelDrafter", "make_drafter",
    "PrefixCache", "TenantPolicy", "TenantQueue", "parse_tenant_spec",
]
