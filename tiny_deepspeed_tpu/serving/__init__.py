# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Production serving tier: continuous batching over a paged KV cache.

Import-side contract: importing this package (or building a
ServingEngine) changes NOTHING about training — the training step's HLO
is byte-identical with serving imported but unused, pinned in
tests/test_serving.py alongside the telemetry=off convention.

  * `pool`   — paged KV block pool + block tables, int8/fp8 cache blocks
  * `engine` — ServingEngine: prefill/decode phase split, admission,
               eviction, preemption, telemetry
  * `driver` — synthetic Poisson-arrivals load driver + the serial
               `generate()` baseline (bench + tests share it)
"""

from .engine import Request, ServeConfig, ServingEngine
from .pool import KVPoolView, PagedKVPool, PageRef

__all__ = [
    "Request", "ServeConfig", "ServingEngine",
    "KVPoolView", "PagedKVPool", "PageRef",
]
