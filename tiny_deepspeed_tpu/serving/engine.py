# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Continuous batching over the paged KV pool.

`GPT2Model.generate` serves exactly one request at a time: fixed shapes,
one compiled loop, the whole batch enters and leaves together.  Serving
traffic needs the scheduler in between: `ServingEngine` keeps a FIXED
array of `max_active` slots (so the compiled decode step never changes
shape) and, BETWEEN decode steps, admits queued requests, evicts
finished ones, and returns their pool blocks to the free list — batch
occupancy stays high because a finished request's slot and blocks are
reused immediately instead of padding out the longest neighbor.

Phase split, two compiled programs:

  * PREFILL — one request's prompt through the training forward
    (`paged_prefill`, the `return_kv` hook), K/V scattered into its pool
    blocks, first token sampled from the true last-prompt position.
    Prompts pad to power-of-two block-multiple buckets, so distinct
    compiled shapes stay O(log block_size).
  * DECODE — ONE token for EVERY active slot: (S, 1, D) activations,
    each slot reading/writing the pool through its block table at its
    own position (vector `pos`).  Invalid slots carry scratch
    coordinates; no branch, no recompile as occupancy changes.

Block exhaustion preempts the YOUNGEST active request (its blocks free
immediately; it re-queues at the FRONT and later re-prefills from
prompt + tokens-produced-so-far, which under greedy decoding continues
the exact sequence).  A request that could never fit the pool at all is
refused at submit().

Telemetry: batch-occupancy / pool-utilization / queue-depth /
eviction-rate gauges (registered in telemetry/schema.GAUGES), admission/
eviction/preemption/token counters, TTFT + inter-token latency
histograms, and a per-request `request` record into the JSONL metrics
stream on finish.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Deque, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import resolved_cache_dtype
from ..models.sampling import sample_logits
from .pool import SCRATCH_BLOCK, PagedKVPool, page_ref


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs.  `num_blocks` * `block_tokens` is the pool's total
    token capacity shared by every concurrent request; `max_active` is
    the compiled decode step's slot count (occupancy ceiling)."""

    max_active: int = 4
    num_blocks: int = 32
    block_tokens: int = 16
    # paged-pool cache compression: None (rest at the model's
    # resolved_cache_dtype) | "int8" | "fp8" — blockwise-absmax per head
    # vector, scales per (block, token, layer, head); serving/pool.py
    quant: Optional[str] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    # sampling stops at this token when set (the token itself is kept,
    # so outputs stay comparable with fixed-length `generate` prefixes)
    eos_id: Optional[int] = None
    seed: int = 0
    # per-request length ceiling (prompt + generated), default the model
    # context.  This SIZES THE COMPILED STEP: block tables are
    # max_seq_tokens/block_tokens wide and each decode gathers that many
    # cache positions per slot, so a serving tier whose traffic is
    # bounded well under block_size should say so — a 256-context model
    # serving <=40-token requests would otherwise pay a 256-position
    # panel (6x the attention read) every token
    max_seq_tokens: Optional[int] = None


class Request:
    """One generation request through its lifecycle:
    queued -> active -> done (possibly bouncing back to queued on
    preemption).  Wall-clock latency marks use time.monotonic()."""

    _ids = itertools.count()

    def __init__(self, prompt: Sequence[int], max_new_tokens: int):
        self.id = next(Request._ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.tokens: List[int] = []  # generated (includes eos when hit)
        self.state = "queued"
        self.finish_reason: Optional[str] = None
        self.preemptions = 0
        now = time.monotonic()
        self.t_arrival = now
        self.t_admitted: Optional[float] = None  # first admission
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.active_s = 0.0  # completed active windows (preemptions)
        self.token_lat: List[float] = []  # per-token completion gaps

    @property
    def done(self) -> bool:
        return self.state == "done"


class _Slot:
    """An active request's device-side coordinates: its block table and
    current cache length (== the next write position)."""

    def __init__(self, req: Request, table: List[int], pos: int,
                 last_token: int, admitted_at: float):
        self.req = req
        self.table = table
        self.pos = pos
        self.last = last_token
        self.admitted_at = admitted_at


class ServingEngine:
    """Continuous-batching inference engine over one model + params."""

    def __init__(self, model, params, config: ServeConfig = ServeConfig(),
                 *, telemetry=None, logger=None):
        if not getattr(model, "paged_decode_capable", False):
            raise ValueError(
                f"{type(model).__name__} does not support the paged "
                "decode step (paged_decode_capable=False) — MoE capacity "
                "routing cannot batch slots at mixed positions"
            )
        c = model.config
        if c.block_size % config.block_tokens:
            raise ValueError(
                f"block_tokens={config.block_tokens} must divide the "
                f"model context block_size={c.block_size} (prefill "
                "buckets and block tables are block-multiples)"
            )
        if config.max_active < 1:
            raise ValueError("max_active must be >= 1")
        self.model = model
        self.params = params
        self.config = config
        self.telemetry = telemetry
        self.logger = logger
        self.max_seq = config.max_seq_tokens or c.block_size
        if not 1 <= self.max_seq <= c.block_size:
            raise ValueError(
                f"max_seq_tokens={config.max_seq_tokens} must be in "
                f"[1, block_size={c.block_size}]"
            )
        kv_heads = getattr(c, "kv_heads", c.n_head)
        self.pool = PagedKVPool(
            n_layer=c.n_layer, kv_heads=kv_heads, head_dim=c.head_dim,
            num_blocks=config.num_blocks,
            block_tokens=config.block_tokens,
            dtype=resolved_cache_dtype(c), quant=config.quant,
        )
        # one block table row per slot, wide enough for a max_seq
        # request; unused entries point at scratch
        self.max_blocks_per_req = -(-self.max_seq // config.block_tokens)
        self._slots: List[Optional[_Slot]] = [None] * config.max_active
        self._queue: Deque[Request] = deque()
        self._key = jax.random.PRNGKey(config.seed)
        self._ticks = 0
        self._evictions = 0
        self.last_logits = None  # (S, V) f32 of the last decode tick

        bt = config.block_tokens
        temp, top_k = config.temperature, config.top_k

        def decode_step(params, stacked, view, tokens, pos, tables, key):
            x = model._embed_decode(params, tokens, pos)
            page = page_ref(tables, pos, bt)
            x, view = model.paged_decode(stacked, x, view, page)
            logits = model.head(params, x)[:, 0]
            nxt = sample_logits(logits, key, temp, top_k)
            return nxt, logits, view

        def prefill_step(params, stacked, prompt, last_pos, block_ids,
                         view, key):
            logits, view = model.paged_prefill(
                params, prompt, last_pos, block_ids, view, bt,
                stacked=stacked,
            )
            nxt = sample_logits(logits, key, temp, top_k)
            return nxt, view

        # the pool view is DONATED through both programs: each step
        # aliases the pool buffers instead of copying the whole pool
        self._decode_fn = jax.jit(decode_step, donate_argnums=(2,))
        self._prefill_fn = jax.jit(prefill_step, donate_argnums=(5,))
        # "h.*" compute-dtype cast once — params are frozen while serving
        self._stacked = jax.jit(model.stacked_compute_params)(params)

    # -- public API ---------------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: int) -> Request:
        """Queue one request; returns its handle (tokens accumulate on
        it as ticks produce them)."""
        c = self.model.config
        if len(prompt) < 1 or max_new_tokens < 1:
            raise ValueError("need a non-empty prompt and >= 1 new token")
        total = len(prompt) + max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"prompt {len(prompt)} + new {max_new_tokens} tokens > "
                + (f"max_seq_tokens {self.max_seq}"
                   if self.max_seq < c.block_size
                   else f"block_size {c.block_size}")
            )
        worst = -(-total // self.config.block_tokens)
        if worst > self.pool.num_usable:
            raise ValueError(
                f"request needs up to {worst} blocks but the pool has "
                f"{self.pool.num_usable} — raise num_blocks or shrink "
                "the request"
            )
        req = Request(prompt, max_new_tokens)
        self._queue.append(req)
        self._count("serve_submitted")
        return req

    def tick(self) -> int:
        """One scheduler step: admit -> grow/preempt -> one decode step
        for every active slot -> evict finished.  Returns the number of
        tokens produced (prefill first-tokens included)."""
        # growth first: existing slots claim the blocks their next write
        # needs BEFORE admission can take them — the other order lets a
        # fresh admission strand a grower, whose preempt-youngest victim
        # is then the just-prefilled request (a full prefill thrown away
        # per block boundary while the pool is tight)
        self._grow()
        produced = self._admit()
        active = [(i, s) for i, s in enumerate(self._slots)
                  if s is not None]
        if active:
            S = self.config.max_active
            tokens = np.zeros((S,), np.int32)
            pos = np.zeros((S,), np.int32)
            tables = np.full((S, self.max_blocks_per_req), SCRATCH_BLOCK,
                             np.int32)
            for i, s in active:
                tokens[i] = s.last
                pos[i] = s.pos
                tables[i, :len(s.table)] = s.table
            nxt, logits, view = self._decode_fn(
                self.params, self._stacked, self.pool.view,
                tokens, pos, tables, self._next_key(),
            )
            self.pool.view = view
            self.last_logits = logits
            nxt = np.asarray(nxt)
            tnow = time.monotonic()
            for i, s in active:
                t = int(nxt[i])
                s.pos += 1
                s.last = t
                self._append_token(s.req, t, tnow)
                produced += 1
                if self._finished(s.req):
                    self._finish(i, s)
        self._ticks += 1
        self._update_gauges()
        return produced

    def drain(self, max_ticks: Optional[int] = None) -> int:
        """Tick until every submitted request is done; returns total
        tokens produced.  `max_ticks` bounds runaway loops in tests."""
        total = 0
        ticks = 0
        while self._queue or any(s is not None for s in self._slots):
            total += self.tick()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(
                    f"drain exceeded {max_ticks} ticks with "
                    f"{len(self._queue)} queued"
                )
        return total

    @property
    def n_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def active_block_tables(self) -> dict:
        """{request id: list of physical block ids} for every active
        slot — what the pool-accounting acceptance sums against
        pool.blocks_in_use at each tick."""
        return {s.req.id: list(s.table)
                for s in self._slots if s is not None}

    def describe(self) -> str:
        q = self.config.quant or str(jnp.dtype(self.pool.view.k.dtype))
        return (
            f"serving(max_active={self.config.max_active}, "
            f"blocks={self.pool.num_usable}x"
            f"{self.config.block_tokens}, cache={q})"
        )

    # -- scheduler internals ------------------------------------------------

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _bucket(self, p: int) -> int:
        """Prefill pad length: the smallest power-of-two multiple of
        block_tokens >= p (compiled prefill shapes stay O(log T))."""
        bt = self.config.block_tokens
        nb = -(-p // bt)
        b = 1
        while b < nb:
            b *= 2
        return min(b * bt, self.model.config.block_size)

    def _admit(self) -> int:
        """FIFO admission: prefill queued requests into free slots while
        the pool can hold their prompts.  Head-of-line blocking is
        deliberate — skipping ahead would starve long prompts."""
        produced = 0
        while self._queue:
            try:
                slot_i = self._slots.index(None)
            except ValueError:
                break
            req = self._queue[0]
            prompt_now = req.prompt + req.tokens  # preemption continuation
            p = len(prompt_now)
            bt = self.config.block_tokens
            # blocks for the prompt AND its first decode write (position
            # p): same count as ceil(p/bt) except when p lands exactly
            # on a block boundary — without the extra block that first
            # decode write would land in the scratch block (lost K/V),
            # or need a _grow after admission that can preempt the
            # admission itself
            ids = self.pool.alloc(p // bt + 1)
            if ids is None:
                break
            self._queue.popleft()
            t_adm = time.monotonic()
            if req.t_admitted is None:
                req.t_admitted = t_adm
            bucket = self._bucket(p)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :p] = prompt_now
            block_ids = np.full((bucket // bt,), SCRATCH_BLOCK, np.int32)
            # the prefill panel only spans the bucket; the +1 decode
            # block can lie past it (boundary p == bucket) — it is
            # reached through the slot table, not the prefill scatter
            k = min(len(ids), bucket // bt)
            block_ids[:k] = ids[:k]
            nxt, view = self._prefill_fn(
                self.params, self._stacked, padded, p - 1, block_ids,
                self.pool.view, self._next_key(),
            )
            self.pool.view = view
            tok = int(np.asarray(nxt)[0])
            slot = _Slot(req, table=ids, pos=p, last_token=tok,
                         admitted_at=t_adm)
            self._slots[slot_i] = slot
            req.state = "active"
            self._count("serve_admissions")
            self._append_token(req, tok, time.monotonic())
            produced += 1
            if self._finished(req):
                self._finish(slot_i, slot)
        return produced

    def _grow(self) -> None:
        """Allocate the next block for any slot whose write position
        crossed a block boundary; on exhaustion, preempt the youngest
        active request until the grower fits (or is itself preempted)."""
        for i, slot in enumerate(self._slots):
            if slot is None or self._slots[i] is not slot:
                continue
            while (self._slots[i] is slot
                   and len(slot.table) < slot.pos
                   // self.config.block_tokens + 1):
                ids = self.pool.alloc(1)
                if ids is not None:
                    slot.table.extend(ids)
                    continue
                victim_i, victim = max(
                    ((j, s) for j, s in enumerate(self._slots)
                     if s is not None),
                    key=lambda js: js[1].admitted_at,
                )
                self._preempt(victim_i, victim)

    def _preempt(self, i: int, slot: _Slot) -> None:
        req = slot.req
        self.pool.free_blocks(slot.table)
        self._slots[i] = None
        req.state = "queued"
        req.active_s += time.monotonic() - slot.admitted_at
        req.preemptions += 1
        # front of the queue: it resumes (re-prefilling prompt + tokens
        # so far — greedy-exact continuation) as soon as blocks free up
        self._queue.appendleft(req)
        self._count("serve_preemptions")

    def _finished(self, req: Request) -> bool:
        if len(req.tokens) >= req.max_new_tokens:
            req.finish_reason = req.finish_reason or "length"
            return True
        eos = self.config.eos_id
        if eos is not None and req.tokens and req.tokens[-1] == eos:
            req.finish_reason = "eos"
            return True
        return False

    def _finish(self, i: int, slot: _Slot) -> None:
        req = slot.req
        self.pool.free_blocks(slot.table)
        self._slots[i] = None
        req.state = "done"
        req.t_done = time.monotonic()
        self._evictions += 1
        self._count("serve_evictions")
        if self.logger is not None:
            self.logger.log_meta(
                kind="request",
                request_id=req.id,
                prompt_tokens=len(req.prompt),
                new_tokens=len(req.tokens),
                queue_s=round(req.t_admitted - req.t_arrival, 6),
                ttft_s=round(req.t_first - req.t_arrival, 6),
                # rate over the ACTIVE windows only (each admission ->
                # preemption/done: prefill + decode), not the request
                # lifetime — queue waits (initial AND re-queued after
                # preemption) are reported by queue_s/preemptions, and
                # folding them in here would collapse this field into a
                # duplicate of overall latency
                decode_tokens_per_s=round(
                    len(req.tokens)
                    / max(req.active_s
                          + (req.t_done - slot.admitted_at), 1e-9), 3),
                preemptions=req.preemptions,
                finish=req.finish_reason or "length",
            )

    def _append_token(self, req: Request, tok: int, tnow: float) -> None:
        # per-token latency = gap since the previous token's completion
        # (arrival for the first — i.e. the first gap IS the TTFT)
        last_t = getattr(req, "_t_last", req.t_arrival)
        req.tokens.append(tok)
        req.token_lat.append(tnow - last_t)
        req._t_last = tnow
        if req.t_first is None:
            req.t_first = tnow
            if self.telemetry is not None:
                self.telemetry.histogram("serve_ttft_s").observe(
                    tnow - req.t_arrival)
        elif self.telemetry is not None:
            self.telemetry.histogram("serve_token_latency_s").observe(
                req.token_lat[-1])
        self._count("serve_tokens")

    def _count(self, name: str) -> None:
        if self.telemetry is not None:
            self.telemetry.counter(name).inc()

    def _update_gauges(self) -> None:
        if self.telemetry is None:
            return
        t = self.telemetry
        t.gauge("serve_batch_occupancy",
                self.n_active / self.config.max_active)
        t.gauge("serve_pool_utilization",
                self.pool.blocks_in_use / self.pool.num_usable)
        t.gauge("serve_queue_depth", float(len(self._queue)))
        t.gauge("serve_eviction_rate",
                self._evictions / max(1, self._ticks))
