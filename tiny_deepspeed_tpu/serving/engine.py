# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Continuous batching over the paged KV pool.

`GPT2Model.generate` serves exactly one request at a time: fixed shapes,
one compiled loop, the whole batch enters and leaves together.  Serving
traffic needs the scheduler in between: `ServingEngine` keeps a FIXED
array of `max_active` slots (so the compiled decode step never changes
shape) and, BETWEEN decode steps, admits queued requests, evicts
finished ones, and returns their pool blocks to the free list — batch
occupancy stays high because a finished request's slot and blocks are
reused immediately instead of padding out the longest neighbor.

Phase split, two compiled programs:

  * PREFILL — one request's prompt through the training forward
    (`paged_prefill`, the `return_kv` hook), K/V scattered into its pool
    blocks, first token sampled from the true last-prompt position.
    Prompts pad to power-of-two block-multiple buckets, so distinct
    compiled shapes stay O(log block_size).
  * DECODE — ONE token for EVERY active slot: (S, 1, D) activations,
    each slot reading/writing the pool through its block table at its
    own position (vector `pos`).  Invalid slots carry scratch
    coordinates; no branch, no recompile as occupancy changes.

Block exhaustion preempts the YOUNGEST active request (its blocks free
immediately; it re-queues at the FRONT and later re-prefills from
prompt + tokens-produced-so-far, which continues the exact sequence).
A request that could never fit the pool at all is refused at submit().

Speculative decoding (`spec_draft=` / `spec_k=`; serving/spec.py +
serving/drafter.py): the decode step's ONE-token-per-slot contract
relaxes to 1..k+1 — a drafter proposes up to k continuation tokens per
slot, one shape-stable verify program scores all k+1 span positions
through the same paged attention, and the acceptance core commits the
longest target-exact prefix (greedy output bit-identical to
`generate`; only VERIFIED tokens reach the request, the journal, or
the pool — rejected draft K/V routes to the scratch block inside the
verify program itself).  Growth/admission extend block ownership to
the span horizon, the SLO shed price re-bases on wall per committed
token, and the guard/journal/preemption machinery is shared: the spec
path is one more decode implementation under the same scheduler.

Fault posture (the serving robustness layer):

  * SLOs — `submit(..., deadline_s=)` attaches a completion deadline
    (seconds from arrival).  The scheduler SHEDS queued requests whose
    deadline is overdue or unmeetable (priced from the measured
    per-tick decode-wall history), EXPIRES active requests that blow
    their deadline, and REFUSES admission outright above the
    `max_queue` / `shed_pool_util` watermarks — so a deadline-blind
    queue can never grow unboundedly.  Every outcome is a distinct
    terminal status on the request and its JSONL record:
    `ok` / `shed` / `expired` / `failed`.
  * Decode health — the compiled decode step reduces each slot's
    logits to a per-slot non-finite flag fetched alongside the sampled
    tokens (no extra device sync); poisoned slots are QUARANTINED
    (blocks freed, request `failed`, the rest of the batch keeps
    serving), and a watchdog WARM-RESTARTS the engine — fresh pool +
    slot array, compiled programs kept — after `guard_k_restart`
    consecutive poisoned ticks or any exception out of a tick
    (serving/guard.py).
  * Crash recovery — an append-only request journal (admissions +
    produced tokens, fsync batched per tick; serving/journal.py) lets
    `recover()` re-queue a dead engine's in-flight requests
    front-of-line with their produced prefix, riding the preemption
    resume path.

Determinism guarantee: sampling keys derive ONLY from (request seed,
output position) — `models/sampling.request_position_key` — never from
the scheduler tick, batch composition, preemption count, or restarts.
Greedy (temperature == 0) continuation is token-exact by argmax;
temperature > 0 re-samples the SAME tokens after preemption, warm
restart, or journal recovery because position i of request r always
draws from the same key (categorical is Gumbel argmax, sharing greedy's
robustness to the prefill-vs-decode numeric path difference).  A
request's token sequence is therefore a pure function of
(params, prompt, seed) — which is exactly what makes the journal's
"re-queue with produced prefix" recovery exact.

Telemetry: batch-occupancy / pool-utilization / queue-depth /
eviction-rate gauges plus the fault-path serve_shed / serve_expired /
serve_quarantined / serve_restarts gauges (telemetry/schema.GAUGES),
admission/eviction/preemption/token counters, TTFT + inter-token latency
histograms, and a per-request `request` record (terminal `status` field)
into the JSONL metrics stream at every terminal outcome.

Observability layer (the serving twin of the training step traces):

  * Request-lifecycle spans — every Request accumulates timestamped
    lifecycle events (submitted -> admitted(slot) -> preempted /
    restart_requeued / quarantined / expired -> terminal:<status>)
    recorded inside the scheduler hooks, serialized on its `request`
    record; `scripts/trace_view.py` lays them out as a Perfetto
    timeline with one track per decode slot plus a queue track.
  * Tail-latency attribution — each terminal request's latency is
    decomposed into queue-wait / prefill / decode-active /
    preempted-wait / restart-overhead components that PARTITION
    `lat_s` (sum == terminal latency, pinned by test), so "why was p99
    400 ms" has a named answer; `scripts/serve_report.py` rolls them up.
  * Per-tick time series — a `tick` JSONL record (wall split: host
    scheduling vs prefill vs decode dispatch vs token fetch; occupancy,
    pool utilization, queue depth; per-tick admission/eviction/
    preemption/shed counts), emitted when a scheduler event happened OR
    every `tick_record_every` ticks — long traces stay bounded while
    every eventful tick is captured.
  * Serving flight recorder — the last `flight_ticks` tick entries ride
    a telemetry/flight.py ring (host dicts only, no device sync) and
    flush as ONE `flight` record when quarantine, a watchdog restart, a
    shed burst, or `recover()` fires: every postmortem carries its
    lead-up, not just the event.

All of it is host-side bookkeeping around the SAME compiled programs —
the decode/prefill HLO is byte-identical with observability on or off
(the existing serving-off-path pin covers it).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import resolved_cache_dtype
from ..models.sampling import sample_logits_at, sample_logits_per_slot
from .guard import DecodeHealthGuard
from .journal import RequestJournal, ServingKilled
from .pool import (
    SCRATCH_BLOCK, BlockPayload, PagedKVPool, export_blocks,
    import_blocks, page_ref, paged_append_span,
)
from .prefix import PrefixCache
from .tenancy import TenantPolicy, TenantQueue

# decode-wall samples needed before deadline shedding trusts its price
# estimate (a cold engine must not shed on compile-time noise)
_MIN_GAP_SAMPLES = 5


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs.  `num_blocks` * `block_tokens` is the pool's total
    token capacity shared by every concurrent request; `max_active` is
    the compiled decode step's slot count (occupancy ceiling)."""

    max_active: int = 4
    num_blocks: int = 32
    block_tokens: int = 16
    # paged-pool cache compression: None (rest at the model's
    # resolved_cache_dtype) | "int8" | "fp8" — blockwise-absmax per head
    # vector, scales per (block, token, layer, head); serving/pool.py
    quant: Optional[str] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    # sampling stops at this token when set (the token itself is kept,
    # so outputs stay comparable with fixed-length `generate` prefixes)
    eos_id: Optional[int] = None
    seed: int = 0
    # per-request length ceiling (prompt + generated), default the model
    # context.  This SIZES THE COMPILED STEP: block tables are
    # max_seq_tokens/block_tokens wide and each decode gathers that many
    # cache positions per slot, so a serving tier whose traffic is
    # bounded well under block_size should say so — a 256-context model
    # serving <=40-token requests would otherwise pay a 256-position
    # panel (6x the attention read) every token
    max_seq_tokens: Optional[int] = None
    # admission watermarks: submit() SHEDS (terminal status "shed",
    # never queued) when the queue already holds max_queue requests, or
    # when the pool is at shed_pool_util utilization with a backlog —
    # load shedding at the door instead of unbounded queue growth
    max_queue: Optional[int] = None
    shed_pool_util: Optional[float] = None
    # decode-health guard (serving/guard.py): per-tick non-finite logit
    # check + quarantine + warm-restart watchdog.  guard_k_restart =
    # consecutive poisoned ticks before the watchdog trips.
    health_guard: bool = True
    guard_k_restart: int = 3
    # per-tick `tick` record sampling cadence: an eventful tick (any
    # admission/eviction/preemption/shed/expiry/quarantine/restart)
    # always emits when a logger is attached; a quiet decode tick emits
    # every this-many ticks (0 = eventful ticks only) — bounded metrics
    # files on long-running servers
    tick_record_every: int = 16
    # serving flight recorder: ring capacity in ticks (0 disables);
    # flushed as one `flight` record on quarantine / watchdog restart /
    # shed burst / recover()
    flight_ticks: int = 64
    # sheds within one tick window that count as a "shed burst" and
    # trigger a flight flush (overload postmortems need the lead-up too)
    shed_burst: int = 3
    # speculative decoding (serving/spec.py): None = plain one-token
    # decode (the exact pre-spec programs); "ngram" = model-free
    # prompt-lookup drafter; "model:self" / "model:<preset>" = a small
    # same-family draft model with its own cache (serving/drafter.py).
    # Each tick the drafter proposes up to spec_k tokens per slot and
    # ONE verify pass through the target commits 1..spec_k+1 of them —
    # greedy output stays bit-identical to `generate` (acceptance is
    # token equality), temperature>0 stays target-exact and
    # deterministic under the (seed, position) keys.
    spec_draft: Optional[str] = None
    spec_k: int = 4
    # shared-prefix KV reuse (serving/prefix.py): admission walks a
    # radix tree of committed full blocks keyed by token prefix,
    # aliases matched blocks into the new request's block table
    # (refcounted — copy-on-write discipline: every writable block
    # stays private), and prefills only the unmatched suffix through a
    # span program riding the spec-verify attention.  Greedy output is
    # token-identical with the cache on or off; the tree keeps finished
    # requests' prompt blocks warm and yields them LRU under pool
    # pressure.  Does not compose with spec_draft (the suffix prefill
    # and the draft span both own the span path — refused loudly).
    prefix_cache: bool = False
    # paged-attention kernel dispatch (ops/paged_attn_pallas.py):
    # "auto" (default) runs the Pallas fused block-table-gather kernel
    # on TPU kernel targets and the XLA materialized-panel path
    # elsewhere; "on"/"off" force one arm — "off" is the byte-identical
    # pre-kernel program (the A/B baseline), "on" on a CPU mesh needs
    # the kernel's interpret mode (tests).  Applied at trace time to
    # every program this engine compiles (decode, spec verify, suffix
    # prefill), scoped so sibling engines' choices never mix.
    paged_kernel: str = "auto"
    # multi-tenant serving (serving/tenancy.py): {tenant: TenantPolicy}
    # swaps FIFO admission for weighted-fair stride scheduling with
    # per-tenant token budgets, door watermarks, and SLO-class default
    # deadlines; submit() takes tenant=.  Tenants NOT in the dict get
    # default policy (weight 1, no budget) — set it empty ({}) to tag
    # requests per tenant with everyone at defaults.
    tenants: Optional[Dict[str, TenantPolicy]] = None


class Request:
    """One generation request through its lifecycle:
    queued -> active -> done (possibly bouncing back to queued on
    preemption, warm restart, or journal recovery).  `status` is the
    terminal outcome: "ok" (finished), "shed" (never served — refused
    at the watermark or deadline-unmeetable in queue), "expired"
    (served but blew its deadline), "failed" (quarantined on
    non-finite decode logits).  Wall-clock marks use time.monotonic()."""

    _ids = itertools.count()

    def __init__(self, prompt: Sequence[int], max_new_tokens: int, *,
                 deadline_s: Optional[float] = None,
                 seed: Optional[int] = None, id: Optional[int] = None,
                 tenant: Optional[str] = None,
                 trace_id: Optional[str] = None):
        self.id = next(Request._ids) if id is None else int(id)
        # cross-engine trace correlation: stamped ONCE at submit and
        # carried through disagg migration (the object itself moves),
        # failover adoption (handles are reused), and journal recovery
        # (persisted on the submit line).  The default derives from the
        # id, so a pre-v15 journal replays to the SAME trace_id the
        # original submit stamped — correlation survives even journals
        # that predate the field.
        self.trace_id = (f"t{self.id:06d}" if trace_id is None
                         else str(trace_id))
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        # multi-tenant serving: which tenant submitted this request
        # (None on untagged traffic) — drives the weighted-fair queue,
        # per-tenant watermarks/SLO class, and the record's attribution
        self.tenant = None if tenant is None else str(tenant)
        # shared-prefix cache accounting, cumulative over this
        # request's admissions: blocks aliased from the radix tree and
        # prompt tokens whose prefill those aliases avoided
        self.prefix_blocks = 0
        self.prefix_tokens = 0
        # per-request sampling seed: with temperature > 0, token i draws
        # from fold(fold(engine_base_key, seed), i) — deterministic
        # across preemption/restart/recovery (module docstring)
        self.seed = self.id if seed is None else int(seed)
        self.tokens: List[int] = []  # generated (includes eos when hit)
        # speculative-decoding accounting (stays 0 with spec off):
        # drafts proposed for / accepted into this request's sequence
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.state = "queued"
        self.status: Optional[str] = None  # terminal: ok/shed/expired/failed
        self.finish_reason: Optional[str] = None
        self.preemptions = 0
        now = time.monotonic()
        self.t_arrival = now
        self.t_admitted: Optional[float] = None  # first admission
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.active_s = 0.0  # completed active windows (preemptions)
        self.token_lat: List[float] = []  # per-token completion gaps
        self._journaled = False
        # lifecycle event timeline: (name, t_monotonic[, slot]) tuples,
        # serialized on the request record — trace_view.py's queue/slot
        # tracks are built from these
        self.events: List[tuple] = [("submitted", now)]
        # tail-latency attribution: the components PARTITION the terminal
        # latency — at any instant the request is in exactly one bucket
        # (waiting with a reason, prefilling, or decode-active), and
        # every transition closes one window with the same timestamp
        # that opens the next, so the sum telescopes to t_done-t_arrival
        self.lat_components = {"queue": 0.0, "prefill": 0.0,
                               "decode": 0.0, "preempt": 0.0,
                               "restart": 0.0, "migrate": 0.0}
        self._wait_since: Optional[float] = now
        self._wait_kind = "queue"
        self.last_slot: Optional[int] = None
        # disaggregated serving (fleet/disagg.py): the priced paged-KV
        # handoff this request paid — resting-dtype bytes moved between
        # the prefill and decode pools, and which link class carried
        # them ("ici" / "dcn", the wire_link_split granule logic).
        # Zero/None on single-engine paths; serialized on the request
        # record only when a migration happened.
        self.kv_migration_bytes = 0
        self.kv_migration_link: Optional[str] = None

    def event(self, name: str, t: float, slot: Optional[int] = None,
              replica: Optional[int] = None):
        """Append a lifecycle event.  `replica` stamps the CROSS-ENGINE
        markers (exported/imported/recovered/engine_lost) with the
        engine they left or arrived at, so one request's spans render
        on correlated per-replica tracks: a marker that leaves an
        engine (exported, engine_lost) attributes the events since the
        previous marker to its replica; one that arrives (imported,
        recovered) attributes the events after it.  Serialized as
        [name, t], [name, t, slot], or [name, t, slot, replica] —
        single-engine events keep their historical 2/3-tuple shape."""
        e: tuple = (name, t)
        if slot is not None or replica is not None:
            e += (slot,)
        if replica is not None:
            e += (replica,)
        self.events.append(e)

    @property
    def done(self) -> bool:
        return self.state == "done"

    @property
    def deadline(self) -> Optional[float]:
        """Absolute monotonic deadline (None = no SLO).  Recovered
        requests re-base on their recovery time — the original arrival
        clock died with the old process."""
        if self.deadline_s is None:
            return None
        return self.t_arrival + self.deadline_s


class _Slot:
    """An active request's device-side coordinates: its block table and
    current cache length (== the next write position)."""

    def __init__(self, req: Request, table: List[int], pos: int,
                 last_token: int, admitted_at: float,
                 prefill_s: float = 0.0):
        self.req = req
        self.table = table
        self.pos = pos
        self.last = last_token
        self.admitted_at = admitted_at
        # this admission's prefill wall — subtracted from the active
        # window when it closes, so the decode-active component never
        # double-counts the prefill component
        self.prefill_s = prefill_s


@dataclasses.dataclass
class KVHandoff:
    """One request in transit between two engines — the disaggregated
    prefill->decode migration unit (fleet/disagg.py).  `payload` holds
    the request's pool blocks in the SOURCE pool's resting dtype
    (quantized pools migrate 1-byte blocks + scales); `pos`/`last` are
    the slot coordinates the importing engine seats the request at."""

    req: Request
    payload: BlockPayload
    pos: int
    last: int
    block_tokens: int
    src_replica: Optional[int] = None


class ServingEngine:
    """Continuous-batching inference engine over one model + params.

    See the module docstring for the scheduling and fault-handling
    contract; the determinism guarantee (sampling keys from (request
    seed, position) only) is what makes preemption resume, warm restart,
    and `recover()` all token-exact — at temperature 0 AND above."""

    def __init__(self, model, params, config: ServeConfig = ServeConfig(),
                 *, telemetry=None, logger=None,
                 journal: Union[None, str, RequestJournal] = None,
                 replica_id: Optional[int] = None):
        if not getattr(model, "paged_decode_capable", False):
            raise ValueError(
                f"{type(model).__name__} does not support the paged "
                "decode step (paged_decode_capable=False) — MoE capacity "
                "routing cannot batch slots at mixed positions"
            )
        c = model.config
        if c.block_size % config.block_tokens:
            raise ValueError(
                f"block_tokens={config.block_tokens} must divide the "
                f"model context block_size={c.block_size} (prefill "
                "buckets and block tables are block-multiples)"
            )
        if config.max_active < 1:
            raise ValueError("max_active must be >= 1")
        if config.prefix_cache and config.spec_draft is not None:
            raise ValueError(
                "prefix_cache does not compose with spec_draft: the "
                "suffix prefill and the draft span both own the span "
                "program, and the drafter's accept-or-residual commit "
                "is not wired through the suffix path — run one or "
                "the other"
            )
        self.model = model
        self.params = params
        self.config = config
        self.telemetry = telemetry
        self.logger = logger
        # live observability plane (telemetry/live.py): when attached,
        # each tick pushes the registry snapshot (host dicts only) into
        # the aggregator the /metrics exporter reads — opt-in, strictly
        # off the compiled path
        self.live = None
        # SLO error budgets (telemetry/slo.py): when attached, every
        # terminal request is observed and fast-burn alerts arm the
        # flight ring
        self.slo = None
        # fleet identity: stamped on this engine's request/tick records
        # when set (fleet/router.py, fleet/disagg.py) so one metrics
        # stream can carry a whole fleet; None keeps single-engine
        # records byte-compatible with pre-fleet readers
        self.replica_id = replica_id
        self._journal: Optional[RequestJournal] = None
        self.max_seq = config.max_seq_tokens or c.block_size
        if not 1 <= self.max_seq <= c.block_size:
            raise ValueError(
                f"max_seq_tokens={config.max_seq_tokens} must be in "
                f"[1, block_size={c.block_size}]"
            )
        # journal attach (property: stamps the serving geometry into the
        # file) — after max_seq so the stamp reflects the real geometry
        self.journal = journal
        kv_heads = getattr(c, "kv_heads", c.n_head)
        self._pool_args = dict(
            n_layer=c.n_layer, kv_heads=kv_heads, head_dim=c.head_dim,
            num_blocks=config.num_blocks,
            block_tokens=config.block_tokens,
            dtype=resolved_cache_dtype(c), quant=config.quant,
        )
        self.pool = PagedKVPool(**self._pool_args)
        # one block table row per slot, wide enough for a max_seq
        # request; unused entries point at scratch
        self.max_blocks_per_req = -(-self.max_seq // config.block_tokens)
        self._slots: List[Optional[_Slot]] = [None] * config.max_active
        # admission queue: plain FIFO, or the weighted-fair per-tenant
        # stride scheduler when tenants are configured
        self._queue: Union[Deque[Request], TenantQueue] = (
            TenantQueue(config.tenants) if config.tenants is not None
            else deque())
        # shared-prefix radix tree (None = cache off; rebuilt empty
        # with the pool on warm restart)
        self._prefix: Optional[PrefixCache] = (
            PrefixCache(config.block_tokens) if config.prefix_cache
            else None)
        self._guard = (DecodeHealthGuard(config.guard_k_restart)
                       if config.health_guard else None)
        self._ticks = 0
        self._evictions = 0
        self._shed = 0
        self._expired = 0
        self._quarantined = 0
        self._restarts = 0
        self._restarts_since_progress = 0
        # serving flight recorder (telemetry/flight.py ring reused with
        # tick entries): record() every tick, flush on fault triggers
        if config.flight_ticks:
            from ..telemetry.flight import FlightRecorder
            self._flight = FlightRecorder(config.flight_ticks)
        else:
            self._flight = None
        self._flight_reason: Optional[str] = None
        # per-tick wall split + scheduler counts (tick records + flight)
        self._seg = {"prefill_s": 0.0, "decode_s": 0.0, "fetch_s": 0.0,
                     "draft_s": 0.0}
        self._tick_counts = dict.fromkeys(
            ("admitted", "evicted", "preempted", "expired",
             "quarantined", "restarted"), 0)
        self._shed_seen = 0
        # recent decode walls PER COMMITTED TOKEN: the measured
        # inter-token service price for deadline feasibility.  On the
        # plain path one tick commits one token per active slot, so the
        # entry is just the tick's decode wall; under speculation a
        # tick's wall divides by its per-slot token yield — the tick
        # walls go bimodal (draft+verify vs plain) and yield-dependent,
        # and pricing from the raw wall would over-fire shedding on
        # cheap high-acceptance ticks
        self._gap_hist: Deque[float] = deque(maxlen=128)
        # speculative-decoding accounting (engine lifetime)
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_ticks = 0
        self._spec_tokens = 0
        # chaos / fault-injection hooks (resilience/chaos.py)
        self._poison_pending: set = set()
        self._prefill_exc: Optional[BaseException] = None
        # (S, V) f32 of the last PLAIN decode tick (debug surface; a
        # speculative engine's verify logits are (S, K+1, V) and are
        # consumed in-program — it leaves this None)
        self.last_logits = None

        from ..ops.paged_attn_pallas import (
            PAGED_KERNEL_MODES, paged_kernel_forced,
        )
        if config.paged_kernel not in PAGED_KERNEL_MODES:
            raise ValueError(
                f"paged_kernel={config.paged_kernel!r} must be one of "
                f"{PAGED_KERNEL_MODES}"
            )

        def _kwrap(fn):
            """Bracket a compiled program's CALLS with this engine's
            paged-kernel mode: jit traces lazily at first call, so the
            trace-time gate reads the right mode, and later (cached)
            calls pay one no-op context enter.  "auto" skips the
            wrapper entirely — the default engine's call path (and its
            programs) stay byte-identical to the pre-kernel tier.
            Forced windows hold the module's mode lock, so two FORCED
            engines on parallel fleet threads serialize their calls
            instead of racing the trace-time gate; an "auto" engine
            lazily tracing a fresh shape bucket during a sibling's
            forced window remains a (documented) mixed-fleet hazard —
            don't mix forced and auto replicas in one parallel fleet."""
            if config.paged_kernel == "auto":
                return fn

            def call(*a, **kw):
                with paged_kernel_forced(config.paged_kernel):
                    return fn(*a, **kw)
            return call

        bt = config.block_tokens
        temp, top_k = config.temperature, config.top_k
        base_key = jax.random.PRNGKey(config.seed)

        def decode_step(params, stacked, view, tokens, pos, tables,
                        seeds, nprod, poison):
            x = model._embed_decode(params, tokens, pos)
            page = page_ref(tables, pos, bt)
            x, view = model.paged_decode(stacked, x, view, page)
            logits = model.head(params, x)[:, 0]
            # chaos operand: 0.0 off-path (tokens bit-identical — x+0.0
            # never changes an argmax or a categorical draw), NaN on a
            # poisoned slot.  The per-slot health flag rides the same
            # computation the token fetch already synchronizes on.
            logits = logits + poison[:, None]
            bad = ~jnp.all(jnp.isfinite(logits), axis=-1)
            nxt = sample_logits_per_slot(
                logits, base_key, seeds, nprod, temp, top_k)
            return nxt, logits, bad, view

        def prefill_step(params, stacked, prompt, last_pos, block_ids,
                         view, seed, nprod):
            logits, view = model.paged_prefill(
                params, prompt, last_pos, block_ids, view, bt,
                stacked=stacked,
            )
            nxt = sample_logits_at(logits, base_key, seed, nprod, temp,
                                   top_k)
            return nxt, view

        # the pool view is DONATED through both programs: each step
        # aliases the pool buffers instead of copying the whole pool
        self._decode_fn = _kwrap(jax.jit(decode_step, donate_argnums=(2,)))
        self._prefill_fn = _kwrap(
            jax.jit(prefill_step, donate_argnums=(5,)))
        # "h.*" compute-dtype cast once — params are frozen while serving
        self._stacked = jax.jit(model.stacked_compute_params)(params)
        # shared-prefix suffix prefill: when admission aliased m full
        # blocks, only the UNMATCHED suffix runs — a span program (the
        # spec-verify attention pointed at prefill): suffix tokens
        # embed at their absolute positions, attend to the aliased
        # prefix through the block tables plus themselves under the
        # windowed causal mask, the first token samples at the true
        # last prompt position, and the suffix K/V commits through
        # `paged_append_span` (pad offsets past `count` route to
        # scratch).  Compiled per power-of-two suffix bucket, exactly
        # like the full prefill's prompt buckets.
        if config.prefix_cache:
            block_size = c.block_size

            def prefill_suffix_step(params, stacked, span, tables, pos0,
                                    last_off, count, view, seed, nprod):
                k1 = span.shape[1]
                positions = jnp.minimum(
                    pos0[:, None] + jnp.arange(k1)[None, :],
                    block_size - 1)
                x = model._embed_decode_span(params, span, positions)
                page = page_ref(tables, pos0, bt)
                x, sks, svs = model.paged_verify(stacked, x, view, page)
                logits = model.head(params, x, position=last_off)[:, 0]
                nxt = sample_logits_at(logits, base_key, seed, nprod,
                                       temp, top_k)
                view = paged_append_span(view, sks, svs, tables, pos0,
                                         count, bt)
                return nxt, view

            self._prefill_suffix_fn = _kwrap(
                jax.jit(prefill_suffix_step, donate_argnums=(7,)))
        else:
            self._prefill_suffix_fn = None
        # speculative decoding: the drafter + ONE compiled verify
        # program (serving/spec.py); imported lazily so the spec-off
        # engine's import graph — and its compiled programs — are
        # exactly the pre-spec ones
        if config.spec_draft is not None:
            from ..models.sampling import spec_prefill_commit
            from .spec import SpecDecoder
            self._spec = SpecDecoder(model, params, config, base_key,
                                     max_seq=self.max_seq)
            # a forced paged-kernel mode must cover EVERY compiled
            # program on the spec path, not just the engine's own: the
            # verify span program, and a model drafter's paged
            # prefill/rollout jits (they ride the same paged attention
            # and trace just as lazily) — otherwise a forced-"off"
            # A/B arm would still run the kernel inside the drafter
            self._spec._verify = _kwrap(self._spec._verify)
            for prog in ("_rollout", "_prefill"):
                if hasattr(self._spec.drafter, prog):
                    setattr(self._spec.drafter, prog,
                            _kwrap(getattr(self._spec.drafter, prog)))
            # the span horizon: growth/admission must own blocks out to
            # pos + spec_k so accepted drafts' K/V always land in-table
            self._span_k = config.spec_k

            def prefill_step_spec(params, stacked, prompt, last_pos,
                                  block_ids, view, seed, nprod, prop):
                logits, view = model.paged_prefill(
                    params, prompt, last_pos, block_ids, view, bt,
                    stacked=stacked,
                )
                # a spec engine commits EVERY position through the one
                # accept-or-residual rule — `prop` is the drafter's
                # proposal for this position, so a re-admission (whose
                # first token lands here instead of mid-verify) draws
                # the same token the undisturbed run committed
                nxt = spec_prefill_commit(logits, prop, base_key, seed,
                                          nprod, temp, top_k)
                return nxt, view

            self._prefill_fn = _kwrap(
                jax.jit(prefill_step_spec, donate_argnums=(5,)))
        else:
            self._spec = None
            self._span_k = 0

    # -- public API ---------------------------------------------------------

    @property
    def journal(self) -> Optional[RequestJournal]:
        return self._journal

    @journal.setter
    def journal(self, j: Union[None, str, RequestJournal]) -> None:
        """Attach a request journal (path or instance) and stamp THIS
        engine's serving geometry into it — `recover()` validates that
        stamp against the recovering engine up front, so a journal
        replayed onto a mismatched sibling fails with both geometries
        named instead of deep inside pool scatter."""
        self._journal = RequestJournal(j) if isinstance(j, str) else j
        if self._journal is not None:
            self._journal.geometry(self._geometry())

    def _geometry(self) -> Dict[str, int]:
        """The compiled serving shapes replay-exactness depends on:
        a sibling engine must share ALL of these for a journal replay
        to re-prefill and continue token-exact."""
        c = self.model.config
        return dict(
            block_size=int(c.block_size),
            max_seq_tokens=int(self.max_seq),
            vocab=int(c.vocab_size),
            block_tokens=int(self.config.block_tokens),
        )

    def attach_slo(self, tracker) -> None:
        """Attach an SLO error-budget tracker (telemetry/slo.py): every
        terminal request is observed, fast burn arms the flight ring.
        A METHOD (not a bare attr) so chaos/fleet wrappers can fan it
        out — setattr on a delegating wrapper would strand the tracker
        on the wrapper while the inner engine reads its own None."""
        self.slo = tracker

    def attach_live(self, aggregator) -> None:
        """Attach a live-plane aggregator (telemetry/live.py): each
        tick pushes the registry snapshot for the /metrics exporter."""
        self.live = aggregator

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               deadline_s: Optional[float] = None,
               seed: Optional[int] = None,
               tenant: Optional[str] = None) -> Request:
        """Queue one request; returns its handle (tokens accumulate on
        it as ticks produce them).  `deadline_s` attaches a completion
        SLO (seconds from now); `seed` pins the temperature>0 sampling
        stream (default: the request id); `tenant` tags the request's
        owner when multi-tenancy is configured — its policy's SLO-class
        deadline applies when the request carries none, and its door
        watermark/budget/weight govern admission.  Above any admission
        watermark the request comes back already terminal with
        status "shed" — check `req.status`, not an exception: overload
        is an expected outcome, a malformed request is not (those still
        raise ValueError)."""
        c = self.model.config
        if len(prompt) < 1 or max_new_tokens < 1:
            raise ValueError("need a non-empty prompt and >= 1 new token")
        total = len(prompt) + max_new_tokens
        if total > self.max_seq:
            raise ValueError(
                f"prompt {len(prompt)} + new {max_new_tokens} tokens > "
                + (f"max_seq_tokens {self.max_seq}"
                   if self.max_seq < c.block_size
                   else f"block_size {c.block_size}")
            )
        worst = -(-total // self.config.block_tokens)
        if worst > self.pool.num_usable:
            raise ValueError(
                f"request needs up to {worst} blocks but the pool has "
                f"{self.pool.num_usable} — raise num_blocks or shrink "
                "the request"
            )
        cfg = self.config
        if deadline_s is None and isinstance(self._queue, TenantQueue):
            # SLO class: the tenant's default completion deadline
            deadline_s = self._queue.policy(tenant).deadline_s
        req = Request(prompt, max_new_tokens, deadline_s=deadline_s,
                      seed=seed, tenant=tenant)
        self._count("serve_submitted")
        if isinstance(self._queue, TenantQueue):
            tq = self._queue.policy(tenant).max_queue
            if tq is not None and self._queue.depth(tenant) >= tq:
                # the isolation primitive: a flooding tenant's overflow
                # sheds at ITS OWN watermark and never reaches the
                # shared queue/pool
                self._queue.note_shed(tenant)
                self._shed_req(req, "tenant_queue_watermark")
                return req
        if cfg.max_queue is not None and len(self._queue) >= cfg.max_queue:
            self._shed_req(req, "queue_watermark")
            return req
        if (cfg.shed_pool_util is not None and self._queue
                # raw utilization first (O(1)): effective <= raw, so
                # the O(tree) reclaimable walk only runs when the raw
                # number already trips the watermark
                and (self.pool.blocks_in_use / self.pool.num_usable
                     >= cfg.shed_pool_util)
                and self._effective_pool_util() >= cfg.shed_pool_util):
            self._shed_req(req, "pool_watermark")
            return req
        if self.journal is not None:
            # admissions are durable at submit time (one fsync per
            # submit; token lines batch per tick) — a crash right after
            # submit() still replays the request
            self.journal.submit(req)
            req._journaled = True
            self.journal.commit()
        self._queue.append(req)
        return req

    def tick(self, *, decode: bool = True) -> int:
        """One scheduler step: enforce deadlines -> admit ->
        grow/preempt -> one decode step for every active slot ->
        quarantine/evict.  Returns the number of tokens produced
        (prefill first-tokens included).

        `decode=False` stops after admission — the PREFILL half of a
        disaggregated pair (fleet/disagg.py): prompts prefill into this
        engine's pool and first tokens sample, but no decode step runs;
        the admitted slots park until `export_request` hands them to a
        decode engine.

        Any exception out of the tick body (a poisoned pool view, a
        chaos-injected prefill failure) trips the watchdog warm restart
        when the health guard is on: in-flight requests re-queue
        front-of-line and continue token-exact.  `ServingKilled` (the
        chaos stand-in for process death) always propagates — a real
        kill leaves no engine to restart."""
        t0 = time.monotonic()
        tick_i = self._ticks
        self._seg = {"prefill_s": 0.0, "decode_s": 0.0, "fetch_s": 0.0,
                     "draft_s": 0.0}
        self._tick_counts = dict.fromkeys(self._tick_counts, 0)
        try:
            produced = self._tick_body(decode=decode)
        except ServingKilled:
            raise
        except Exception as e:
            if self._guard is None:
                raise
            self._warm_restart(f"tick exception: {type(e).__name__}: {e}")
            produced = 0
        if self.journal is not None:
            self.journal.commit()
        self._ticks += 1
        if produced:
            self._restarts_since_progress = 0
        self._update_gauges()
        self._record_tick(tick_i, t0, produced)
        if self.live is not None and self.telemetry is not None:
            # push the tick's registry snapshot into the live plane:
            # plain host dicts (floats), so the exporter thread can
            # never reach a device value through the aggregator
            self.live.ingest(self.telemetry.snapshot(),
                             replica=self.replica_id)
        return produced

    def drain(self, max_ticks: Optional[int] = None) -> int:
        """Tick until every submitted request is done; returns total
        tokens produced.  `max_ticks` bounds runaway loops in tests."""
        total = 0
        ticks = 0
        while self._queue or any(s is not None for s in self._slots):
            total += self.tick()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(
                    f"drain exceeded {max_ticks} ticks with "
                    f"{len(self._queue)} queued"
                )
        return total

    def recover(self, journal: Union[None, str] = None, *,
                adopt: Optional[Dict[int, Request]] = None
                ) -> List[Request]:
        """Re-queue a crashed engine's in-flight requests from its
        journal, FRONT of the queue in their original admission order,
        each with the token prefix the journal had committed — they
        continue through the preemption resume path (re-prefill
        prompt + produced), token-exact under the (seed, position)
        sampling keys.  Requests the journal shows ALREADY finished —
        every token produced, or an eos in the prefix — but whose end
        line was torn away are closed out "ok" directly (re-queuing an
        eos-finished request would decode PAST its eos and diverge
        from the uninterrupted run).  Returns the
        re-queued handles.  Call on a FRESH engine built with the same
        model/params/config as the dead one (exactness needs the same
        programs); latency marks restart at recovery time.

        The journal's geometry stamp is validated against THIS engine
        up front — replay is only exact onto the same compiled shapes,
        and failover (fleet/failover.py) made the mismatched-sibling
        path load-bearing: without the check it fails deep inside pool
        scatter with no hint which side is wrong.

        Prefix cache: a recovering engine starts WARM-FROM-EMPTY — the
        radix tree indexed the dead engine's pool, which died with it.
        Replay is exact regardless (the cache only changes where K/V
        is read from, never the committed tokens), and the re-admitted
        requests re-warm the tree as they prefill.

        `adopt` maps request id -> an EXISTING Request handle to reuse
        (fleet failover: the dead replica's callers keep their handles
        — the sibling resets each to its committed prefix and continues
        it, so `submit`-returned objects survive engine loss).  When
        this engine journals to a DIFFERENT file than `journal`, every
        recovered request is re-journaled here (submit + committed
        prefix): the sibling's own journal stays self-contained for a
        second failure."""
        path = journal
        if path is None:
            if self.journal is None:
                raise ValueError(
                    "recover() needs a journal path (or an engine "
                    "constructed with journal=)"
                )
            path = self.journal.path
        geom = RequestJournal.read_geometry(path)
        if geom is not None:
            mine = self._geometry()
            bad = {k: (geom[k], mine[k]) for k in mine
                   if k in geom and geom[k] != mine[k]}
            if bad:
                raise ValueError(
                    "journal/engine geometry mismatch — replaying "
                    f"{path} onto this engine would fail inside pool "
                    "scatter (replay is only exact onto the same "
                    "compiled shapes): " + ", ".join(
                        f"{k}: journal={j} vs engine={e}"
                        for k, (j, e) in sorted(bad.items()))
                )
        # re-journal into a DIFFERENT journal than the one replayed:
        # the failover path, where the sibling's WAL must become
        # self-contained for the requests it adopts
        cross = (self.journal is not None
                 and os.path.abspath(self.journal.path)
                 != os.path.abspath(path))
        interrupted, done_ids = RequestJournal.replay(path)
        out: List[Request] = []
        max_seen = max(
            [e["id"] for e in interrupted] + done_ids, default=-1)
        for e in interrupted:
            req = adopt.get(e["id"]) if adopt else None
            if req is not None:
                # the caller's live handle: reset to the journal's
                # committed prefix (tokens past the last commit died
                # with the engine; re-decoding reproduces them exactly)
                # and keep its lifecycle/attribution history — the
                # abandon() that closed the dead engine already opened
                # the restart-overhead wait window
                now = time.monotonic()
                req.tokens = list(e["tokens"])
                # per-token latency entries past the committed prefix
                # belong to tokens that died with the engine — the
                # re-decode appends fresh ones
                req.token_lat = req.token_lat[:len(req.tokens)]
                req.state = "queued"
                req.status = None
                req.finish_reason = None
                if req._wait_since is None:
                    req._wait_since, req._wait_kind = now, "restart"
                req.event("recovered", now, replica=self.replica_id)
            else:
                req = Request(e["prompt"], e["max_new"],
                              deadline_s=e["deadline_s"], seed=e["seed"],
                              id=e["id"], tenant=e.get("tenant"),
                              trace_id=e.get("trace"))
                req.tokens = list(e["tokens"])
                # the wait from recovery to re-admission is restart
                # overhead, not queue wait: the crash-restart cycle (not
                # arrival pressure) is what the request is paying for
                req._wait_kind = "restart"
                req.event("recovered", req.t_arrival,
                          replica=self.replica_id)
            if cross:
                self.journal.submit(req)
                self.journal.tokens(req.id, req.tokens)
            req._journaled = self.journal is not None
            if self._finished(req):
                # finished before the crash (length OR eos) — only its
                # end line was lost; close it out, never re-queue
                self._terminal(req, "ok", req.finish_reason)
            else:
                out.append(req)
        for req in reversed(out):
            self._queue.appendleft(req)
        # keep fresh ids clear of everything the journal ever issued
        nxt = next(Request._ids)
        Request._ids = itertools.count(max(nxt, max_seen + 1))
        self._count("serve_recovered", len(out))
        if self.journal is not None:
            self.journal.commit()  # the closed-out requests' end lines
        # postmortem marker: a fresh engine has no tick lead-up (it died
        # with the old process), but the flush stamps the recovery and
        # how many requests re-queued into the metrics stream
        if self._flight is not None and self.logger is not None:
            self._flight.flush(self.logger, "serve_recover",
                               at_step=self._ticks,
                               **({"replica_id": self.replica_id}
                                  if self.replica_id is not None else {}))
        return out

    # -- disaggregation hooks (fleet/disagg.py) -----------------------------

    def export_request(self, i: int) -> KVHandoff:
        """Pop active slot `i` and hand its request off WITH its paged
        K/V block contents — the source half of a disaggregated
        prefill->decode migration.  The payload leaves in the pool's
        resting dtype (a quantized pool migrates 1-byte blocks +
        scales, the same 4x compression it rests at); the slot's blocks
        return to this engine's free list immediately (the gather
        materialized fresh arrays).  The request re-opens a wait window
        — billed to migration-wait (`comp_migrate_s`) — until the
        importing engine seats it."""
        slot = self._slots[i]
        if slot is None:
            raise ValueError(f"slot {i} is empty — nothing to export")
        req = slot.req
        now = time.monotonic()
        payload = export_blocks(self.pool.view, slot.table)
        self.pool.free_blocks(slot.table)
        self._slots[i] = None
        self._close_active(req, slot, now)
        req.state = "queued"
        # the window until the importing engine seats it is MIGRATION
        # wait, not queue wait: the request isn't contending for this
        # engine's slots, it's paying the cross-engine handoff — the
        # component serve_report's cross-engine tail attribution reads
        req._wait_since, req._wait_kind = now, "migrate"
        req.event("exported", now, i, replica=self.replica_id)
        return KVHandoff(req=req, payload=payload, pos=slot.pos,
                         last=slot.last,
                         block_tokens=self.config.block_tokens,
                         src_replica=self.replica_id)

    def can_import(self, n_blocks: int) -> bool:
        """Whether `import_request` of an `n_blocks` payload would seat
        right now — a free decode slot and enough free pool blocks.
        The disagg coordinator checks BEFORE exporting so a handoff is
        never left in limbo between two engines."""
        return (None in self._slots
                and self.pool.blocks_free >= n_blocks)

    def import_request(self, handoff: KVHandoff) -> bool:
        """Seat an exported request — the destination half of the
        migration: allocate blocks, scatter the payload into them, and
        occupy a decode slot at the handoff's (pos, last) coordinates,
        WITHOUT re-running prefill (the K/V moved instead).  Returns
        False (nothing consumed) when no slot or blocks are free;
        geometry/dtype mismatches between the pools raise with both
        sides named (serving/pool.import_blocks)."""
        if self._spec is not None:
            raise ValueError(
                "import_request on a speculative engine is unsupported "
                "— drafter state only rebuilds through the prefill "
                "admission path"
            )
        if handoff.block_tokens != self.config.block_tokens:
            raise ValueError(
                f"paged-KV migration geometry mismatch: payload blocks "
                f"hold {handoff.block_tokens} tokens but this engine's "
                f"hold {self.config.block_tokens}"
            )
        n = int(handoff.payload.k.shape[0])
        if n > self.max_blocks_per_req:
            raise ValueError(
                f"{n}-block payload exceeds this engine's "
                f"{self.max_blocks_per_req}-block table width "
                f"(max_seq_tokens={self.max_seq}) — source and "
                "destination engines must share max_seq_tokens"
            )
        try:
            slot_i = self._slots.index(None)
        except ValueError:
            return False
        ids = self.pool.alloc(n)
        if ids is None:
            return False
        self.pool.view = import_blocks(self.pool.view, ids,
                                       handoff.payload)
        req = handoff.req
        now = time.monotonic()
        if req._wait_since is not None:
            req.lat_components[req._wait_kind] += now - req._wait_since
            req._wait_since = None
        if req.t_admitted is None:
            req.t_admitted = now
        req.event("imported", now, slot_i, replica=self.replica_id)
        req.last_slot = slot_i
        req.state = "active"
        self._slots[slot_i] = _Slot(req, table=ids, pos=handoff.pos,
                                    last_token=handoff.last,
                                    admitted_at=now, prefill_s=0.0)
        self._count("serve_admissions")
        return True

    # -- fleet failover hooks (fleet/failover.py) ---------------------------

    def abandon(self) -> None:
        """Mark this engine DEAD after a fatal fault: close every active
        request's window (billed to restart-overhead — the engine, not
        the scheduler, took the slot away), clear the queue (the journal
        is the durable copy a sibling replays), and close the journal
        WITHOUT committing its buffer — an in-process death must look on
        disk exactly like a SIGKILL between append and fsync.  The pool
        is left as-is: it died with the engine."""
        now = time.monotonic()
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            s.req.state = "queued"
            self._close_active(s.req, s, now)
            s.req._wait_since, s.req._wait_kind = now, "restart"
            s.req.event("engine_lost", now, i,
                        replica=self.replica_id)
        self._slots = [None] * self.config.max_active
        for req in self._queue:
            req.event("engine_lost", now, replica=self.replica_id)
        self._queue.clear()
        self._poison_pending.clear()
        if self._journal is not None:
            self._journal.abandon()

    @property
    def n_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def restarts(self) -> int:
        return self._restarts

    def active_slots(self) -> List[int]:
        """Indices of occupied decode slots (chaos targets these)."""
        return [i for i, s in enumerate(self._slots) if s is not None]

    def active_block_tables(self) -> dict:
        """{request id: list of physical block ids} for every active
        slot — what the pool-accounting acceptance sums against
        pool.blocks_in_use at each tick."""
        return {s.req.id: list(s.table)
                for s in self._slots if s is not None}

    def poison_slot(self, i: int) -> None:
        """Arm a NaN poison on slot i's logits for the NEXT decode step
        (the chaos harness's slot-poison fault — resilience/chaos.py).
        The poison rides a per-slot operand that is 0.0 off-path, so an
        unpoisoned tick's tokens are bit-identical.  The fault model is
        SLOT-addressed (a bad device lane), not request-addressed: it
        hits whichever request occupies slot i at that decode step —
        which can differ from the occupant at arm time if the scheduler
        reseats the slot earlier in the same tick.  A tick that runs no
        decode step discards the arm rather than letting it linger."""
        if not 0 <= i < self.config.max_active:
            raise ValueError(f"slot {i} out of range")
        self._poison_pending.add(i)

    def arm_prefill_exception(self, exc: BaseException) -> None:
        """Arm ONE exception raised at the next admission's prefill
        (chaos "prefill_raise"): the request re-queues, the watchdog
        warm-restarts."""
        self._prefill_exc = exc

    def _prefix_saved_bytes(self) -> int:
        """Pool bytes sharing is saving RIGHT NOW, measured from the
        refcounts: every holder beyond a block's first would need its
        own physical block without aliasing.  Block bytes come off the
        device arrays' dtypes (resting dtype + scales), not a model."""
        if self._prefix is None:
            return 0
        excess = sum(n - 1 for n in self.pool.ref_counts().values()
                     if n > 1)
        if not excess:
            return 0
        kb = self.pool.kv_bytes()
        total_blocks = self.pool.num_usable + 1  # + scratch
        return int(excess * kb["total_bytes"] / total_blocks)

    def prefix_stats(self) -> Optional[Dict]:
        """Shared-prefix cache outcomes (None with the cache off):
        hit rate = prompt tokens aliased / prompt tokens admitted,
        plus the raw counters and the measured bytes-of-pool saved."""
        if self._prefix is None:
            return None
        pc = self._prefix
        return {
            "hit_rate": round(
                pc.tokens_avoided / max(1, pc.prompt_tokens), 4),
            "hits": pc.hits, "misses": pc.misses,
            "blocks_aliased": pc.blocks_aliased,
            "prefill_tokens_avoided": pc.tokens_avoided,
            "prompt_tokens": pc.prompt_tokens,
            "cached_blocks": len(pc),
            "tree_evictions": pc.evicted,
            "pool_saved_bytes": self._prefix_saved_bytes(),
        }

    def tenant_stats(self) -> Optional[Dict]:
        """Per-tenant scheduler accounting (None without tenants):
        queued depth, admitted token cost, weight, door sheds, and
        budget utilization when a budget is configured."""
        if not isinstance(self._queue, TenantQueue):
            return None
        return self._queue.stats()

    def tenant_queue_full(self, tenant: Optional[str]) -> bool:
        """Whether a submit() for `tenant` would shed at its own door
        watermark right now — the fleet router's tenant-aware door
        check (fleet/router.py)."""
        if not isinstance(self._queue, TenantQueue):
            return False
        tq = self._queue.policy(tenant).max_queue
        return tq is not None and self._queue.depth(tenant) >= tq

    def describe(self) -> str:
        q = self.config.quant or str(jnp.dtype(self.pool.view.k.dtype))
        spec = (f", {self._spec.describe()}"
                if self._spec is not None else "")
        extras = ""
        if self._prefix is not None:
            extras += f", prefix_cache={len(self._prefix)} blocks"
        if isinstance(self._queue, TenantQueue):
            extras += f", tenants={len(self.config.tenants)}"
        return (
            f"serving(max_active={self.config.max_active}, "
            f"blocks={self.pool.num_usable}x"
            f"{self.config.block_tokens}, cache={q}, "
            f"guard={'on' if self._guard else 'off'}{spec}{extras})"
        )

    # -- scheduler internals ------------------------------------------------

    def _tick_body(self, decode: bool = True) -> int:
        if isinstance(self._queue, TenantQueue):
            self._queue.on_tick()  # per-tenant budget accrual
        self._enforce_deadlines(time.monotonic())
        # growth first: existing slots claim the blocks their next write
        # needs BEFORE admission can take them — the other order lets a
        # fresh admission strand a grower, whose preempt-youngest victim
        # is then the just-prefilled request (a full prefill thrown away
        # per block boundary while the pool is tight)
        self._grow()
        produced = self._admit()
        active = [(i, s) for i, s in enumerate(self._slots)
                  if s is not None]
        if active and decode:
            if self._spec is not None:
                produced += self._decode_spec(active)
            else:
                produced += self._decode_plain(active)
        else:
            # no decode step ran: a poison armed for this tick must not
            # linger and hit whatever occupies the slot ticks later
            self._poison_pending.clear()
        return produced

    def _slot_arrays(self, active):
        """The decode/verify programs' per-slot operand vectors (empty
        slots carry scratch coordinates — branch-free, shape-stable)."""
        S = self.config.max_active
        tokens = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        seeds = np.zeros((S,), np.int32)
        nprod = np.zeros((S,), np.int32)
        poison = np.zeros((S,), np.float32)
        tables = np.full((S, self.max_blocks_per_req), SCRATCH_BLOCK,
                         np.int32)
        for i, s in active:
            tokens[i] = s.last
            pos[i] = s.pos
            seeds[i] = s.req.seed
            nprod[i] = len(s.req.tokens)
            tables[i, :len(s.table)] = s.table
        if self._poison_pending:
            for i in self._poison_pending:
                poison[i] = np.nan
            self._poison_pending.clear()
        return tokens, pos, seeds, nprod, poison, tables

    def _decode_plain(self, active) -> int:
        """One token for every active slot — the exact pre-speculation
        decode tick (spec off compiles and runs only this path)."""
        produced = 0
        tokens, pos, seeds, nprod, poison, tables = \
            self._slot_arrays(active)
        t_dec = time.monotonic()
        nxt, logits, bad, view = self._decode_fn(
            self.params, self._stacked, self.pool.view,
            tokens, pos, tables, seeds, nprod, poison,
        )
        # dispatch returns before the device finishes (async); the
        # np.asarray token fetch below is the sync — the tick record
        # splits the two (decode_s vs fetch_s)
        t_disp = time.monotonic()
        self.pool.view = view
        self.last_logits = logits
        nxt = np.asarray(nxt)
        # same computation, already synchronized by the token fetch
        bad = np.asarray(bad)
        tnow = time.monotonic()
        self._seg["decode_s"] += t_disp - t_dec
        self._seg["fetch_s"] += tnow - t_disp
        self._gap_hist.append(tnow - t_dec)
        poisoned = (set(self._guard.observe(bad, [i for i, _ in
                                                  active]))
                    if self._guard is not None else set())
        for i, s in active:
            if i in poisoned:
                self._quarantine(i, s)
                continue
            t = int(nxt[i])
            s.pos += 1
            s.last = t
            self._append_token(s.req, t, tnow)
            if self.journal is not None:
                self.journal.tokens(s.req.id, [t])
            produced += 1
            if self._finished(s.req):
                self._finish(i, s)
        if self._guard is not None and self._guard.should_restart:
            self._warm_restart(
                f"{self._guard.consecutive_poisoned} consecutive "
                "poisoned decode ticks"
            )
        return produced

    def _decode_spec(self, active) -> int:
        """Speculative tick: drafter proposes up to K tokens per slot,
        ONE verify pass through the target scores all K+1 span
        positions, and 1..K+1 tokens commit per surviving slot.  Only
        VERIFIED tokens ever reach the request, the journal, or the
        pool (the verify program routes rejected-draft K/V to scratch);
        quarantine, the watchdog, and the deadline machinery see the
        same per-slot surface as the plain path."""
        k = self._spec.k
        produced = 0
        t_draft = time.monotonic()
        drafts = self._spec.propose(self._slots)  # (S, K+1) int32
        t_mid = time.monotonic()
        self._seg["draft_s"] += t_mid - t_draft
        tokens, pos, seeds, nprod, poison, tables = \
            self._slot_arrays(active)
        S = self.config.max_active
        # [head, d_1..d_K, extra]: columns 0..K are the scored span,
        # the trailing extra is the bonus position's proposal
        span = np.zeros((S, k + 2), np.int32)
        span[:, 0] = tokens
        span[:, 1:] = drafts
        # the last position whose K/V this request will ever need
        # (total-2: the final token's K/V is never read); -1 parks
        # empty slots at count 0 — every write routes to scratch
        limit_kv = np.full((S,), -1, np.int32)
        for i, s in active:
            limit_kv[i] = (len(s.req.prompt) + s.req.max_new_tokens - 2)
        t_dec = time.monotonic()
        acc, final, bad, view = self._spec.verify(
            self.params, self._stacked, self.pool.view,
            span, pos, tables, seeds, nprod, limit_kv, poison,
        )
        t_disp = time.monotonic()
        self.pool.view = view
        acc = np.asarray(acc)
        final = np.asarray(final)
        bad = np.asarray(bad)
        tnow = time.monotonic()
        self._seg["decode_s"] += t_disp - t_dec
        self._seg["fetch_s"] += tnow - t_disp
        poisoned = (set(self._guard.observe(bad, [i for i, _ in
                                                  active]))
                    if self._guard is not None else set())
        eos = self.config.eos_id
        committed = 0
        for i, s in active:
            if i in poisoned:
                self._quarantine(i, s)
                continue
            n_acc = int(acc[i])
            toks = [int(t) for t in span[i, 1:1 + n_acc]]
            toks.append(int(final[i]))
            remaining = s.req.max_new_tokens - len(s.req.tokens)
            toks = toks[:remaining]
            if eos is not None and eos in toks:
                toks = toks[:toks.index(eos) + 1]  # keep the eos itself
            s.req.spec_proposed += k
            s.req.spec_accepted += min(n_acc, len(toks))
            self._spec_proposed += k
            self._spec_accepted += min(n_acc, len(toks))
            for t in toks:
                self._append_token(s.req, t, tnow)
            if self.journal is not None:
                self.journal.tokens(s.req.id, toks)
            s.pos += len(toks)
            s.last = toks[-1]
            produced += len(toks)
            committed += len(toks)
            if self._finished(s.req):
                self._finish(i, s)
        # deadline price: this tick's wall per COMMITTED token — the
        # draft+verify wall amortizes over the span yield, so a
        # high-acceptance tick prices CHEAPER per token than its raw
        # (bimodal) wall suggests
        wall = tnow - t_draft
        if committed:
            self._gap_hist.append(wall * len(active) / committed)
            self._spec_ticks += 1
            self._spec_tokens += committed
        if self._guard is not None and self._guard.should_restart:
            self._warm_restart(
                f"{self._guard.consecutive_poisoned} consecutive "
                "poisoned decode ticks"
            )
        return produced

    def _gap_p50(self) -> Optional[float]:
        """Median measured decode wall PER COMMITTED TOKEN — the
        inter-token service price for deadline feasibility.  On the
        plain path each entry is a decode-tick wall (one token per slot
        per tick); under speculation each entry is the tick wall scaled
        by its per-slot token yield, so shedding prices the tokens
        actually delivered instead of over-firing on the bimodal
        draft+verify tick walls.  None until warm (a cold engine's
        first walls are XLA compiles, not service time)."""
        if len(self._gap_hist) < _MIN_GAP_SAMPLES:
            return None
        return float(np.median(np.asarray(self._gap_hist)))

    def _enforce_deadlines(self, now: float) -> None:
        """Shed queued requests that cannot meet their deadline; expire
        active ones that already blew it."""
        if self._queue and any(r.deadline is not None
                               for r in self._queue):
            gap = self._gap_p50()
            for req in list(self._queue):
                dl = req.deadline
                if dl is None:
                    continue
                reason = None
                if now >= dl:
                    reason = "deadline_overdue"
                else:
                    remaining = req.max_new_tokens - len(req.tokens)
                    # +1 tick for the prefill it still has to pay
                    if (gap is not None
                            and now + (remaining + 1) * gap > dl):
                        reason = "deadline_unmeetable"
                if reason is not None:
                    # remove() works on the plain deque AND the tenant
                    # queue (which keeps its per-tenant FIFOs intact)
                    self._queue.remove(req)
                    if isinstance(self._queue, TenantQueue):
                        self._queue.note_shed(req.tenant)
                    self._shed_req(req, reason)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            dl = s.req.deadline
            if dl is not None and now > dl:
                self._expire(i, s)

    def _bucket(self, p: int) -> int:
        """Prefill pad length: the smallest power-of-two multiple of
        block_tokens >= p (compiled prefill shapes stay O(log T))."""
        bt = self.config.block_tokens
        nb = -(-p // bt)
        b = 1
        while b < nb:
            b *= 2
        return min(b * bt, self.model.config.block_size)

    def _bucket_span(self, n: int) -> int:
        """Suffix-prefill pad length: the smallest power of two >= n
        (no block-multiple constraint — the span program commits
        through `count`, not a scatter panel)."""
        b = 1
        while b < n:
            b *= 2
        return min(b, self.model.config.block_size)

    def _prefill_operands(self, prompt_now: List[int], ids: List[int]):
        """The full-prompt prefill program's (padded prompt, block-id
        panel) operands — shared by the plain and spec admission
        paths."""
        p = len(prompt_now)
        bt = self.config.block_tokens
        bucket = self._bucket(p)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = prompt_now
        block_ids = np.full((bucket // bt,), SCRATCH_BLOCK, np.int32)
        # the prefill panel only spans the bucket; the +1 decode
        # block can lie past it (boundary p == bucket) — it is
        # reached through the slot table, not the prefill scatter
        k = min(len(ids), bucket // bt)
        block_ids[:k] = ids[:k]
        return padded, block_ids

    def _next_queued(self) -> Optional[Request]:
        """The next admission candidate: FIFO head, or the tenant
        queue's stride-selected request — None when requests are
        queued but every busy tenant is over budget this tick."""
        if isinstance(self._queue, TenantQueue):
            return self._queue.peek()
        return self._queue[0]

    def _pop_queued(self, req: Request) -> None:
        if isinstance(self._queue, TenantQueue):
            self._queue.pop(req)  # charges the tenant's pass + budget
        else:
            self._queue.popleft()

    def _alloc(self, n: int) -> Optional[List[int]]:
        """pool.alloc with prefix-tree reclaim: under pressure the
        radix tree yields its LRU unreferenced leaves (warm cache, no
        live holder) BEFORE the scheduler resorts to preemption —
        cached blocks are an optimization, never a reason to evict a
        running request."""
        ids = self.pool.alloc(n)
        if ids is None and self._prefix is not None:
            if self._prefix.evict(self.pool,
                                  need=n - self.pool.blocks_free):
                ids = self.pool.alloc(n)
        return ids

    def _effective_pool_util(self) -> float:
        """Pool utilization for the shed watermark: allocated blocks
        minus what the prefix tree could reclaim right now — a pool
        full of warm cache is not overloaded, and counting it would
        turn the cache itself into a shed trigger."""
        used = self.pool.blocks_in_use
        if self._prefix is not None:
            used -= self._prefix.reclaimable(self.pool)
        return used / self.pool.num_usable

    def _admit(self) -> int:
        """Admission: prefill queued requests into free slots while the
        pool can hold their prompts — FIFO (head-of-line blocking is
        deliberate: skipping ahead would starve long prompts), or the
        weighted-fair tenant schedule when tenants are configured.
        With the prefix cache on, admission first walks the radix tree:
        matched full blocks alias into the block table (refcounted)
        and only the unmatched suffix pays a prefill."""
        produced = 0
        while self._queue:
            try:
                slot_i = self._slots.index(None)
            except ValueError:
                break
            req = self._next_queued()
            if req is None:
                break  # every queued tenant over budget until next tick
            prompt_now = req.prompt + req.tokens  # preemption continuation
            p = len(prompt_now)
            bt = self.config.block_tokens
            # shared-prefix match: alias at most (p-1)//bt full blocks
            # — at least one prompt token always remains for the
            # suffix program (which also samples the first token), and
            # every block the request will WRITE stays private
            alias: List[int] = []
            if self._prefix is not None:
                alias = self._prefix.match(
                    prompt_now, limit=(p - 1) // bt, tick=self._ticks)
                if alias:
                    # pin the aliased blocks (this table's refcount)
                    # BEFORE allocating: the fresh-block alloc may
                    # evict tree leaves, and a matched node must not
                    # be reclaimed out from under its own admission
                    self.pool.share(alias)
            # blocks for the prompt AND its first decode write (position
            # p): same count as ceil(p/bt) except when p lands exactly
            # on a block boundary — without the extra block that first
            # decode write would land in the scratch block (lost K/V),
            # or need a _grow after admission that can preempt the
            # admission itself.  Under speculation the first write is a
            # whole span (positions p..p+spec_k), so the horizon —
            # clamped to the request's final position — replaces p:
            # same worst-case block count as the plain path, claimed up
            # front instead of across the first few grows
            ids_new = self._alloc(
                self._write_horizon(req, p) // bt + 1 - len(alias))
            if ids_new is None:
                if alias:
                    self.pool.free_blocks(alias)  # roll the pin back
                break
            ids = alias + ids_new
            self._pop_queued(req)
            if self._prefill_exc is not None:
                # chaos: the prefill "fails"; put everything back the
                # way a real mid-admission fault would find it and let
                # the watchdog take it from here
                exc, self._prefill_exc = self._prefill_exc, None
                self.pool.free_blocks(ids)
                if isinstance(self._queue, TenantQueue):
                    self._queue.refund(req)  # no work happened
                self._queue.appendleft(req)
                raise exc
            t_adm = time.monotonic()
            if req.t_admitted is None:
                req.t_admitted = t_adm
            # the wait window (queue / preempted-wait / restart-overhead,
            # whichever re-queued it) closes at the same stamp the active
            # window opens — the attribution components telescope
            if req._wait_since is not None:
                req.lat_components[req._wait_kind] += t_adm - req._wait_since
                req._wait_since = None
            req.event("admitted", t_adm, slot_i)
            req.last_slot = slot_i
            try:
                if alias:
                    # suffix prefill: the aliased blocks already hold
                    # positions < p0 — only the unmatched suffix runs,
                    # through the span program (padded to a power-of-
                    # two suffix bucket; pad offsets commit nothing)
                    p0 = len(alias) * bt
                    suffix = prompt_now[p0:]
                    k1 = self._bucket_span(len(suffix))
                    span = np.zeros((1, k1), np.int32)
                    span[0, :len(suffix)] = suffix
                    tables = np.full((1, self.max_blocks_per_req),
                                     SCRATCH_BLOCK, np.int32)
                    tables[0, :len(ids)] = ids
                    nxt, view = self._prefill_suffix_fn(
                        self.params, self._stacked, span, tables,
                        np.asarray([p0], np.int32),
                        np.int32(p - 1 - p0),
                        np.asarray([len(suffix)], np.int32),
                        self.pool.view, np.int32(req.seed),
                        np.int32(len(req.tokens)),
                    )
                elif self._spec is not None:
                    # the drafter rebuilds this slot's draft cache from
                    # the SAME committed prefix — the one admission
                    # path every resume (preemption, warm restart,
                    # recovery) rides, so drafter state never needs
                    # separate fault handling — and hands back its
                    # proposal for the first post-prefix position (the
                    # spec prefill's accept-or-residual operand)
                    prop = self._spec.on_admit(slot_i, prompt_now)
                    padded, block_ids = self._prefill_operands(
                        prompt_now, ids)
                    nxt, view = self._prefill_fn(
                        self.params, self._stacked, padded, p - 1,
                        block_ids, self.pool.view, np.int32(req.seed),
                        np.int32(len(req.tokens)), np.int32(prop),
                    )
                else:
                    padded, block_ids = self._prefill_operands(
                        prompt_now, ids)
                    nxt, view = self._prefill_fn(
                        self.params, self._stacked, padded, p - 1,
                        block_ids, self.pool.view, np.int32(req.seed),
                        np.int32(len(req.tokens)),
                    )
                self.pool.view = view
                tok = int(np.asarray(nxt)[0])
            except Exception:
                # a REAL prefill failure (transient XLA error, wedged
                # view): put the request back exactly like the chaos
                # path does, or the watchdog's restart — which only
                # re-queues OCCUPIED slots — would drop it in a
                # non-terminal limbo forever.  Re-opening the wait
                # window at the admission stamp keeps the latency
                # partition telescoping (the aborted window bills to
                # the wait bucket it interrupted).
                self.pool.free_blocks(ids)
                req.event("admission_aborted", time.monotonic(), slot_i)
                req._wait_since = t_adm
                if isinstance(self._queue, TenantQueue):
                    self._queue.refund(req)  # no work happened
                self._queue.appendleft(req)
                raise
            pf = time.monotonic() - t_adm
            self._seg["prefill_s"] += pf
            req.lat_components["prefill"] += pf
            if self._prefix is not None:
                # commit the prompt's full blocks to the radix tree —
                # new nodes take their own refcount, which is what
                # keeps them warm after this request's table frees
                self._prefix.insert(prompt_now, ids[:p // bt],
                                    self.pool, tick=self._ticks)
                self._prefix.note_admission(len(alias), p)
                req.prefix_blocks += len(alias)
                req.prefix_tokens += len(alias) * bt
            slot = _Slot(req, table=ids, pos=p, last_token=tok,
                         admitted_at=t_adm, prefill_s=pf)
            self._slots[slot_i] = slot
            req.state = "active"
            self._count("serve_admissions")
            self._tick_counts["admitted"] += 1
            self._append_token(req, tok, time.monotonic())
            if self.journal is not None:
                self.journal.tokens(req.id, [tok])
            produced += 1
            if self._finished(req):
                self._finish(slot_i, slot)
        return produced

    def _write_horizon(self, req: Request, pos: int) -> int:
        """The furthest position this slot's NEXT decode step may write:
        `pos` on the plain path (byte-for-byte the pre-spec behavior),
        `pos + spec_k` under speculation (the whole draft span's K/V
        must land in owned blocks), clamped to the request's LAST
        WRITABLE position total-2 — the final token's K/V is never
        written (nothing attends past it; the verify program's
        limit_kv routes those offsets to scratch), so growing a block
        for it would burst the plain path's worst-case block count and
        preempt neighbors for storage nobody fills."""
        if not self._span_k:
            return pos
        total = len(req.prompt) + req.max_new_tokens
        return min(pos + self._span_k, total - 2)

    def _grow(self) -> None:
        """Allocate the next block for any slot whose write horizon
        crossed a block boundary; on exhaustion, preempt the youngest
        active request until the grower fits (or is itself preempted)."""
        for i, slot in enumerate(self._slots):
            if slot is None or self._slots[i] is not slot:
                continue
            while (self._slots[i] is slot
                   and len(slot.table)
                   < self._write_horizon(slot.req, slot.pos)
                   // self.config.block_tokens + 1):
                ids = self._alloc(1)  # prefix tree yields before preemption
                if ids is not None:
                    slot.table.extend(ids)
                    continue
                victim_i, victim = max(
                    ((j, s) for j, s in enumerate(self._slots)
                     if s is not None),
                    key=lambda js: js[1].admitted_at,
                )
                self._preempt(victim_i, victim)

    def _close_active(self, req: Request, slot: _Slot,
                      now: float) -> None:
        """Close an active window at `now`: the decode-active component
        is the window minus this admission's prefill wall (already in
        the prefill component)."""
        win = now - slot.admitted_at
        req.active_s += win
        req.lat_components["decode"] += max(0.0, win - slot.prefill_s)

    def _preempt(self, i: int, slot: _Slot) -> None:
        req = slot.req
        now = time.monotonic()
        self.pool.free_blocks(slot.table)
        self._slots[i] = None
        req.state = "queued"
        self._close_active(req, slot, now)
        req.preemptions += 1
        req._wait_since, req._wait_kind = now, "preempt"
        req.event("preempted", now, i)
        # front of the queue: it resumes (re-prefilling prompt + tokens
        # so far — an exact continuation under the (seed, position)
        # sampling keys) as soon as blocks free up
        self._queue.appendleft(req)
        self._count("serve_preemptions")
        self._tick_counts["preempted"] += 1

    def _warm_restart(self, reason: str) -> None:
        """Watchdog escalation: rebuild the pool and slot array, keep
        the compiled programs (same shapes/dtypes — no recompile),
        re-queue every in-flight request front-of-line with its
        produced prefix.  Raises after repeated restarts with zero
        progress between them — a fault the restart cannot clear must
        surface, not spin."""
        self._restarts += 1
        self._restarts_since_progress += 1
        if self._restarts_since_progress > 5:
            raise RuntimeError(
                f"serving engine warm-restarted "
                f"{self._restarts_since_progress} times without "
                f"producing a token (last reason: {reason}) — the fault "
                "is persistent; refusing to spin"
            )
        self._count("serve_restarts")
        now = time.monotonic()
        # oldest admission ends up frontmost (appendleft in reverse)
        occupied = sorted(
            ((i, s) for i, s in enumerate(self._slots) if s is not None),
            key=lambda js: js[1].admitted_at, reverse=True,
        )
        for i, s in occupied:
            s.req.state = "queued"
            self._close_active(s.req, s, now)
            s.req.preemptions += 1
            # restart-overhead, not preempted-wait: the engine (not pool
            # pressure) took the slot away — the attribution dashboard
            # must bill the watchdog, not the scheduler
            s.req._wait_since, s.req._wait_kind = now, "restart"
            s.req.event("restart_requeued", now, i)
            self._queue.appendleft(s.req)
        self._slots = [None] * self.config.max_active
        self._poison_pending.clear()
        self.pool = PagedKVPool(**self._pool_args)
        if self._prefix is not None:
            # the tree indexes blocks of the pool that just died with
            # the restart — it rebuilds empty alongside (warm-from-
            # empty, same as journal recovery; lifetime stats carry on)
            old = self._prefix
            self._prefix = PrefixCache(self.config.block_tokens)
            for attr in ("hits", "misses", "blocks_aliased",
                         "tokens_avoided", "prompt_tokens", "evicted"):
                setattr(self._prefix, attr, getattr(old, attr))
        if self._guard is not None:
            self._guard.reset()
        self._tick_counts["restarted"] += 1
        self._arm_flight("serve_restart")
        if self.logger is not None:
            self.logger.log_meta(kind="fault", fault="serve_restart",
                                 at_step=self._ticks, action=reason)

    def _finished(self, req: Request) -> bool:
        if len(req.tokens) >= req.max_new_tokens:
            req.finish_reason = req.finish_reason or "length"
            return True
        eos = self.config.eos_id
        if eos is not None and req.tokens and req.tokens[-1] == eos:
            req.finish_reason = "eos"
            return True
        return False

    def _finish(self, i: int, slot: _Slot) -> None:
        req = slot.req
        now = time.monotonic()
        self.pool.free_blocks(slot.table)
        self._slots[i] = None
        self._evictions += 1
        self._count("serve_evictions")
        self._tick_counts["evicted"] += 1
        self._close_active(req, slot, now)
        self._terminal(req, "ok", req.finish_reason or "length",
                       now=now, slot=i)

    def _expire(self, i: int, slot: _Slot) -> None:
        req = slot.req
        now = time.monotonic()
        self.pool.free_blocks(slot.table)
        self._slots[i] = None
        self._expired += 1
        self._count("serve_expired")
        self._tick_counts["expired"] += 1
        self._close_active(req, slot, now)
        req.event("expired", now, i)
        self._terminal(req, "expired", "deadline", now=now, slot=i)

    def _quarantine(self, i: int, slot: _Slot) -> None:
        req = slot.req
        now = time.monotonic()
        self.pool.free_blocks(slot.table)
        self._slots[i] = None
        self._quarantined += 1
        self._count("serve_quarantined")
        self._tick_counts["quarantined"] += 1
        self._close_active(req, slot, now)
        req.event("quarantined", now, i)
        self._arm_flight("serve_quarantine")
        self._terminal(req, "failed", "nonfinite_logits", now=now, slot=i)

    def _shed_req(self, req: Request, reason: str) -> None:
        self._shed += 1
        self._count("serve_shed")
        self._terminal(req, "shed", f"shed:{reason}")

    def _terminal(self, req: Request, status: str, finish: str, *,
                  now: Optional[float] = None,
                  slot: Optional[int] = None) -> None:
        """The ONE exit for every request outcome: state, journal end
        line, JSONL `request` record with the terminal `status`.
        `now` is the timestamp the caller already closed its active
        window with — reusing it keeps the latency-component partition
        exact (sum(comp_*) == lat_s) instead of leaking the gap between
        two clock reads into neither bucket."""
        req.state = "done"
        req.status = status
        req.finish_reason = finish
        req.t_done = time.monotonic() if now is None else now
        if req._wait_since is not None:
            # terminal straight out of a wait (shed in queue, closed-out
            # recovery): the open wait window is the final component
            req.lat_components[req._wait_kind] += (
                req.t_done - req._wait_since)
            req._wait_since = None
        req.event(f"terminal:{status}", req.t_done, slot)
        if self.journal is not None and req._journaled:
            self.journal.end(req.id, status, finish)
        if self.slo is not None:
            # error-budget accounting observes every terminal outcome
            # (logger or not): good iff ok AND inside the objective's
            # latency bounds.  A fast-burn transition arms the flight
            # ring — the postmortem lands at the moment the budget
            # started dying — and persists an `slo` record.
            ttft = (None if req.t_first is None
                    else req.t_first - req.t_arrival)
            self.slo.observe(
                tenant=req.tenant, ok=(status == "ok"), ttft_s=ttft,
                latency_s=req.t_done - req.t_arrival,
                replica=self.replica_id, t=req.t_done)
            alerts = self.slo.check(t=req.t_done)
            if alerts:
                if any(a["kind"] == "fast_burn" for a in alerts):
                    self._arm_flight("slo_fast_burn")
                if self.logger is not None:
                    self.slo.record(self.logger, step=self._ticks)
        if self.logger is not None:
            comp = req.lat_components
            rec = dict(
                request_id=req.id,
                prompt_tokens=len(req.prompt),
                new_tokens=len(req.tokens),
                preemptions=req.preemptions,
                status=status,
                finish=finish,
                lat_s=round(req.t_done - req.t_arrival, 6),
                comp_queue_s=round(comp["queue"], 6),
                comp_prefill_s=round(comp["prefill"], 6),
                comp_decode_s=round(comp["decode"], 6),
                comp_preempt_s=round(comp["preempt"], 6),
                comp_restart_s=round(comp["restart"], 6),
                trace_id=req.trace_id,
                events=[[e[0], round(e[1], 6)] + list(e[2:])
                        for e in req.events],
            )
            if comp["migrate"]:
                # cross-engine handoff wait (disagg export -> import):
                # only migrated requests carry it, so single-engine
                # records keep the pre-v15 five-way partition
                rec["comp_migrate_s"] = round(comp["migrate"], 6)
            if req.last_slot is not None:
                rec["slot"] = req.last_slot
            if self.replica_id is not None:
                rec["replica_id"] = self.replica_id
            if req.kv_migration_bytes:
                # disaggregated handoff pricing: measured payload bytes
                # + which link class carried them (fleet/disagg.py)
                rec["kv_migration_bytes"] = int(req.kv_migration_bytes)
                rec["kv_migration_link"] = req.kv_migration_link or "ici"
            if self._spec is not None:
                # per-request speculation yield: drafts proposed for /
                # accepted into this sequence (accept rate = ratio)
                rec["spec_proposed"] = req.spec_proposed
                rec["spec_accepted"] = req.spec_accepted
            if req.tenant is not None:
                rec["tenant"] = req.tenant
            if self._prefix is not None:
                # shared-prefix yield, cumulative over admissions:
                # blocks aliased from the tree and the prompt tokens
                # whose prefill those aliases avoided
                rec["prefix_blocks"] = req.prefix_blocks
                rec["prefix_tokens"] = req.prefix_tokens
            if req.deadline_s is not None:
                rec["deadline_s"] = req.deadline_s
            if req.t_admitted is not None:
                rec["queue_s"] = round(req.t_admitted - req.t_arrival, 6)
            if req.t_first is not None:
                rec["ttft_s"] = round(req.t_first - req.t_arrival, 6)
            if req.tokens and req.active_s > 0:
                # rate over the ACTIVE windows only (each admission ->
                # preemption/terminal: prefill + decode) — queue waits
                # are reported by queue_s/preemptions, and folding them
                # in would collapse this into a duplicate of latency
                rec["decode_tokens_per_s"] = round(
                    len(req.tokens) / max(req.active_s, 1e-9), 3)
            self.logger.log_meta(kind="request", **rec)

    def _append_token(self, req: Request, tok: int, tnow: float) -> None:
        # per-token latency = gap since the previous token's completion
        # (arrival for the first — i.e. the first gap IS the TTFT)
        last_t = getattr(req, "_t_last", req.t_arrival)
        req.tokens.append(tok)
        req.token_lat.append(tnow - last_t)
        req._t_last = tnow
        if req.t_first is None:
            req.t_first = tnow
            if self.telemetry is not None:
                self.telemetry.histogram("serve_ttft_s").observe(
                    tnow - req.t_arrival)
        elif self.telemetry is not None:
            self.telemetry.histogram("serve_token_latency_s").observe(
                req.token_lat[-1])
        self._count("serve_tokens")

    def _count(self, name: str, n: int = 1) -> None:
        if self.telemetry is not None:
            self.telemetry.counter(name).inc(n)

    def _update_gauges(self) -> None:
        if self.telemetry is None:
            return
        t = self.telemetry
        # fleet replicas share one registry and tick in parallel: the
        # replica label keeps each engine's gauges on its OWN key
        # (serve_queue_depth{replica=0}) instead of last-writer-wins
        # over a shared one.  replica=None drops the label, so
        # single-engine runs keep their historical bare keys.
        rid = self.replica_id
        t.gauge("serve_batch_occupancy",
                self.n_active / self.config.max_active, replica=rid)
        t.gauge("serve_pool_utilization",
                self.pool.blocks_in_use / self.pool.num_usable,
                replica=rid)
        t.gauge("serve_queue_depth", float(len(self._queue)),
                replica=rid)
        t.gauge("serve_eviction_rate",
                self._evictions / max(1, self._ticks), replica=rid)
        t.gauge("serve_shed", float(self._shed), replica=rid)
        t.gauge("serve_expired", float(self._expired), replica=rid)
        t.gauge("serve_quarantined", float(self._quarantined),
                replica=rid)
        t.gauge("serve_restarts", float(self._restarts), replica=rid)
        if self._spec is not None:
            t.gauge("serve_spec_accept_rate",
                    self._spec_accepted / max(1, self._spec_proposed),
                    replica=rid)
            t.gauge("serve_spec_tokens_per_tick",
                    self._spec_tokens / max(1, self._spec_ticks),
                    replica=rid)
        if self._prefix is not None:
            pc = self._prefix
            t.gauge("serve_prefix_hit_rate",
                    pc.tokens_avoided / max(1, pc.prompt_tokens),
                    replica=rid)
            t.gauge("serve_prefix_blocks_aliased",
                    float(pc.blocks_aliased), replica=rid)
            t.gauge("serve_prefix_tokens_avoided",
                    float(pc.tokens_avoided), replica=rid)
            t.gauge("serve_prefix_cached_blocks", float(len(pc)),
                    replica=rid)
            t.gauge("serve_prefix_pool_saved_bytes",
                    float(self._prefix_saved_bytes()), replica=rid)
        if isinstance(self._queue, TenantQueue):
            active = {r.tenant for r in self._queue}
            active |= {s.req.tenant for s in self._slots
                       if s is not None}
            active.discard(None)
            t.gauge("serve_tenants_active", float(len(active)),
                    replica=rid)

    # -- per-tick time series + serving flight recorder ---------------------

    # flush-trigger precedence when several fire in one tick: the record
    # names the gravest one (a restart subsumes its quarantines)
    _FLIGHT_PRIORITY = {"serve_shed_burst": 1, "slo_fast_burn": 2,
                        "serve_quarantine": 2,
                        "serve_restart": 3, "serve_recover": 3}

    def _arm_flight(self, reason: str) -> None:
        cur = self._FLIGHT_PRIORITY.get(self._flight_reason, 0)
        if self._FLIGHT_PRIORITY[reason] > cur:
            self._flight_reason = reason

    def _record_tick(self, tick_i: int, t0: float, produced: int) -> None:
        """End-of-tick bookkeeping: append the tick entry to the flight
        ring (host dicts, no device sync), emit a `tick` JSONL record
        when the tick was eventful or the sampling cadence hit, and
        flush the flight ring if a fault trigger armed it this tick.

        The wall split: prefill/decode/fetch are measured around the two
        compiled programs (dispatch vs the token-fetch sync); sched_s is
        the remainder — deadline enforcement, growth, admission
        bookkeeping, journal commit, gauge updates.  Submit-time sheds
        happen OUTSIDE ticks and land on the next tick's `shed` count.

        Without a logger none of this can ever be emitted (every flush
        path needs the sink), so it is skipped wholesale — a production
        engine with logging off pays nothing per tick, and the flight
        ring covers ticks from logger attach onward (serve_bench
        attaches AFTER warmup, so warm ticks stay out of postmortems by
        construction)."""
        if self.logger is None:
            self._flight_reason = None
            self._shed_seen = self._shed
            return
        wall = time.monotonic() - t0
        seg = self._seg
        sched = max(0.0, wall - seg["prefill_s"] - seg["decode_s"]
                    - seg["fetch_s"] - seg["draft_s"])
        shed_delta = self._shed - self._shed_seen
        self._shed_seen = self._shed
        if shed_delta >= self.config.shed_burst:
            self._arm_flight("serve_shed_burst")
        c = self._tick_counts
        counts = dict(c, shed=shed_delta, produced=produced)
        state = dict(
            occupancy=round(self.n_active / self.config.max_active, 4),
            pool_util=round(
                self.pool.blocks_in_use / self.pool.num_usable, 4),
            queue_depth=len(self._queue),
        )
        segments = dict(
            sched_s=round(sched, 6),
            prefill_s=round(seg["prefill_s"], 6),
            decode_s=round(seg["decode_s"], 6),
            fetch_s=round(seg["fetch_s"], 6),
        )
        if self._spec is not None:
            # the draft-vs-verify wall split: draft_s is the drafter's
            # proposal wall, decode_s+fetch_s the verify program's —
            # only spec runs emit the field, so spec-off tick records
            # are byte-identical to the pre-spec schema
            segments["draft_s"] = round(seg["draft_s"], 6)
        if self._flight is not None:
            # the ring reuses FlightRecorder's schema: the tick's state +
            # counts ride the `health` dict, the wall split `segments`
            self._flight.record(
                tick_i, step_s=wall,
                health={k: float(v) for k, v in
                        {**state, **counts}.items()},
                segments=segments,
            )
        eventful = any(counts[k] for k in
                       ("admitted", "evicted", "preempted", "shed",
                        "expired", "quarantined", "restarted"))
        every = self.config.tick_record_every
        sampled = bool(every) and tick_i % every == 0
        if eventful or sampled:
            extra = ({} if self.replica_id is None
                     else {"replica_id": self.replica_id})
            self.logger.log_meta(
                kind="tick", tick=tick_i,
                t_s=round(t0, 6), wall_s=round(wall, 6),
                **segments, **state, **counts, **extra,
                emit="event" if eventful else "sample",
            )
        if self._flight_reason is not None:
            if self._flight is not None:
                # the flush carries the writer's replica so trace_view's
                # anchoring rule can pick among same-numbered ticks of a
                # SHARED fleet stream by key instead of file order
                self._flight.flush(self.logger, self._flight_reason,
                                   at_step=tick_i,
                                   **({"replica_id": self.replica_id}
                                      if self.replica_id is not None
                                      else {}))
            self._flight_reason = None
