# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Draft-token proposers for speculative decoding (serving/spec.py).

Two drafters behind ONE interface — `propose(slots) -> (S, K+1) int32`
proposals per decode slot (K verifiable drafts + the bonus position's
proposal, autoregressively consistent: proposal j conditions on
proposals 1..j-1, so any position's proposal is a pure function of the
prefix — the acceptance core's determinism guarantee needs exactly
that), plus an `on_admit` hook fired at every
(re)admission so a drafter with state can rebuild it from the committed
prefix (which is also what makes drafter state compose with preemption,
warm restart, and journal recovery: admission is the ONE path every
resume rides).  `on_admit` returns the drafter's proposal for the first
post-prefix position — the spec prefill commits its token through the
same accept-or-residual rule the verify core uses, so a position's
sampling path never depends on which program reached it first:

  * `NgramDrafter` ("ngram") — model-free prompt-lookup (PLD): propose
    the continuation of the most recent earlier occurrence of the
    context's own suffix n-gram.  Deterministic, zero weights, zero
    device work — the right drafter when outputs echo their context
    (templates, code, the repetition loops untrained models fall into),
    and the cheap default on the CPU tier-1 mesh.

  * `ModelDrafter` ("model:<preset>" / "model:self") — a small
    same-family autoregressive model with its OWN cache: a statically-
    tabled paged pool (slot s permanently owns blocks [1+s*W, (s+1)*W]
    — contiguous per slot, no allocation churn ever) written through
    the same `paged_prefill`/`paged_decode` machinery the target uses.
    Each tick one compiled (K+1)-step greedy rollout proposes for
    every slot at once; the rollout's first step embeds the tick's
    actual committed head token, which simultaneously absorbs the
    previous tick's correction and overwrites any rejected-draft K/V
    at that position — no separate catch-up pass.

Both drafters propose DETERMINISTICALLY (greedy argmax / lookup), i.e.
a point-mass proposal distribution: the acceptance core
(models/sampling.spec_accept_per_slot) stays target-exact with q = 1,
and a request's proposals are a pure function of its committed prefix —
which is exactly what the serving determinism guarantee needs across
preemption/restart/recovery.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .pool import SCRATCH_BLOCK, PagedKVPool, PageRef


class NgramDrafter:
    """Prompt-lookup decoding: match the context's trailing n-gram
    (longest first, `max_n` down to `min_n`) against the most recent
    earlier occurrence in the context itself, and propose the K tokens
    that followed it.  No match (or a short continuation) pads by
    repeating the last proposed/context token — the verify step rejects
    bad guesses for free, so padding costs nothing but wasted verify
    width."""

    def __init__(self, k: int, max_n: int = 3, min_n: int = 1):
        if k < 1:
            raise ValueError("drafter k must be >= 1")
        if not 1 <= min_n <= max_n:
            raise ValueError("need 1 <= min_n <= max_n")
        self.k = int(k)
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def describe(self) -> str:
        return f"ngram(n<={self.max_n})"

    def on_admit(self, slot_i: int, prompt_now: List[int]) -> int:
        # stateless beyond the context itself; the return value is the
        # drafter's proposal for the FIRST post-prefix position (the
        # prefill program's accept-or-residual operand) — the same
        # lookup `propose_one` would make there
        t = self._lookup_next(prompt_now, self.max_n, self.min_n)
        return int(t if t is not None else
                   (prompt_now[-1] if prompt_now else 0))

    @staticmethod
    def _lookup_next(ctx: List[int], max_n: int, min_n: int):
        """The single next token after the most recent earlier
        occurrence of ctx's trailing n-gram (longest n first), or None
        when nothing matches."""
        n_ctx = len(ctx)
        for n in range(min(max_n, n_ctx - 1), min_n - 1, -1):
            pat = ctx[-n:]
            # most recent occurrence ENDING before the final position,
            # so a continuation token exists
            for start in range(n_ctx - n - 1, -1, -1):
                if ctx[start:start + n] == pat:
                    return ctx[start + n]
        return None

    def propose_one(self, ctx: List[int]) -> List[int]:
        """K+1 proposed continuation tokens, AUTOREGRESSIVELY
        consistent: proposal j re-runs the lookup on ctx extended by
        proposals 1..j-1, so the proposal for any position is a pure
        function of the (hypothetically committed) prefix at that
        position — the property the acceptance core's determinism
        guarantee rests on (a span-START-only lookup would make
        proposals depend on where the scheduler's spans happen to
        align, which shifts across preemption/restart replays)."""
        ext = list(ctx)
        out: List[int] = []
        for _ in range(self.k + 1):
            t = self._lookup_next(ext, self.max_n, self.min_n)
            if t is None:
                t = ext[-1] if ext else 0  # pad: verify rejects free
            out.append(t)
            ext.append(t)
        return out

    def propose(self, slots) -> np.ndarray:
        """(S, K+1) proposals — K verifiable drafts plus the bonus
        position's proposal: row i continues slot i's committed context
        (prompt + produced tokens); empty slots propose zeros (their
        verify lanes compute on scratch and commit nothing)."""
        drafts = np.zeros((len(slots), self.k + 1), np.int32)
        for i, s in enumerate(slots):
            if s is None:
                continue
            drafts[i] = self.propose_one(s.req.prompt + s.req.tokens)
        return drafts


class ModelDrafter:
    """Small-model drafter over its own statically-tabled paged cache.

    The drafter cache's invariant mirrors the scheduler's: after a
    tick committing `a` drafts + one resampled token, the cache holds
    the drafter's K/V for every COMMITTED position (accepted drafts'
    rollout writes ARE that K/V; the resampled token is absorbed by the
    next rollout's first step, overwriting the rejected draft's stale
    entry at its position).  (Re)admission prefills the slot's region
    from prompt + produced, so preemption/restart/recovery resume from
    the same state an uninterrupted run would hold."""

    def __init__(self, model, params, k: int, *, max_active: int,
                 max_seq: int, block_tokens: int):
        if k < 1:
            raise ValueError("drafter k must be >= 1")
        if not getattr(model, "paged_decode_capable", False):
            raise ValueError(
                f"draft model {type(model).__name__} is not paged-decode "
                "capable (paged_decode_capable=False)"
            )
        import jax

        from ..models.gpt2 import resolved_cache_dtype
        c = model.config
        if c.block_size < max_seq:
            raise ValueError(
                f"draft model context block_size={c.block_size} is "
                f"smaller than the engine's max_seq_tokens={max_seq} — "
                "the drafter must be able to prefill any committed "
                "prefix the engine can hold (a longer prefix would "
                "crash at (re)admission); serve with max_seq_tokens <= "
                "the draft context or pick a longer-context drafter"
            )
        self.model = model
        self.params = params
        self.k = int(k)
        self._bt = int(block_tokens)
        self.max_seq = min(int(max_seq), c.block_size)
        self._W = -(-self.max_seq // self._bt)
        kv_heads = getattr(c, "kv_heads", c.n_head)
        self.pool = PagedKVPool(
            n_layer=c.n_layer, kv_heads=kv_heads, head_dim=c.head_dim,
            num_blocks=max_active * self._W, block_tokens=self._bt,
            dtype=resolved_cache_dtype(c),
        )
        w = self._W
        self._tables = np.asarray(
            [[1 + s * w + j for j in range(w)] for s in range(max_active)],
            np.int32,
        )
        self._stacked = jax.jit(model.stacked_compute_params)(params)
        self._rollout = jax.jit(self._rollout_impl, donate_argnums=(2,))

        def _prefill(params, stacked, idx, last_pos, block_ids, view):
            return model.paged_prefill(
                params, idx, last_pos, block_ids, view, self._bt,
                stacked=stacked,
            )

        self._prefill = jax.jit(_prefill, donate_argnums=(5,))

    def describe(self) -> str:
        c = self.model.config
        return f"model({c.n_layer}L{c.n_embd}D)"

    def _rollout_impl(self, params, stacked, view, tok, pos):
        """K+1 greedy decode steps for every slot at once: (S,) head
        tokens at (S,) head positions -> ((S, K+1) proposals, new
        view) — K verifiable drafts plus the bonus position's proposal,
        autoregressively consistent by construction (each step
        conditions on the previous proposals through the cache).
        Positions at/past the cache horizon route their writes to
        scratch and clamp their reads — a slot near its length limit
        proposes garbage the verify step simply rejects."""
        import jax
        import jax.numpy as jnp

        tables = jnp.asarray(self._tables)
        bt, w, ms = self._bt, self._W, self.max_seq

        def step(carry, _):
            tok, pos, view = carry
            safe = jnp.minimum(pos, ms - 1)
            x = self.model._embed_decode(params, tok, safe)
            j = jnp.minimum(pos // bt, w - 1)
            blk = jnp.take_along_axis(tables, j[:, None], axis=1)[:, 0]
            blk = jnp.where(pos < ms, blk, SCRATCH_BLOCK)
            page = PageRef(tables, blk, off=pos % bt, pos=safe)
            x, view = self.model.paged_decode(stacked, x, view, page)
            logits = self.model.head(params, x)[:, 0]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, pos + 1, view), nxt

        (_, _, view), toks = jax.lax.scan(
            step, (tok, pos, view), None, length=self.k + 1)
        return jnp.swapaxes(toks, 0, 1), view

    def _bucket(self, p: int) -> int:
        """Prefill pad length (same power-of-two-blocks rule as the
        engine, so drafter prefill shapes stay O(log T) too)."""
        nb = -(-p // self._bt)
        b = 1
        while b < nb:
            b *= 2
        return min(b * self._bt, self.model.config.block_size)

    def on_admit(self, slot_i: int, prompt_now: List[int]) -> int:
        """(Re)build slot_i's drafter cache from the committed prefix;
        returns the draft model's greedy proposal for the first
        post-prefix position (argmax of its own prefill logits — the
        same token its rollout would propose there), which the engine's
        spec prefill consumes as the accept-or-residual operand."""
        p = len(prompt_now)
        bucket = self._bucket(p)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = prompt_now
        block_ids = np.full((bucket // self._bt,), SCRATCH_BLOCK, np.int32)
        n = min(len(block_ids), self._W)
        block_ids[:n] = self._tables[slot_i, :n]
        logits, view = self._prefill(
            self.params, self._stacked, padded, p - 1, block_ids,
            self.pool.view,
        )
        self.pool.view = view
        return int(np.argmax(np.asarray(logits)[0]))

    def propose(self, slots) -> np.ndarray:
        s_count = len(slots)
        tok = np.zeros((s_count,), np.int32)
        # empty slots park at the horizon: scratch writes, clamped reads
        pos = np.full((s_count,), self.max_seq, np.int32)
        for i, s in enumerate(slots):
            if s is None:
                continue
            tok[i] = s.last
            pos[i] = s.pos
        drafts, view = self._rollout(
            self.params, self._stacked, self.pool.view, tok, pos)
        self.pool.view = view
        return np.asarray(drafts)


def make_drafter(spec: str, model, params, k: int, *, max_active: int,
                 max_seq: int, block_tokens: int, seed: int = 0):
    """Drafter factory for the `spec_draft` knob:

      * "ngram"          -> NgramDrafter (model-free prompt lookup)
      * "model:self"     -> ModelDrafter over the TARGET model/params
                            (a perfect-acceptance reference: every
                            rollout step costs a full target pass, so
                            it never wins throughput — tests and
                            acceptance-rate ceilings use it)
      * "model:<preset>" -> ModelDrafter over a fresh-initialized
                            preset (models.ALL_PRESETS) sharing the
                            target's vocab.  NOTE: random-init weights
                            exercise the machinery; a THROUGHPUT win
                            needs a trained drafter that actually
                            predicts the target.
    """
    if spec == "ngram":
        return NgramDrafter(k)
    if spec.startswith("model:"):
        name = spec[len("model:"):]
        if name == "self":
            dmodel, dparams = model, params
        else:
            import jax

            from ..models import ALL_PRESETS, build_model
            if name not in ALL_PRESETS:
                raise ValueError(
                    f"unknown draft preset {name!r}; spec_draft takes "
                    f"'ngram', 'model:self', or 'model:<preset>' with a "
                    f"preset in {sorted(ALL_PRESETS)}"
                )
            dmodel = build_model(name)
            if dmodel.config.vocab_size != model.config.vocab_size:
                raise ValueError(
                    f"draft preset {name!r} has vocab_size "
                    f"{dmodel.config.vocab_size} but the target serves "
                    f"{model.config.vocab_size} — drafts are token ids, "
                    "the vocabularies must match"
                )
            dparams = dmodel.init(jax.random.PRNGKey(seed))
        return ModelDrafter(dmodel, dparams, k, max_active=max_active,
                            max_seq=max_seq, block_tokens=block_tokens)
    raise ValueError(
        f"spec_draft {spec!r} not understood: use 'ngram', "
        "'model:self', or 'model:<preset>'"
    )
