# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Multi-tenant admission: weighted-fair scheduling with per-tenant
token budgets, SLO classes, and door watermarks.

One undifferentiated FIFO lets a single abusive client starve every
other tenant's SLO — its burst lands first, head-of-line blocking does
the rest.  When `ServeConfig.tenants` is set the engine swaps its FIFO
for the `TenantQueue` here:

  * STRIDE SCHEDULING across per-tenant FIFOs — every tenant carries a
    `pass` value advanced by admitted-cost / weight; the next admission
    always comes from the eligible tenant with the minimum pass, so
    over any contended window tenants admit tokens proportional to
    their weights (the deficit/stride family; stride keeps the
    bookkeeping to one counter per tenant and is naturally
    work-conserving — an idle fleet serves the only busy tenant at
    full rate regardless of weight).
  * TOKEN BUDGETS — a tenant with `tokens_per_tick` set accrues budget
    each scheduler tick (capped at `burst_tokens`), and its head
    request is only eligible while the budget covers the request's
    cost; an over-budget tenant is skipped, NOT rotated to later (its
    own FIFO order is preserved), so a flood burns its own budget and
    queue while well-behaved tenants admit around it.
  * SLO CLASSES — `deadline_s` stamps a default completion deadline on
    the tenant's requests at submit; from there the existing PR-8
    machinery (queue sheds, active expiry, priced unmeetable sheds) is
    already per-request and therefore per-tenant for free.
  * DOOR WATERMARKS — `max_queue` bounds the tenant's OWN queue;
    beyond it the engine sheds at submit ("tenant_queue_watermark"),
    which is the isolation primitive: the abusive tenant's overflow
    never reaches the shared pool at all.

A request's admission cost is `len(prompt) + max_new_tokens` — the
tokens it will occupy end to end (prefill work + decode work + pool
footprint are all roughly proportional).  Preemption resume re-charges
the same cost: the re-prefill is real work, and billing it to the
owner keeps the scheduler honest about who is consuming the engine.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, Iterator, List, Optional


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's scheduling contract.  All fields optional: a
    tenant submitted with no configured policy gets the defaults
    (weight 1, no budget, no watermark, no SLO class)."""

    # stride-scheduling share: under contention the tenant admits
    # tokens proportional to weight / sum(weights of busy tenants)
    weight: float = 1.0
    # admission token budget: accrual per scheduler tick (None = no
    # cap — weighted fairness alone)
    tokens_per_tick: Optional[float] = None
    # budget accrual ceiling (default 8 ticks' worth): bounds the burst
    # an idle tenant can save up
    burst_tokens: Optional[float] = None
    # per-tenant door watermark: submissions beyond this many queued
    # requests shed at the door ("tenant_queue_watermark")
    max_queue: Optional[int] = None
    # SLO class: default completion deadline stamped on the tenant's
    # requests when they carry none of their own
    deadline_s: Optional[float] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got "
                             f"{self.weight}")
        if self.tokens_per_tick is not None and self.tokens_per_tick <= 0:
            raise ValueError("tokens_per_tick must be > 0 when set")

    @property
    def burst(self) -> Optional[float]:
        if self.tokens_per_tick is None:
            return None
        return (self.burst_tokens if self.burst_tokens is not None
                else 8.0 * self.tokens_per_tick)


def request_cost(req) -> int:
    """Admission cost in tokens: prompt + full decode commitment."""
    return len(req.prompt) + req.max_new_tokens


class _TenantState:
    __slots__ = ("fifo", "pass_v", "budget", "policy",
                 "admitted_tokens", "budget_granted", "sheds")

    def __init__(self, policy: TenantPolicy):
        self.fifo: Deque = deque()
        self.pass_v = 0.0
        self.policy = policy
        self.budget = policy.burst  # start full: cold != throttled
        self.admitted_tokens = 0
        self.budget_granted = (policy.burst or 0.0)
        self.sheds = 0


class TenantQueue:
    """Drop-in for the engine's admission deque, scheduling across
    per-tenant FIFOs.  The engine drives it through the same surface
    it uses on the plain deque (`append` / `appendleft` / `remove` /
    iteration / len) plus the scheduler hooks: `on_tick` (budget
    accrual), `peek` (the stride-selected next admissible request, or
    None when every busy tenant is out of budget), and `pop(req)`
    (remove + charge the cost the peek priced)."""

    def __init__(self, policies: Dict[str, TenantPolicy]):
        self._policies = dict(policies)
        self._t: Dict[str, _TenantState] = {}
        # global virtual time: the pass of the last scheduled tenant —
        # a newly-busy tenant starts here instead of at 0, so going
        # idle never banks unbounded priority
        self._vtime = 0.0

    def policy(self, tenant: Optional[str]) -> TenantPolicy:
        return self._policies.get(tenant) or TenantPolicy()

    def _state(self, tenant: Optional[str]) -> _TenantState:
        key = tenant or ""
        st = self._t.get(key)
        if st is None:
            st = self._t[key] = _TenantState(self.policy(tenant))
        return st

    # -- deque-compatible surface ------------------------------------------

    def append(self, req) -> None:
        st = self._state(getattr(req, "tenant", None))
        if not st.fifo:
            st.pass_v = max(st.pass_v, self._vtime)
        st.fifo.append(req)

    def appendleft(self, req) -> None:
        """Front of the request's OWN tenant FIFO — preemption resume /
        recovery keep their within-tenant order; cross-tenant order
        stays the stride schedule's call."""
        st = self._state(getattr(req, "tenant", None))
        if not st.fifo:
            st.pass_v = max(st.pass_v, self._vtime)
        st.fifo.appendleft(req)

    def remove(self, req) -> None:
        self._state(getattr(req, "tenant", None)).fifo.remove(req)

    def clear(self) -> None:
        for st in self._t.values():
            st.fifo.clear()

    def __len__(self) -> int:
        return sum(len(st.fifo) for st in self._t.values())

    def __bool__(self) -> bool:
        return any(st.fifo for st in self._t.values())

    def __iter__(self) -> Iterator:
        for key in sorted(self._t):
            yield from self._t[key].fifo

    def depth(self, tenant: Optional[str]) -> int:
        st = self._t.get(tenant or "")
        return len(st.fifo) if st is not None else 0

    # -- scheduler hooks ----------------------------------------------------

    def on_tick(self) -> None:
        """Budget accrual, once per scheduler tick."""
        for st in self._t.values():
            rate = st.policy.tokens_per_tick
            if rate is None or st.budget is None:
                continue
            add = min(rate, st.policy.burst - st.budget)
            if add > 0:
                st.budget += add
                st.budget_granted += add

    def _eligible(self, st: _TenantState) -> bool:
        if not st.fifo:
            return False
        if st.budget is None:
            return True
        return st.budget >= request_cost(st.fifo[0])

    def peek(self):
        """The stride-selected next request: head of the minimum-pass
        tenant whose budget covers it.  None when tenants are queued
        but all over budget — admission waits for the next tick's
        accrual (never a deadlock: on_tick refills every tick)."""
        best = None
        for key in sorted(self._t):
            st = self._t[key]
            if not self._eligible(st):
                continue
            if best is None or (st.pass_v, key) < (best[0].pass_v,
                                                   best[1]):
                best = (st, key)
        return best[0].fifo[0] if best else None

    def pop(self, req) -> None:
        """Commit the admission `peek` selected: remove `req` and
        charge its cost to the tenant's pass (stride) and budget."""
        st = self._state(getattr(req, "tenant", None))
        assert st.fifo and st.fifo[0] is req, \
            "pop() must take the request peek() selected"
        st.fifo.popleft()
        cost = float(request_cost(req))
        st.pass_v += cost / st.policy.weight
        self._vtime = st.pass_v
        if st.budget is not None:
            st.budget = max(0.0, st.budget - cost)
        st.admitted_tokens += int(cost)

    def refund(self, req) -> None:
        """Undo one `pop` charge — an ABORTED admission (chaos or real
        prefill exception re-queues the request untouched): without
        the refund a transient fault bills the tenant full cost for
        zero work, and the re-admission charges it AGAIN — a
        budget-capped tenant could starve for ticks behind one flaky
        prefill.  The caller re-queues the request separately
        (appendleft)."""
        st = self._state(getattr(req, "tenant", None))
        cost = float(request_cost(req))
        st.pass_v = max(0.0, st.pass_v - cost / st.policy.weight)
        # the pop advanced vtime to this tenant's charged pass; pull it
        # back too (the abort raises out of the same admission loop, so
        # no other pop intervened) — otherwise the re-queue's
        # idle-rejoin seeding (max(pass, vtime)) re-imposes the charge
        # the refund just rolled back
        self._vtime = min(self._vtime, st.pass_v)
        if st.budget is not None:
            st.budget = min(st.policy.burst, st.budget + cost)
        st.admitted_tokens = max(0, st.admitted_tokens - int(cost))

    # -- reporting ----------------------------------------------------------

    def stats(self) -> Dict[str, Dict]:
        """Per-tenant scheduler accounting for the report surface:
        admitted token cost, budget granted (accrued, capped) and
        utilization = admitted / granted when a budget is configured."""
        out: Dict[str, Dict] = {}
        for key, st in self._t.items():
            d = {"queued": len(st.fifo),
                 "admitted_tokens": st.admitted_tokens,
                 "weight": st.policy.weight,
                 "sheds": st.sheds}
            if st.policy.tokens_per_tick is not None:
                d["budget_granted"] = round(st.budget_granted, 1)
                d["budget_utilization"] = round(
                    st.admitted_tokens / max(st.budget_granted, 1e-9), 4)
            out[key or "-"] = d
        return out

    def note_shed(self, tenant: Optional[str]) -> None:
        self._state(tenant).sheds += 1


def parse_tenant_spec(spec: str) -> Dict[str, TenantPolicy]:
    """CLI tenant spec -> policies: comma list of
    `name[:weight[:tokens_per_tick[:max_queue]]]` entries, e.g.
    "pro:4,free:1:64:8".  Empty or 0 trailing fields inherit the
    defaults (0 means "uncapped", not a zero budget — a zero budget
    would never admit)."""
    out: Dict[str, TenantPolicy] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        name = parts[0]
        kw = {}
        if len(parts) > 1 and parts[1]:
            kw["weight"] = float(parts[1])
        if len(parts) > 2 and parts[2] and float(parts[2]) > 0:
            kw["tokens_per_tick"] = float(parts[2])
        if len(parts) > 3 and parts[3] and int(parts[3]) > 0:
            kw["max_queue"] = int(parts[3])
        out[name] = TenantPolicy(**kw)
    if not out:
        raise ValueError(f"empty tenant spec {spec!r}")
    return out
