# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Crash-recoverable request journal: the serving tier's write-ahead log.

The engine loses every in-flight request when its process dies — the KV
pool is gone, but the REQUESTS are replayable: under the (seed, position)
sampling keys a request's token sequence is a pure function of (prompt,
produced prefix), so re-prefilling prompt + produced continues exactly
(the same mechanism preemption resume rides).  What recovery needs is
just the host-side facts: which requests were admitted and which tokens
each had produced — this module journals exactly that.

Write discipline:

  * every event is ONE JSONL line, written whole in a single `write()`
    call (atomic at the line level — a crash can tear at most the final
    line, and `replay` tolerates a torn tail);
  * lines buffer in memory during a scheduler tick and `commit()` writes
    + flushes + fsyncs them once per tick — one fsync per tick, not one
    per token (the "fsync batched per tick" contract).  Tokens produced
    after the last commit are LOST from the journal on a crash; recovery
    simply re-decodes them to the same values.

Event lines:

    {"ev": "geom", "block_size": 64, "max_seq_tokens": 40,
     "vocab": 128, "block_tokens": 8}
    {"ev": "submit", "id": 3, "prompt": [...], "max_new": 16,
     "deadline_s": null, "seed": 3}
    {"ev": "tok", "id": 3, "toks": [41, 7]}
    {"ev": "end", "id": 3, "status": "ok", "finish": "length"}

The `geom` line is the engine's serving geometry, stamped when the
journal attaches: replay is only exact onto an engine with the same
compiled shapes, and `ServingEngine.recover()` validates the journal's
geometry against its own UP FRONT (naming both sides) instead of
failing deep inside pool scatter — the check failover made load-bearing
(a journal replayed onto an arbitrary sibling, not the engine that
wrote it).

`replay()` folds a journal back into (pending requests in admission
order, finished ids): a request with an "end" line is done; everything
else is interrupted and re-queues front-of-line with its produced
prefix (`ServingEngine.recover`).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple


class ServingKilled(RuntimeError):
    """Simulated process death between journal-append and commit — the
    chaos harness's stand-in for a SIGKILL at the worst write moment
    (resilience/chaos.py).  The engine must NOT catch this and warm-
    restart: a real kill leaves no engine to restart; recovery happens
    in the next process via `ServingEngine.recover`."""


class RequestJournal:
    """Append-only JSONL journal of admissions and produced tokens."""

    def __init__(self, path: str):
        self.path = str(path)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        # repair-on-open: a crash can tear the previous writer's FINAL
        # line (partial write, no newline).  Appending after it would
        # glue the next line onto the fragment — one merged unparseable
        # line that is no longer the tail, which `replay` rightly calls
        # corruption.  The fragment carries nothing replay would keep
        # (torn tails are skipped), so truncate it before appending —
        # the standard WAL open-repair.
        self._repair_torn_tail()
        # append mode: recovery continues the SAME file, so a second
        # crash replays both segments
        self._fh = open(self.path, "a")
        self._buf: List[str] = []
        # test hook: called in commit() after lines are handed to the
        # buffer but before they reach the file — where a kill hurts most
        self._commit_hook = None

    def _repair_torn_tail(self) -> None:
        if not os.path.exists(self.path):
            return
        size = os.path.getsize(self.path)
        if size == 0:
            return
        with open(self.path, "rb+") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) == b"\n":
                return
            # walk back to the last newline; everything after it is the
            # torn fragment
            f.seek(0)
            data = f.read()
            cut = data.rfind(b"\n") + 1  # 0 when no newline at all
            f.truncate(cut)

    # -- append (buffered; atomic single-write lines) -----------------------

    def _append(self, rec: Dict) -> None:
        self._buf.append(json.dumps(rec) + "\n")

    def submit(self, req) -> None:
        rec = {
            "ev": "submit", "id": req.id, "prompt": list(req.prompt),
            "max_new": req.max_new_tokens, "deadline_s": req.deadline_s,
            "seed": req.seed,
        }
        tenant = getattr(req, "tenant", None)
        if tenant is not None:
            # multi-tenant attribution survives recovery; absent on
            # untagged traffic so pre-tenancy journals replay unchanged
            rec["tenant"] = tenant
        trace = getattr(req, "trace_id", None)
        if trace is not None:
            # cross-engine trace correlation survives recovery the same
            # way: the id stamped at submit() is the one a failover
            # sibling re-admits under, so a request's spans on two
            # replicas share a track key; absent pre-v15
            rec["trace"] = trace
        self._append(rec)

    def tokens(self, req_id: int, toks: List[int]) -> None:
        if toks:
            self._append({"ev": "tok", "id": req_id,
                          "toks": [int(t) for t in toks]})

    def end(self, req_id: int, status: str, finish: str) -> None:
        self._append({"ev": "end", "id": req_id, "status": status,
                      "finish": finish})

    def geometry(self, geom: Dict) -> None:
        """Stamp the writing engine's serving geometry (committed
        immediately — the line must exist before any crash could need
        it).  Appended once per attaching engine; `read_geometry` reads
        the FIRST stamp, i.e. the geometry the journaled requests were
        actually served under."""
        self._append({"ev": "geom", **geom})
        self.commit()

    def commit(self) -> None:
        """Write every buffered line (one write() per line), flush, and
        fsync — called once per scheduler tick."""
        if self._commit_hook is not None:
            hook, self._commit_hook = self._commit_hook, None
            hook()
        if not self._buf:
            return
        for line in self._buf:
            self._fh.write(line)
        self._buf = []
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def arm_commit_hook(self, fn) -> None:
        """Install a ONE-SHOT hook that runs at the next commit() before
        any buffered line reaches the file.  The chaos harness raises
        ServingKilled here (the buffered tick is lost, exactly like a
        kill between append and fsync); the kill-mid-trace worker calls
        os.kill(pid, SIGKILL) for the real thing."""
        self._commit_hook = fn

    def close(self) -> None:
        if self._fh is not None:
            self.commit()
            self._fh.close()
            self._fh = None

    def abandon(self) -> None:
        """Drop the uncommitted buffer and close the file WITHOUT
        committing — the in-process stand-in for the writing engine's
        death (fleet failover: the dead replica's buffered tick is lost
        exactly as a SIGKILL between append and fsync would lose it;
        recovery re-decodes those tokens to the same values)."""
        self._buf = []
        self._commit_hook = None
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- replay -------------------------------------------------------------

    @staticmethod
    def read_geometry(path: str) -> Optional[Dict]:
        """The FIRST `geom` line's fields (the writing engine's serving
        geometry), or None for a journal that predates the stamp.
        Torn/garbage lines are skipped — geometry reading must never be
        stricter than `replay`, which tolerates a torn tail."""
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("ev") == "geom":
                    return {k: v for k, v in rec.items() if k != "ev"}
        return None

    @staticmethod
    def replay(path: str) -> Tuple[List[Dict], List[int]]:
        """Fold a journal into (interrupted, finished_ids).

        `interrupted` is a list of {"id", "prompt", "max_new",
        "deadline_s", "seed", "tokens"} dicts in ADMISSION order — each
        an in-flight request at crash time with the token prefix the
        journal had committed.  A torn final line (the crash landed
        mid-write) is skipped; a torn line anywhere else is a corrupt
        journal and raises."""
        reqs: Dict[int, Dict] = {}
        done: List[int] = []
        with open(path) as f:
            lines = f.readlines()
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    break  # torn tail: the crash interrupted this write
                raise ValueError(
                    f"{path}: corrupt journal line {i + 1} (not the "
                    "final line — this is not a torn-tail crash "
                    "artifact)"
                )
            ev, rid = rec.get("ev"), rec.get("id")
            if ev == "submit":
                reqs[rid] = {
                    "id": rid, "prompt": rec["prompt"],
                    "max_new": rec["max_new"],
                    "deadline_s": rec.get("deadline_s"),
                    "seed": rec.get("seed", rid),
                    "tenant": rec.get("tenant"),
                    "trace": rec.get("trace"), "tokens": [],
                }
            elif ev == "tok" and rid in reqs:
                reqs[rid]["tokens"].extend(rec["toks"])
            elif ev == "end" and rid in reqs:
                done.append(rid)
                del reqs[rid]
        return list(reqs.values()), done
