# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Paged KV cache: one preallocated HBM pool, per-request block tables.

The contiguous decode cache (`GPT2Model._prefill`) allocates
(L, B, Hkv, T_max, Dh) per generate() call — every request pays for its
MAXIMUM length up front, and concurrent requests of different lengths
cannot share the allocation.  Serving traffic needs the opposite: the
pool here is ONE (num_blocks, block_tokens, L, KVH, Dh) K/V pair sized
for the whole engine, carved into fixed `block_tokens`-token blocks.  A
request owns just the blocks its current length needs (a host-side block
table of physical block ids); a finished request's blocks return to the
free list and the next admission reuses them.  On TPU this is the
decode-throughput design point (the Gemma serving comparison, PAPERS.md
arXiv:2605.25645): HBM stays densely packed with live cache, so batch
occupancy — not per-request padding — bounds tokens/s.

Physical block 0 is SCRATCH: never allocated, it absorbs the writes of
invalid slots and bucket-padding positions so the compiled step stays
shape-stable without branching.  Scratch contents are garbage by design;
every read path masks by true position before the softmax.

Quantized cache blocks (`quant="int8" | "fp8"`) rest the pool at 1
byte/element, reusing the blockwise-absmax codec from `parallel/comm.py`
(the grad_comm PR's machinery) with the codec block = one (Dh,) head
vector and the f32 scale stored per (block, token, layer, head) — the
place a per-vector scale gets to live that the contiguous in-scan cache
never had.  Dequantization happens at attention time on the gathered
panel; `_decode_attention` then accumulates in f32 as always.

Everything jit-traceable is a pure function over `KVPoolView` (a pytree
riding the decode scan's carry); `PagedKVPool` is the host-side owner:
device arrays + free list + exact accounting.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp

# the never-allocated block absorbing invalid-slot / padding writes
SCRATCH_BLOCK = 0

KV_QUANT_MODES = (None, "int8", "fp8")
_QDTYPE = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}


class KVPoolView(NamedTuple):
    """The pool's device arrays, as traced through the compiled steps.

    k/v: (num_blocks, block_tokens, L, KVH, Dh) in the resting dtype
    (resolved_cache_dtype, or int8/e4m3 when quantized); k_scale/v_scale:
    (num_blocks, block_tokens, L, KVH) f32 per-head-vector absmax scales,
    None on the unquantized path (None prunes to an empty pytree subtree,
    so the compiled step never sees the operands)."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array]
    v_scale: Optional[jax.Array]


class PageRef(NamedTuple):
    """Per-slot cache coordinates for one decode step (loop-invariant
    across layers): tables (S, max_blocks) physical block ids (unused
    entries -> SCRATCH_BLOCK), blk/off (S,) this token's write block and
    in-block offset, pos (S,) each slot's current length (the attention
    mask bound)."""

    tables: jax.Array
    blk: jax.Array
    off: jax.Array
    pos: jax.Array


def page_ref(tables, pos, block_tokens: int) -> PageRef:
    """Derive the write coordinates once per token, outside the layer
    scan: position p lands in logical block p // block_tokens at offset
    p % block_tokens."""
    j = pos // block_tokens
    blk = jnp.take_along_axis(tables, j[:, None], axis=1)[:, 0]
    return PageRef(tables, blk, off=pos % block_tokens, pos=pos)


def quant_mode(view: KVPoolView) -> Optional[str]:
    """The pool's quantization mode, read off its STATIC dtypes — no
    extra non-array argument has to thread through jit."""
    if view.k_scale is None:
        return None
    return "int8" if view.k.dtype == jnp.int8 else "fp8"


def _quant_vectors(x, mode: str):
    """(..., Dh) f32-able -> (q same shape, scales (...,)) via the
    grad-comm blockwise-absmax codec with codec block = the Dh head
    vector (parallel/comm.quantize_blockwise, round-to-nearest — KV
    vectors are read many times, so unbiasedness-via-dither buys nothing
    and costs a PRNG operand)."""
    from ..parallel.comm import quantize_blockwise
    dh = x.shape[-1]
    q, s = quantize_blockwise(
        x.astype(jnp.float32).reshape(-1), mode, block=dh
    )
    return q.reshape(x.shape), s.reshape(x.shape[:-1])


def paged_append(view: KVPoolView, k, v, l, page: PageRef) -> KVPoolView:
    """Write one token's K/V sliver per slot — k/v (S, KVH, Dh) — at
    (page.blk, page.off, l).  Invalid slots' coordinates point at the
    scratch block, so the scatter is branch-free."""
    mode = quant_mode(view)
    if mode is None:
        return view._replace(
            k=view.k.at[page.blk, page.off, l].set(k.astype(view.k.dtype)),
            v=view.v.at[page.blk, page.off, l].set(v.astype(view.v.dtype)),
        )
    qk, sk = _quant_vectors(k, mode)
    qv, sv = _quant_vectors(v, mode)
    return KVPoolView(
        k=view.k.at[page.blk, page.off, l].set(qk),
        v=view.v.at[page.blk, page.off, l].set(qv),
        k_scale=view.k_scale.at[page.blk, page.off, l].set(sk),
        v_scale=view.v_scale.at[page.blk, page.off, l].set(sv),
    )


def paged_panel(view: KVPoolView, l, page: PageRef, out_dtype):
    """Gather layer l's K/V panels through the block tables:
    (S, KVH, max_blocks * block_tokens, Dh) per side, ready for
    `_decode_attention`.  Unquantized panels stay in the pool's resting
    dtype (the attention consumes it directly); quantized panels
    dequantize to `out_dtype` here — the 1-byte blocks are what crossed
    HBM, the dequantized panel is attention-local."""
    mode = quant_mode(view)

    def panel(pool, scale):
        pl = jax.lax.dynamic_index_in_dim(pool, l, 2, keepdims=False)
        g = pl[page.tables]  # (S, Bmax, bt, KVH, Dh)
        s, bmax, bt, kvh, dh = g.shape
        g = g.reshape(s, bmax * bt, kvh, dh).swapaxes(1, 2)
        if mode is None:
            return g
        sl = jax.lax.dynamic_index_in_dim(scale, l, 2, keepdims=False)
        sg = sl[page.tables].reshape(s, bmax * bt, kvh).swapaxes(1, 2)
        return (g.astype(jnp.float32) * sg[..., None]).astype(out_dtype)

    return panel(view.k, view.k_scale), panel(view.v, view.v_scale)


def paged_append_span(view: KVPoolView, ks, vs, tables, pos0, count,
                      block_tokens: int) -> KVPoolView:
    """Commit a verified SPAN of tokens' K/V per slot — the speculative
    decoding multi-token append.  ks/vs: (L, S, KVH, K1, Dh) span K/V
    stacks (the verify scan's ys: span offset j is the token at absolute
    position pos0[s]+j); tables: (S, W) block tables; pos0: (S,) span
    base positions; count: (S,) int32 in [0, K1] — how many leading span
    offsets COMMIT.  Offsets >= count (rejected drafts, inactive slots,
    positions past the request's K/V horizon) route to the scratch block
    and never enter the pool, so acceptance truncates the write itself:
    no rejected-draft K/V to clean up, boundary-exact per slot (the
    block index comes through the slot's own table, same as the single-
    token `paged_append`).  One scatter per side covers all L layers."""
    L, S, KVH, K1, Dh = ks.shape
    j = jnp.arange(K1)[None, :]
    wpos = pos0[:, None] + j  # (S, K1) absolute write positions
    valid = j < count[:, None]
    W = tables.shape[1]
    # clamp the table lookup BEFORE masking: an invalid offset's write
    # position may index past the table, and OOB gather clamping would
    # otherwise read a real block id that the where() must override
    bidx = jnp.minimum(wpos // block_tokens, W - 1)
    blk = jnp.take_along_axis(tables, bidx, axis=1)
    blk = jnp.where(valid, blk, SCRATCH_BLOCK)
    off = jnp.where(valid, wpos % block_tokens, 0)

    def prep(a):  # (L, S, KVH, K1, Dh) -> (S*K1, L, KVH, Dh) slabs
        return a.transpose(1, 3, 0, 2, 4).reshape(S * K1, L, KVH, Dh)

    kb, vb = prep(ks), prep(vs)
    bf, of = blk.reshape(-1), off.reshape(-1)
    mode = quant_mode(view)
    if mode is None:
        return view._replace(
            k=view.k.at[bf, of].set(kb.astype(view.k.dtype)),
            v=view.v.at[bf, of].set(vb.astype(view.v.dtype)),
        )
    qk, sk = _quant_vectors(kb, mode)
    qv, sv = _quant_vectors(vb, mode)
    return KVPoolView(
        k=view.k.at[bf, of].set(qk),
        v=view.v.at[bf, of].set(qv),
        k_scale=view.k_scale.at[bf, of].set(sk),
        v_scale=view.v_scale.at[bf, of].set(sv),
    )


class BlockPayload(NamedTuple):
    """The CONTENTS of a set of pool blocks in transit between two
    engines' pools — the disaggregated prefill->decode migration unit
    (fleet/disagg.py).  Arrays keep the pool's RESTING dtype: a
    quantized pool hands off 1-byte blocks plus their f32 scales, so
    migrated bytes get the same 4x compression as pool bytes.  k/v:
    (n_blocks, block_tokens, L, KVH, Dh); scales (n_blocks, block_tokens,
    L, KVH) or None on the unquantized path."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array]
    v_scale: Optional[jax.Array]


def export_blocks(view: KVPoolView, ids: List[int]) -> BlockPayload:
    """Gather physical blocks `ids` out of the pool, contents only —
    the source side of a paged-KV migration.  The gather materializes
    fresh arrays, so the caller may free (and the pool reuse) the
    source blocks immediately after."""
    idx = jnp.asarray(list(ids), jnp.int32)

    def sel(a):
        return None if a is None else a[idx]

    return BlockPayload(sel(view.k), sel(view.v),
                        sel(view.k_scale), sel(view.v_scale))


def import_blocks(view: KVPoolView, ids: List[int],
                  payload: BlockPayload) -> KVPoolView:
    """Scatter a migrated payload into freshly allocated blocks `ids`
    of THIS pool — the destination side of a paged-KV migration.  The
    two pools must agree on resting dtype, quantization mode, and block
    geometry; a mismatch is refused up front naming both sides (the
    alternative is garbage K/V read through the decode panel)."""
    if payload.k.dtype != view.k.dtype:
        raise ValueError(
            f"paged-KV migration dtype mismatch: payload rests at "
            f"{jnp.dtype(payload.k.dtype)} but this pool at "
            f"{jnp.dtype(view.k.dtype)} — source and destination pools "
            "must share the same `quant` / cache dtype"
        )
    if (payload.k_scale is None) != (view.k_scale is None):
        raise ValueError(
            "paged-KV migration quantization mismatch: payload is "
            f"{'un' if payload.k_scale is None else ''}scaled but this "
            f"pool is {'un' if view.k_scale is None else ''}scaled"
        )
    if tuple(payload.k.shape[1:]) != tuple(view.k.shape[1:]):
        raise ValueError(
            f"paged-KV migration geometry mismatch: payload blocks are "
            f"{tuple(payload.k.shape[1:])} (block_tokens, L, KVH, Dh) "
            f"but this pool's are {tuple(view.k.shape[1:])}"
        )
    if len(ids) != payload.k.shape[0]:
        raise ValueError(
            f"{len(ids)} destination blocks for a "
            f"{payload.k.shape[0]}-block payload"
        )
    idx = jnp.asarray(list(ids), jnp.int32)
    new = view._replace(
        k=view.k.at[idx].set(payload.k),
        v=view.v.at[idx].set(payload.v),
    )
    if view.k_scale is not None:
        new = new._replace(
            k_scale=view.k_scale.at[idx].set(payload.k_scale),
            v_scale=view.v_scale.at[idx].set(payload.v_scale),
        )
    return new


def payload_bytes(payload: BlockPayload) -> int:
    """The migration's wire footprint: what actually moves between the
    pools (resting-dtype blocks + scales — NOT the dequantized f32
    size), summed from the arrays' own dtypes/shapes so the priced
    number is measured, not modeled."""
    return int(sum(
        a.size * jnp.dtype(a.dtype).itemsize
        for a in payload if a is not None
    ))


def paged_scatter(view: KVPoolView, ks, vs, block_ids,
                  block_tokens: int) -> KVPoolView:
    """Scatter a prefill's full-prompt K/V — ks/vs (L, 1, KVH, P, Dh)
    from the `return_kv` forward hook — into the pool blocks `block_ids`
    ((P / block_tokens,) physical ids; bucket-padding tail entries point
    at scratch).  P is the bucket length, always a block multiple."""
    mode = quant_mode(view)

    def prep(a):
        L, b, kvh, p, dh = a.shape  # b == 1: prefill is per-request
        a = a[:, 0].transpose(2, 0, 1, 3)  # (P, L, KVH, Dh)
        return a.reshape(p // block_tokens, block_tokens, L, kvh, dh)

    kb, vb = prep(ks), prep(vs)
    if mode is None:
        return view._replace(
            k=view.k.at[block_ids].set(kb.astype(view.k.dtype)),
            v=view.v.at[block_ids].set(vb.astype(view.v.dtype)),
        )
    qk, sk = _quant_vectors(kb, mode)
    qv, sv = _quant_vectors(vb, mode)
    return KVPoolView(
        k=view.k.at[block_ids].set(qk),
        v=view.v.at[block_ids].set(qv),
        k_scale=view.k_scale.at[block_ids].set(sk),
        v_scale=view.v_scale.at[block_ids].set(sv),
    )


class PagedKVPool:
    """Host-side pool owner: the device arrays plus exact block
    accounting.  `num_blocks` is the USABLE count — one extra scratch
    block is allocated on top and never handed out.

    Blocks are REFCOUNTED (the prefix-cache extension of the original
    LIFO free list): `alloc` hands a block out at refcount 1, `share`
    bumps it for every additional holder (a second request's block
    table aliasing a shared prefix, or the radix tree keeping a
    finished request's prompt blocks warm), and `free_blocks` is a
    DECREMENT — the block returns to the free list only when its last
    holder lets go.  With no sharing in play every refcount is 1 and
    the semantics (and the LIFO realloc determinism the tests pin) are
    byte-identical to the pre-refcount pool.  The exact-accounting
    invariant becomes: free + distinct-allocated == usable, and every
    allocated block's refcount equals its holder count (table
    occurrences + one for a prefix-tree node) — what
    tests/test_serving_prefix.py asserts per tick."""

    def __init__(self, *, n_layer: int, kv_heads: int, head_dim: int,
                 num_blocks: int, block_tokens: int, dtype,
                 quant: Optional[str] = None):
        if quant not in KV_QUANT_MODES:
            raise ValueError(
                f"KV-cache quant must be one of {KV_QUANT_MODES}, "
                f"got {quant!r}"
            )
        if num_blocks < 1 or block_tokens < 1:
            raise ValueError("num_blocks and block_tokens must be >= 1")
        self.num_usable = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self.quant = quant
        total = self.num_usable + 1  # + scratch
        shape = (total, block_tokens, n_layer, kv_heads, head_dim)
        rest = _QDTYPE.get(quant, dtype)

        def scale():
            # distinct arrays per side: the view is DONATED through the
            # compiled steps, and two fields aliasing one zeros buffer
            # would be a double donation
            return jnp.zeros(shape[:-1], jnp.float32) if quant else None

        self.view = KVPoolView(
            k=jnp.zeros(shape, rest), v=jnp.zeros(shape, rest),
            k_scale=scale(), v_scale=scale(),
        )
        # pop() hands out ascending ids from 1; frees push back LIFO —
        # both deterministic, which the realloc-determinism test pins
        self._free: List[int] = list(range(total - 1, 0, -1))
        # block id -> holder count, for every allocated block (ids in
        # the free list never appear here)
        self._ref: Dict[int, int] = {}

    # -- accounting ---------------------------------------------------------

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        """DISTINCT allocated blocks — a block aliased by three holders
        still occupies one physical block."""
        return self.num_usable - len(self._free)

    def refcount(self, b: int) -> int:
        """Holder count of block `b` (0 = free)."""
        return self._ref.get(int(b), 0)

    def ref_counts(self) -> Dict[int, int]:
        """{block id: holder count} snapshot over every allocated block
        — what the per-tick exact-accounting pin compares against the
        holders it can enumerate (active tables + prefix-tree nodes)."""
        return dict(self._ref)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n physical block ids at refcount 1, or None WITHOUT
        allocating when fewer than n are free (admission is
        all-or-nothing)."""
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            self._ref[b] = 1
        return ids

    def share(self, ids: List[int]) -> None:
        """Add one holder to each allocated block in `ids` — the
        aliasing primitive: a new request's block table (or the prefix
        tree) referencing blocks some other holder already owns.
        Sharing a free block is refused: its contents are up for
        reuse, so an alias would read garbage."""
        for b in ids:
            if self._ref.get(b, 0) < 1:
                raise ValueError(
                    f"cannot share block {b}: not allocated (a free "
                    "block's contents are reusable garbage)"
                )
        for b in ids:
            self._ref[b] += 1

    def free_blocks(self, ids: List[int]) -> None:
        """Drop one holder per id; a block whose LAST holder lets go
        returns to the free list (LIFO, in `ids` order — with all
        refcounts at 1 this is exactly the pre-refcount extend)."""
        from collections import Counter
        drops = Counter(int(b) for b in ids)
        for b, n in drops.items():
            if not 1 <= b <= self.num_usable:
                raise ValueError(f"freeing invalid block id {b}")
            if self._ref.get(b, 0) < n:
                raise ValueError(
                    f"double free of block {b}: {n} release(s) against "
                    f"refcount {self._ref.get(b, 0)}"
                )
        for b in ids:
            b = int(b)
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._free.append(b)

    def kv_bytes(self) -> dict:
        """The pool's resting HBM footprint, FROM the device arrays'
        dtypes/shapes (what the quantization acceptance asserts against,
        not a model): K+V block bytes, scale bytes, and the per-element
        width."""
        k = self.view.k
        blocks = 2 * k.size * jnp.dtype(k.dtype).itemsize
        scales = (
            2 * self.view.k_scale.size
            * jnp.dtype(self.view.k_scale.dtype).itemsize
            if self.view.k_scale is not None else 0
        )
        return {
            "kv_block_bytes": int(blocks),
            "scale_bytes": int(scales),
            "total_bytes": int(blocks + scales),
            "dtype": str(jnp.dtype(k.dtype)),
            "itemsize": int(jnp.dtype(k.dtype).itemsize),
        }
