# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Synthetic serving load: Poisson arrivals through the engine, and the
serial `generate()` baseline the continuous-batching numbers are judged
against.  Shared by `scripts/serve_bench.py`, `bench.py` (BENCH_SERVE)
and tests/test_serving.py so the three never measure different things.
"""

from __future__ import annotations

import time
from typing import List, NamedTuple, Optional, Sequence

import numpy as np


class Arrival(NamedTuple):
    """One trace entry: when (seconds from trace start; 0.0 everywhere
    = closed-loop max-pressure mode), what prompt, how many tokens —
    plus an optional per-request completion deadline (seconds from
    submission; the engine's SLO machinery sheds/expires around it)
    and an optional tenant tag (multi-tenant scheduling)."""

    at_s: float
    prompt: List[int]
    max_new_tokens: int
    deadline_s: Optional[float] = None
    tenant: Optional[str] = None


def poisson_trace(n_requests: int, *, rate_rps: Optional[float],
                  prompt_lens: Sequence[int], max_new_tokens: int,
                  vocab_size: int, seed: int = 0,
                  deadline_s: Optional[float] = None) -> List[Arrival]:
    """Exponential inter-arrivals at `rate_rps` (None = all at t=0),
    prompts drawn uniformly from `prompt_lens` / the vocab.  Seeded —
    the same trace replays against every engine configuration.
    `deadline_s` stamps every arrival with the same completion SLO."""
    rng = np.random.default_rng(seed)
    t = 0.0
    trace = []
    for _ in range(n_requests):
        if rate_rps is not None:
            t += float(rng.exponential(1.0 / rate_rps))
        plen = int(rng.choice(np.asarray(prompt_lens)))
        prompt = rng.integers(0, vocab_size, size=plen).tolist()
        trace.append(Arrival(t, prompt, max_new_tokens, deadline_s))
    return trace


def shared_prefix_trace(n_requests: int, *,
                        rate_rps: Optional[float],
                        prefix_pool: int, prefix_len: int,
                        suffix_lens: Sequence[int],
                        max_new_tokens: int, vocab_size: int,
                        zipf_a: float = 1.2, seed: int = 0,
                        deadline_s: Optional[float] = None,
                        tenants: Optional[dict] = None) -> List[Arrival]:
    """The millions-of-users workload shape: `prefix_pool` distinct
    system prompts of `prefix_len` tokens, each arrival picking one
    Zipf-weighted (a few prompts dominate, a long tail exists — the
    regime prefix caching exists for) and appending a random suffix
    drawn from `suffix_lens`.  `tenants` maps tenant name -> arrival
    weight; each arrival is tagged with a tenant drawn from the
    normalized weights (None = untagged traffic).  Seeded — the same
    trace replays against every engine configuration, which is what
    makes the cache-on/off A/B one workload."""
    if prefix_pool < 1 or prefix_len < 1:
        raise ValueError("prefix_pool and prefix_len must be >= 1")
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, vocab_size, size=prefix_len).tolist()
                for _ in range(prefix_pool)]
    # Zipf over the prefix pool: rank r with weight 1/(r+1)^a
    w = 1.0 / np.arange(1, prefix_pool + 1, dtype=np.float64) ** zipf_a
    w /= w.sum()
    names, tw = None, None
    if tenants:
        names = sorted(tenants)
        tw = np.asarray([float(tenants[n]) for n in names])
        tw = tw / tw.sum()
    t = 0.0
    trace = []
    for _ in range(n_requests):
        if rate_rps is not None:
            t += float(rng.exponential(1.0 / rate_rps))
        i = int(rng.choice(prefix_pool, p=w))
        slen = int(rng.choice(np.asarray(suffix_lens)))
        prompt = prefixes[i] + rng.integers(
            0, vocab_size, size=slen).tolist()
        tenant = (str(rng.choice(names, p=tw))
                  if names is not None else None)
        trace.append(Arrival(t, prompt, max_new_tokens, deadline_s,
                             tenant))
    return trace


def _latency_stats(lats: List[float]) -> dict:
    if not lats:
        return {"p50_ms": 0.0, "p99_ms": 0.0, "mean_ms": 0.0}
    a = np.asarray(lats) * 1e3
    return {
        "p50_ms": round(float(np.percentile(a, 50)), 3),
        "p99_ms": round(float(np.percentile(a, 99)), 3),
        "mean_ms": round(float(a.mean()), 3),
    }


def run_trace(engine, trace: Sequence[Arrival], *,
              realtime: bool = True, max_ticks: int = 200_000,
              no_progress_ticks: int = 2_000,
              slo=None, live=None) -> dict:
    """Drive `engine` (serving.ServingEngine or a ChaosServingEngine
    wrapper) through the trace.

    realtime=True honors arrival times with wall-clock waits (what the
    latency percentiles mean under open-loop load); realtime=False
    submits each arrival as soon as the engine drains ahead of it
    (closed-loop — tests use it to avoid sleeping).  Returns outputs
    per request plus aggregate metrics; per-token latency covers every
    produced token (first token = TTFT).  `status_counts` and
    `ok_tokens_per_s` (goodput: tokens of requests that finished "ok")
    summarize the terminal outcomes under faults/SLOs.

    `no_progress_ticks` bounds LIVELOCK, which `max_ticks` alone cannot:
    an engine that can never admit its queue (e.g. every prompt refused
    after the pool shrank) ticks forever producing nothing.  After that
    many CONSECUTIVE zero-token ticks with work still pending, raise
    with the queue/pool state named instead of spinning to max_ticks."""
    if slo is not None:
        # SLO error budgets (telemetry/slo.py): attached through the
        # engine's own hook so fleet/disagg/chaos wrappers fan the
        # tracker out to every underlying engine
        engine.attach_slo(slo)
    if live is not None:
        engine.attach_live(live)
    requests = []
    pending = list(trace)
    occupancy = []
    pool_util = []
    t0 = time.monotonic()
    ticks = 0
    idle_ticks = 0
    while pending or engine.queue_depth or engine.n_active:
        now = time.monotonic() - t0
        while pending and (not realtime or pending[0].at_s <= now):
            if not realtime:
                # closed-loop feed target: enough queued to fill every
                # free slot next tick (a one-per-spin feed starves a
                # multi-slot fleet's occupancy), capped at the engine's
                # own queue watermark and checked BEFORE submitting —
                # pushing the queue TO the watermark and then feeding
                # into it would shed arrivals that the engine could
                # serve one tick later, turning max-pressure mode into
                # a shed artifact whenever max_queue < max_active
                free = engine.config.max_active - engine.n_active
                target = max(1, free)
                cap = getattr(engine.config, "max_queue", None)
                if cap is not None:
                    target = max(1, min(target, cap))
                if engine.queue_depth >= target:
                    break
            a = pending.pop(0)
            req = engine.submit(
                a.prompt, a.max_new_tokens, deadline_s=a.deadline_s,
                tenant=a.tenant)
            requests.append(req)
            if not realtime and req.status is not None:
                # a TENANT-scoped door shed refuses one tenant, not the
                # engine — other tenants' arrivals must keep feeding or
                # the abuser's sheds would inflate the well-behaved
                # tenants' measured TTFT (the isolation A/B's number)
                if str(req.finish_reason or "").endswith(
                        "tenant_queue_watermark"):
                    continue
                break  # engine-level watermark: it is refusing load
        if (realtime and not engine.queue_depth and not engine.n_active
                and pending):
            # open-loop idle: nothing in flight, next arrival is in the
            # future — wait for it instead of spinning
            time.sleep(max(0.0, pending[0].at_s - (
                time.monotonic() - t0)))
            continue
        if engine.queue_depth or engine.n_active:
            produced = engine.tick()
            occupancy.append(engine.n_active / engine.config.max_active)
            pool_util.append(
                engine.pool.blocks_in_use / engine.pool.num_usable)
            idle_ticks = 0 if produced else idle_ticks + 1
            if idle_ticks >= no_progress_ticks:
                raise RuntimeError(
                    f"engine made no progress for {idle_ticks} "
                    f"consecutive ticks: queue_depth="
                    f"{engine.queue_depth}, active={engine.n_active}, "
                    f"pool blocks_free={engine.pool.blocks_free}/"
                    f"{engine.pool.num_usable} — every queued request "
                    "is unadmittable (pool too small for its prompt, "
                    "or blocks leaked)"
                )
        ticks += 1
        if ticks > max_ticks:
            raise RuntimeError(f"trace did not drain in {max_ticks} ticks")
    wall = time.monotonic() - t0
    toks = sum(len(r.tokens) for r in requests)
    lats = [lat for r in requests for lat in r.token_lat]
    status_counts = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
    for r in requests:
        status_counts[r.status] = status_counts.get(r.status, 0) + 1
    ok_toks = sum(len(r.tokens) for r in requests if r.status == "ok")
    # aggregate latency attribution (the per-request partition summed
    # across the trace): where the trace's total request-seconds went —
    # the bench-JSON view of what serve_report.py breaks down per tail
    comp_totals = {
        k: round(sum(r.lat_components[k] for r in requests), 4)
        for k in ("queue", "prefill", "decode", "preempt", "restart",
                  "migrate")
    }
    # per-tenant aggregates (absent on untagged traffic): goodput,
    # p99 TTFT / end-to-end latency, and terminal outcomes per tenant
    # — the ONE surface the bench, the report, and the isolation pin
    # all read
    by_tenant: dict = {}
    for r in requests:
        if r.tenant is not None:
            by_tenant.setdefault(r.tenant, []).append(r)
    tenants_out = None
    if by_tenant:
        tenants_out = {}
        for name in sorted(by_tenant):
            rs = by_tenant[name]
            ttfts = [r.t_first - r.t_arrival for r in rs
                     if r.t_first is not None]
            lats_t = [r.t_done - r.t_arrival for r in rs
                      if r.t_done is not None]
            sc = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
            for r in rs:
                sc[r.status] = sc.get(r.status, 0) + 1
            tenants_out[name] = {
                "requests": len(rs),
                "status_counts": sc,
                "tokens": sum(len(r.tokens) for r in rs),
                "ok_tokens_per_s": round(
                    sum(len(r.tokens) for r in rs
                        if r.status == "ok") / max(wall, 1e-9), 2),
                "ttft": _latency_stats(ttfts),
                "latency": _latency_stats(lats_t),
            }
        ts = getattr(engine, "tenant_stats", lambda: None)()
        if ts:
            for name, st in ts.items():
                if name in tenants_out:
                    tenants_out[name]["scheduler"] = st
    # shared-prefix cache aggregate (absent with the cache off)
    prefix_out = getattr(engine, "prefix_stats", lambda: None)()
    # speculative-decoding aggregate (zeros stay absent: a spec-off
    # trace reports exactly the pre-spec dict)
    spec_proposed = sum(r.spec_proposed for r in requests)
    spec = None
    if spec_proposed:
        spec_accepted = sum(r.spec_accepted for r in requests)
        spec = {
            "proposed": spec_proposed,
            "accepted": spec_accepted,
            "accept_rate": round(
                spec_accepted / max(1, spec_proposed), 4),
        }
    out = {
        "outputs": {r.id: list(r.tokens) for r in requests},
        "requests": requests,
        "tokens": toks,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(toks / max(wall, 1e-9), 2),
        # goodput: only tokens delivered to requests that finished OK
        # count — shed/expired/failed work is wasted capacity
        "ok_tokens_per_s": round(ok_toks / max(wall, 1e-9), 2),
        "status_counts": status_counts,
        "restarts": engine.restarts,
        "token_latency": _latency_stats(lats),
        "ttft": _latency_stats(
            [r.t_first - r.t_arrival for r in requests
             if r.t_first is not None]),
        "latency_components_s": comp_totals,
        "mean_occupancy": round(float(np.mean(occupancy)), 4)
        if occupancy else 0.0,
        "mean_pool_utilization": round(float(np.mean(pool_util)), 4)
        if pool_util else 0.0,
        "evictions": engine._evictions,
        "preemptions": sum(r.preemptions for r in requests),
    }
    if spec is not None:
        out["spec"] = spec
    if tenants_out is not None:
        out["tenants"] = tenants_out
    if prefix_out is not None:
        out["prefix_cache"] = prefix_out
    if slo is not None:
        out["slo"] = slo.snapshot()
    return out


def run_serial(model, params, trace: Sequence[Arrival], *,
               temperature: float = 0.0,
               top_k: Optional[int] = None) -> dict:
    """The one-at-a-time baseline: the SAME trace through
    `GPT2Model.generate`, each request starting when the previous
    finishes (or when it arrives, whichever is later).  Its per-request
    tokens are also the greedy-parity reference for the batched path."""
    import jax

    outputs = []
    lats: List[float] = []
    t0 = time.monotonic()
    for i, a in enumerate(trace):
        now = time.monotonic() - t0
        if a.at_s > now:
            time.sleep(a.at_s - now)
        t_req = time.monotonic()
        out = model.generate(
            params, np.asarray(a.prompt, np.int32)[None, :],
            a.max_new_tokens, temperature=temperature, top_k=top_k,
            key=jax.random.PRNGKey(i) if temperature != 0.0 else None,
        )
        toks = np.asarray(out)[0, len(a.prompt):].tolist()
        dt = time.monotonic() - t_req
        outputs.append(toks)
        # serial tokens surface all at once: attribute the request wall
        # evenly (the honest per-token number a one-shot script delivers)
        lats.extend([dt / max(len(toks), 1)] * len(toks))
    wall = time.monotonic() - t0
    n = sum(len(o) for o in outputs)
    return {
        "outputs": outputs,
        "tokens": n,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(n / max(wall, 1e-9), 2),
        "token_latency": _latency_stats(lats),
    }
