# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Shared-prefix KV reuse: a radix tree of committed full blocks over
the refcounted paged pool.

System-prompt-heavy traffic (the millions-of-users shape) re-prefills
the same leading tokens for every request, but a position's K/V is a
pure function of the token prefix up to it — a causal forward never
looks right — so two prompts sharing their first m*block_tokens tokens
can share the physical blocks holding those positions.  This module is
the host-side index that makes the sharing findable:

  * the tree is a RADIX over per-block token tuples: one node per
    committed FULL block, keyed by the `block_tokens` tokens it holds,
    children keyed by the next block's tokens.  Matching a prompt walks
    from the root block-by-block; the matched path's physical blocks
    alias straight into the new request's block table (the pool `share`
    primitive bumps their refcounts) and only the unmatched SUFFIX pays
    a prefill.
  * COPY-ON-WRITE discipline without any copying: only FULL blocks that
    sit entirely BEHIND the request's last prompt position are ever
    aliased, so the partially-filled tail block and the first
    decode-write block are always freshly allocated private blocks —
    every write the request will ever issue lands in blocks it owns
    alone.  (The speculative-decoding scratch-block machinery already
    proved in-flight writes can be routed away from shared state; here
    the routing is simpler — shared blocks are read-only by
    construction.)
  * the tree's ownership is one refcount per node (`pool.share` at
    insert), which is what keeps a finished request's prompt blocks
    WARM after its table is freed.  Under pool pressure the engine
    calls `evict`: leaves whose block has no other holder
    (refcount == 1) drop LRU-by-last-hit-tick until enough blocks
    free — a shared block is never freed while referenced, and an
    interior node never drops before its children (children's K/V is
    conditioned on the parent path, so a dangling subtree could never
    be matched again anyway).

The tree never touches device memory itself: blocks stay in the pool,
the tree holds ids.  A warm restart or journal recovery rebuilds pool
AND tree from empty — the cache is an optimization, never part of the
durability story (stated in ServingEngine.recover's contract).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class _Node:
    """One committed full block: `key` is the block's token tuple (the
    edge from the parent), `block` the physical id the tree holds one
    refcount on, `last_hit` the scheduler tick of the last match/insert
    through this node (the LRU eviction key)."""

    __slots__ = ("key", "block", "children", "parent", "last_hit")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Optional["_Node"], last_hit: int):
        self.key = key
        self.block = block
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_hit = last_hit


class PrefixCache:
    """Radix tree of committed full blocks keyed by token prefix."""

    def __init__(self, block_tokens: int):
        self.block_tokens = int(block_tokens)
        self._root = _Node((), -1, None, 0)  # sentinel, holds no block
        self._nodes = 0
        # lifetime counters (the engine's gauges/stats read these;
        # advanced by `note_admission` on LANDED admissions only)
        self.hits = 0          # admissions that aliased >= 1 block
        self.misses = 0        # admissions that aliased none
        self.blocks_aliased = 0
        self.tokens_avoided = 0
        self.prompt_tokens = 0  # total prompt tokens at admissions
        self.evicted = 0

    def __len__(self) -> int:
        return self._nodes

    def reset_stats(self) -> None:
        """Zero the lifetime counters WITHOUT touching the tree — the
        bench warmup path: warm requests should compile the suffix
        program and may warm the tree, but must not inflate the
        measured pass's hit-rate stats."""
        self.hits = self.misses = 0
        self.blocks_aliased = self.tokens_avoided = 0
        self.prompt_tokens = self.evicted = 0

    def _chunks(self, tokens: Sequence[int], n_blocks: int):
        bt = self.block_tokens
        for i in range(n_blocks):
            yield tuple(int(t) for t in tokens[i * bt:(i + 1) * bt])

    # -- lookup -------------------------------------------------------------

    def match(self, tokens: Sequence[int], *, limit: int,
              tick: int) -> List[int]:
        """Physical block ids of the longest cached full-block prefix
        of `tokens`, at most `limit` blocks (the caller caps at
        (p-1)//block_tokens so at least one prompt token is always left
        for the suffix prefill — which is also what keeps every
        writable block private).  Refreshes last_hit along the matched
        path.  The caller must `pool.share` the returned ids before
        any allocation that could trigger eviction, and calls
        `note_admission` once the admission actually lands — a match
        whose admission rolls back on pool exhaustion never counts
        (the hit-rate stats describe work AVOIDED, not work found)."""
        node = self._root
        out: List[int] = []
        for chunk in self._chunks(tokens, limit):
            nxt = node.children.get(chunk)
            if nxt is None:
                break
            nxt.last_hit = tick
            out.append(nxt.block)
            node = nxt
        return out

    def note_admission(self, n_aliased: int, prompt_tokens: int) -> None:
        """Record one LANDED admission's cache outcome (the engine
        calls this after the prefill succeeds)."""
        if n_aliased:
            self.hits += 1
            self.blocks_aliased += n_aliased
            self.tokens_avoided += n_aliased * self.block_tokens
        else:
            self.misses += 1
        self.prompt_tokens += prompt_tokens

    # -- insert -------------------------------------------------------------

    def insert(self, tokens: Sequence[int], table: Sequence[int], pool,
               *, tick: int) -> int:
        """Commit the FULL blocks of an admitted request's prompt:
        `table[i]` holds tokens[i*bt:(i+1)*bt] for every full block
        (the caller passes exactly len(tokens)//bt table entries).  New
        nodes take one `pool.share` refcount each — the tree's own
        ownership, independent of the request's table.  A path already
        present keeps its EXISTING block (the contents are the same by
        the prefix-determinism argument; dropping the duplicate spares
        a redundant warm block) and just refreshes last_hit.  Returns
        the number of new nodes."""
        bt = self.block_tokens
        n = min(len(tokens) // bt, len(table))
        node = self._root
        added = 0
        for i, chunk in enumerate(self._chunks(tokens, n)):
            if len(chunk) < bt:
                break
            nxt = node.children.get(chunk)
            if nxt is None:
                pool.share([table[i]])
                nxt = _Node(chunk, int(table[i]), node, tick)
                node.children[chunk] = nxt
                self._nodes += 1
                added += 1
            else:
                nxt.last_hit = tick
            node = nxt
        return added

    # -- eviction -----------------------------------------------------------

    def _leaves(self) -> List[_Node]:
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evict(self, pool, *, need: int) -> int:
        """Drop unreferenced leaves (block refcount == 1 — the tree is
        the only holder, so freeing actually returns a block) LRU by
        last_hit until `need` blocks freed or nothing droppable
        remains.  ONE leaf scan seeds a heap; a drop that leaves its
        parent childless pushes the parent as a new candidate (its
        eligibility re-checked at pop — alloc-failure paths call this
        repeatedly, so the per-call work must stay O(leaves log
        leaves + freed), not O(leaves x freed)).  Returns blocks
        freed."""
        import heapq
        heap = [(n.last_hit, n.block, n) for n in self._leaves()]
        heapq.heapify(heap)
        freed = 0
        while freed < need and heap:
            _, _, victim = heapq.heappop(heap)
            if victim.children or pool.refcount(victim.block) != 1:
                continue  # grew children / still referenced: skip
            parent = victim.parent
            del parent.children[victim.key]
            pool.free_blocks([victim.block])
            self._nodes -= 1
            self.evicted += 1
            freed += 1
            if parent is not self._root and not parent.children:
                heapq.heappush(heap,
                               (parent.last_hit, parent.block, parent))
        return freed

    # -- introspection ------------------------------------------------------

    def blocks(self) -> List[int]:
        """Every block id the tree currently holds a refcount on."""
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            out.append(n.block)
            stack.extend(n.children.values())
        return out

    def reclaimable(self, pool) -> int:
        """Blocks the tree could hand back under pressure right now
        (held by the tree alone) — what the pool-watermark shed check
        subtracts from raw utilization: warm cache must not read as
        overload."""
        return sum(1 for b in self.blocks() if pool.refcount(b) == 1)
