# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Decode-health guard: quarantine poisoned slots, watchdog the engine.

A non-finite decode logit means a request's next token is garbage — and
with continuous batching one poisoned request must not take the other
`max_active - 1` down with it.  The check is CHEAP by construction: the
compiled decode step already reduces each slot's logits to a per-slot
`bad` flag on device ((S,) bool, `~all(isfinite(logits), -1)`), and the
host reads it off the SAME computation it already fetches the sampled
tokens from — no extra device sync, no second program.

Containment is per-slot: a poisoned slot's request is marked `failed`
(terminal status; its blocks return to the pool) while every other slot
keeps serving — slots are independent in the paged attention (per-slot
panels, per-slot masks), and masked reads of a freed block's stale NaNs
resolve through `jnp.where(mask, att, -inf)` before the softmax, so a
later owner of those blocks is untouched (pinned by the quarantine-storm
pool-accounting test).

The watchdog is the escalation path: K CONSECUTIVE poisoned ticks, or an
exception out of a tick's compiled step, means the fault is not one bad
request but the engine itself (poisoned weights, a wedged pool view) —
the engine then warm-restarts: fresh pool + slot array, every in-flight
request re-queued front-of-line with its produced prefix (the preemption
resume path, so greedy requests continue token-exact), compiled programs
kept (same shapes/dtypes — no recompile).
"""

from __future__ import annotations

from typing import List, Optional, Sequence


class DecodeHealthGuard:
    """Per-tick decode-health bookkeeping.

    `observe(bad, active)` takes the decode step's per-slot non-finite
    flags and the active slot indices, returns the slot indices to
    quarantine (only ACTIVE slots — invalid slots compute on scratch
    garbage by design and their flags mean nothing).  `should_restart`
    latches after `k_restart` consecutive poisoned ticks; the engine
    calls `reset()` after the warm restart it triggers."""

    def __init__(self, k_restart: int = 3):
        if k_restart < 1:
            raise ValueError("k_restart must be >= 1")
        self.k_restart = int(k_restart)
        self.consecutive_poisoned = 0
        self.quarantined_total = 0

    def observe(self, bad: Sequence[bool],
                active: Sequence[int]) -> List[int]:
        poisoned = [i for i in active if bool(bad[i])]
        if poisoned:
            self.consecutive_poisoned += 1
            self.quarantined_total += len(poisoned)
        else:
            self.consecutive_poisoned = 0
        return poisoned

    @property
    def should_restart(self) -> bool:
        return self.consecutive_poisoned >= self.k_restart

    def reset(self) -> None:
        self.consecutive_poisoned = 0
