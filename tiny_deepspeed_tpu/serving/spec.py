# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Speculative decoding over the continuous-batching scheduler.

Plain serving decode commits exactly ONE token per request per tick —
each token pays a full target-model pass, and on small decode batches
the chips idle on memory-bound work.  Speculative decoding (Leviathan
et al., arXiv:2211.17192) converts that idle into parallel
verification: a cheap DRAFTER proposes up to K continuation tokens per
slot (serving/drafter.py — model-free prompt-lookup, or a small
same-family model), and ONE target pass scores all K+1 span positions
per slot at once.  The acceptance core keeps the target distribution
exact (greedy short-circuits to token equality, so greedy speculative
output is bit-identical to `generate`); each verify commits between 1
and K+1 tokens.

The verify program is ONE shape-stable jit, the spec analogue of the
engine's decode step — same (S,) slot-array discipline, same block
tables, same per-slot (seed, position) sampling keys:

  * span embeddings at vector per-(slot, offset) positions;
  * `paged_verify` reads the COMMITTED prefix through the block tables
    (read-only pool view) while the span attends to itself under a
    windowed causal mask — draft K/V never touch the pool during
    scoring;
  * acceptance (models/sampling.spec_accept_per_slot) runs in-program,
    and `pool.paged_append_span` commits exactly the accepted prefix's
    K/V in the same program — rejected-draft K/V route to the scratch
    block, so nothing speculative ever rests in the pool;
  * the per-slot non-finite health flag covers the WHOLE span (the
    decode-health guard quarantines a poisoned slot exactly as on the
    plain path).

The engine (`ServingEngine._decode_spec`) owns scheduling around it:
block growth covers the span horizon, committed tokens journal, and
the SLO shed price re-bases on wall per COMMITTED token.
"""

from __future__ import annotations

import numpy as np

from .drafter import make_drafter
from .pool import page_ref, paged_append_span

# hard ceiling on the draft span: k+1 verify positions multiply decode
# FLOPs and the span must stay well under a pool block in practice
MAX_SPEC_K = 16


class SpecDecoder:
    """One engine's speculative-decoding state: the drafter and the
    compiled verify program.  Stateless across ticks beyond the
    drafter's own cache — everything positional comes from the engine's
    slots each call, which is what keeps preemption/restart/recovery
    composition free."""

    def __init__(self, model, params, config, base_key, *,
                 max_seq: int):
        import jax
        import jax.numpy as jnp

        from ..models.sampling import spec_accept_per_slot

        k = int(config.spec_k)
        if not 1 <= k <= MAX_SPEC_K:
            raise ValueError(
                f"spec_k={config.spec_k} out of range [1, {MAX_SPEC_K}]"
            )
        self.k = k
        self.drafter = make_drafter(
            config.spec_draft, model, params, k,
            max_active=config.max_active, max_seq=max_seq,
            block_tokens=config.block_tokens, seed=config.seed,
        )
        k1 = k + 1
        bt = config.block_tokens
        temp, top_k = config.temperature, config.top_k
        block_size = model.config.block_size

        def verify_step(params, stacked, view, spanx, pos0, tables,
                        seeds, nprod, limit_kv, poison):
            """spanx (S, K1+1) = [committed head, d_1..d_K, extra] —
            the scored span is the first K1 columns; the trailing
            `extra` is the drafter's bonus-position proposal, consumed
            only by the acceptance rule.  pos0 (S,) is the head's
            position; limit_kv (S,) the last position whose K/V the
            request will ever need (total-2; -1 for empty slots).
            Returns (accepted drafts (S,), final token (S,), bad (S,),
            view with the accepted prefix's K/V committed)."""
            span = spanx[:, :k1]
            extra = spanx[:, k1]
            positions = jnp.minimum(
                pos0[:, None] + jnp.arange(k1)[None, :], block_size - 1)
            x = model._embed_decode_span(params, span, positions)
            page = page_ref(tables, pos0, bt)
            x, sks, svs = model.paged_verify(stacked, x, view, page)
            logits = model.head_span(params, x) + poison[:, None, None]
            bad = ~jnp.all(jnp.isfinite(logits), axis=(1, 2))
            acc, final = spec_accept_per_slot(
                logits, span, extra, base_key, seeds, nprod, temp,
                top_k)
            # K/V commit count: the accepted prefix (head + acc drafts),
            # clamped to the request's K/V horizon — the final sampled
            # token's K/V is next tick's head write, never this one's
            count = jnp.clip(
                acc + 1, 0, jnp.maximum(limit_kv + 1 - pos0, 0))
            view = paged_append_span(view, sks, svs, tables, pos0,
                                     count, bt)
            return acc, final, bad, view

        # NOTE: a forced ServeConfig.paged_kernel mode is applied by the
        # ENGINE, which wraps this program (and a model drafter's) with
        # the same _kwrap bracketing as its own decode/prefill jits —
        # one copy of the discipline, in one place (engine.__init__)
        self._verify = jax.jit(verify_step, donate_argnums=(2,))

    def describe(self) -> str:
        return f"spec(k={self.k}, drafter={self.drafter.describe()})"

    def propose(self, slots) -> np.ndarray:
        """(S, K+1) draft proposals for the engine's slot array: K
        verifiable drafts + the bonus position's proposal."""
        return self.drafter.propose(slots)

    def on_admit(self, slot_i: int, prompt_now) -> int:
        """Rebuild the drafter's slot state; returns the drafter's
        proposal for the first post-prefix position (the spec prefill's
        accept-or-residual operand)."""
        return self.drafter.on_admit(slot_i, prompt_now)

    def verify(self, params, stacked, view, span, pos0, tables, seeds,
               nprod, limit_kv, poison):
        return self._verify(params, stacked, view, span, pos0, tables,
                            seeds, nprod, limit_kv, poison)
