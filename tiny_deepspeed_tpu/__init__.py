# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Tiny-DeepSpeed-TPU: a TPU-native re-design of Tiny-DeepSpeed's ZeRO stack.

A brand-new framework (JAX / XLA / pjit / Pallas) providing the capabilities of
the reference liangyuwang/Tiny-DeepSpeed (CUDA/torch, see /root/reference):
single-device, DDP, ZeRO-1, ZeRO-2 and ZeRO-3 training of GPT-2 models, a
custom op layer with swappable kernels and a runtime autotuner, a name-ordered
greedy parameter partitioner ("cache rank map"), and name-keyed SGD/AdamW
optimizers — all re-expressed TPU-first:

  * collectives are XLA collectives over a `jax.sharding.Mesh` (psum /
    reduce_scatter / all_gather over ICI), not NCCL calls in backward hooks
    (reference: tiny_deepspeed/core/zero/ddp/module.py:17-24);
  * compute/communication overlap comes from XLA's latency-hiding scheduler,
    not hand-written async handles (reference: ddp/module.py:36-78);
  * the hot fused kernels are Pallas (reference: Triton layernorm,
    ops/layernorm.py:158-298);
  * meta-device init + cache rank map (reference: zero/utils/partition.py)
    becomes `jax.eval_shape` + NamedSharding placement, so parameters are
    *created* sharded instead of materialized fully then sharded.

Public API shape mirrors the reference's flat surface
(`tiny_deepspeed/core/__init__.py:5-23`):

    from tiny_deepspeed_tpu import (
        DDP, Zero1, Zero2, Zero3, partition_tensors,
        SGD, AdamW, GPTConfig, GPT2Model,
    )
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map under experimental only, with the
    # replication check spelled `check_rep` instead of `check_vma`; the
    # parallel modules (pipeline / ring attention / Ulysses / MoE) call
    # the stable `jax.shard_map(..., check_vma=...)` spelling — adapt it
    # once at package import so every entry path works on both generations
    from jax.experimental.shard_map import shard_map as _shard_map

    def _shard_map_compat(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        names = kwargs.pop("axis_names", None)
        if names is not None:
            # new partial-manual spelling (manual over `axis_names`) ->
            # old complement spelling (auto over everything else)
            mesh = kwargs.get("mesh", args[0] if args else None)
            kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(names)
        return _shard_map(f, *args, **kwargs)

    _jax.shard_map = _shard_map_compat

from .parallel.partition import partition_tensors, materialize_owned
from .parallel.engine import SingleDevice, DDP, Zero1, Zero2, Zero3
from .parallel.mesh import make_mesh, init_distributed
from .optim import SGD, AdamW, schedule
from .models import (
    GPTConfig, GPT2Model, MoEConfig, MoEGPT, LlamaConfig, LlamaModel,
)
from .telemetry import Telemetry

# Reference-shaped optimizer names (reference core/__init__.py:5-23 exports
# DDPSGD/DDPAdamW/Zero{1,2,3}SGD/Zero{1,2,3}AdamW — one subclass per mode
# because each mode re-derives the step/broadcast logic).  Here the ZeRO
# stage lives entirely in the ENGINE (sharding strategy), so every "mode
# optimizer" IS the base optimizer; the aliases keep the reference's import
# surface working verbatim:  `Zero2(model, Zero2AdamW(lr=...))`.
DDPSGD = Zero1SGD = Zero2SGD = Zero3SGD = SGD
DDPAdamW = Zero1AdamW = Zero2AdamW = Zero3AdamW = AdamW

__version__ = "0.4.0"

__all__ = [
    "partition_tensors",
    "materialize_owned",
    "SingleDevice",
    "DDP",
    "Zero1",
    "Zero2",
    "Zero3",
    "make_mesh",
    "init_distributed",
    "SGD",
    "AdamW",
    "schedule",
    "DDPSGD", "DDPAdamW",
    "Zero1SGD", "Zero1AdamW",
    "Zero2SGD", "Zero2AdamW",
    "Zero3SGD", "Zero3AdamW",
    "GPTConfig",
    "GPT2Model",
    "MoEConfig",
    "MoEGPT",
    "LlamaConfig",
    "LlamaModel",
    "Telemetry",
]
