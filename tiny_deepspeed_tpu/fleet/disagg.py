# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Disaggregated serving: prefill and decode on SEPARATE engines, with
a priced paged-KV migration between their pools.

Why split: prefill is compute-bound and bursty (one big matmul panel
per admission), decode is memory-bound and steady (one token per slot
per tick) — on one engine every prefill stalls the whole decode batch
for its wall (the `tick` records' prefill_s spikes).  Disaggregation
gives each phase its own engine: the PREFILL engine runs
admission-only ticks (`ServingEngine.tick(decode=False)`) that fill
pool blocks and sample first tokens; each prefilled request then
migrates — `ServingEngine.export_request` gathers its blocks out of
the prefill pool in the pool's RESTING dtype, `import_request`
scatters them into the decode engine's pool and seats the slot at the
same (pos, last) coordinates, no re-prefill.  A quantized pool
(`quant="int8"|"fp8"`) therefore migrates 1-byte blocks + scales: the
handoff gets the same 4x compression the pool rests at, for free.

The handoff is PRICED, not modeled: `kv_migration_bytes` is summed
from the payload arrays' own dtypes/shapes, and `kv_migration_link`
classifies the transfer with `wire_link_split`'s granule logic — a
source/destination device set inside one DCN granule (slice/process)
rides ICI, anything spanning granules is billed to DCN.  Both land on
the request's JSONL record, so the disaggregation tax is a per-request
measured number in the dashboard (scripts/serve_report.py "Fleet").

Caveats, by construction: speculative decoding is refused (drafter
state only rebuilds through the prefill admission path); a decode-side
preemption or warm restart re-prefills ON the decode engine (its
`_admit` path — correctness first, phase purity second), so only the
first admission of each request is guaranteed to run on the prefill
engine.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Dict, Optional, Union

from ..serving.engine import ServeConfig, ServingEngine
from ..serving.journal import RequestJournal
from ..serving.pool import payload_bytes


def migration_link(src_devices, dst_devices, *,
                   granule_of: Optional[Dict[int, int]] = None,
                   dst_granule: Optional[int] = None) -> str:
    """"ici" or "dcn" for a transfer between two device sets — the
    `wire_link_split` granule logic applied to ONE handoff instead of a
    collective's replica group: devices inside one DCN granule
    (slice_index, else process_index) exchange over ICI; a transfer
    spanning granules must cross DCN and is billed there entirely.

    `granule_of` overrides the attribute-derived granules by device id
    (the same CPU-emulation idiom `wire_link_split` uses); `dst_granule`
    forces every DESTINATION device into that granule — how a CPU-mesh
    test, whose one physical device can never span granules, emulates a
    decode engine living on another slice."""
    src = list(src_devices)
    dst = list(dst_devices)

    def granule(d, forced=None):
        if forced is not None:
            return forced
        if granule_of is not None:
            return granule_of.get(d.id, d.id)
        for attr in ("slice_index", "process_index"):
            if hasattr(d, attr):
                return getattr(d, attr)
        return 0

    grans = ({granule(d) for d in src}
             | {granule(d, dst_granule) for d in dst})
    return "dcn" if len(grans) > 1 else "ici"


class DisaggEngine:
    """A prefill engine and a decode engine behind one driver surface.

    `config` shapes the DECODE engine (slots, pool, SLOs);
    `prefill_config` defaults to the same geometry — the pools MUST
    share block_tokens / max_seq_tokens / quant (import validates, a
    mismatch raises naming both sides), but prefill may run fewer
    slots.  `journal` (path or instance) is SHARED: both engines
    append to one WAL, so `recover()` on either side replays the whole
    pair's requests.

    Each tick: the prefill engine runs an admission-only tick, every
    parked prefilled slot migrates to the decode engine while it has a
    free slot + blocks (oldest admission first, head-of-line like the
    admission queue), then the decode engine runs a full tick.  A
    request that cannot migrate yet parks in its prefill slot — pool
    pressure on the decode side backs admission up into the prefill
    engine, which is the disaggregation flow-control story."""

    def __init__(self, model, params, config: ServeConfig = ServeConfig(),
                 *, prefill_config: Optional[ServeConfig] = None,
                 telemetry=None, logger=None,
                 journal: Union[None, str, RequestJournal] = None,
                 granule_of: Optional[Dict[int, int]] = None,
                 decode_granule: Optional[int] = None,
                 prefill_replica: int = 0, decode_replica: int = 1):
        if config.spec_draft is not None or (
                prefill_config is not None
                and prefill_config.spec_draft is not None):
            raise ValueError(
                "disaggregated serving does not compose with "
                "speculative decoding (spec_draft) — the drafter state "
                "only rebuilds through the prefill admission path, "
                "which import_request bypasses"
            )
        pcfg = prefill_config or config
        for knob in ("block_tokens", "max_seq_tokens", "quant"):
            if getattr(pcfg, knob) != getattr(config, knob):
                raise ValueError(
                    f"prefill/decode pool geometry must match to "
                    f"migrate blocks: {knob}="
                    f"{getattr(pcfg, knob)!r} (prefill) vs "
                    f"{getattr(config, knob)!r} (decode)"
                )
        j = RequestJournal(journal) if isinstance(journal, str) else journal
        self.prefill = ServingEngine(model, params, pcfg,
                                     telemetry=telemetry, logger=logger,
                                     journal=j,
                                     replica_id=prefill_replica)
        self.decode = ServingEngine(model, params, config,
                                    telemetry=telemetry, logger=logger,
                                    journal=j, replica_id=decode_replica)
        self.granule_of = granule_of
        self.decode_granule = decode_granule
        self.telemetry = telemetry
        self.migrations = 0
        self.migrated_bytes = 0
        self.bytes_by_link: Dict[str, int] = {}

    # -- live plane / SLO wiring --------------------------------------------

    def attach_slo(self, tracker) -> None:
        """One budget across the pair: a request migrated to the decode
        engine terminates THERE, so both engines observe into the same
        tracker (the prefill side still terminates door sheds)."""
        self.prefill.attach_slo(tracker)
        self.decode.attach_slo(tracker)

    def attach_live(self, aggregator) -> None:
        self.prefill.attach_live(aggregator)
        self.decode.attach_live(aggregator)

    # -- scheduling ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens, *, deadline_s=None,
               seed=None, tenant=None):
        return self.prefill.submit(prompt, max_new_tokens,
                                   deadline_s=deadline_s, seed=seed,
                                   tenant=tenant)

    def tick(self) -> int:
        produced = self.prefill.tick(decode=False)
        self._migrate()
        produced += self.decode.tick()
        return produced

    def drain(self, max_ticks: Optional[int] = None) -> int:
        total = 0
        ticks = 0
        while self.queue_depth or self.n_active:
            total += self.tick()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(
                    f"disagg drain exceeded {max_ticks} ticks with "
                    f"{self.queue_depth} queued"
                )
        return total

    def _migrate(self) -> None:
        """Move every parked prefilled request the decode engine can
        seat right now, oldest admission first; stop at the first that
        does not fit (head-of-line, like FIFO admission — skipping
        ahead would starve long requests exactly when the pool is
        tight)."""
        occupied = sorted(
            ((i, s) for i, s in enumerate(self.prefill._slots)
             if s is not None),
            key=lambda js: js[1].admitted_at,
        )
        for i, s in occupied:
            if not self.decode.can_import(len(s.table)):
                break
            handoff = self.prefill.export_request(i)
            nbytes = payload_bytes(handoff.payload)
            link = migration_link(
                handoff.payload.k.devices(),
                self.decode.pool.view.k.devices(),
                granule_of=self.granule_of,
                dst_granule=self.decode_granule,
            )
            seated = self.decode.import_request(handoff)
            assert seated, "can_import said yes but import_request no"
            req = handoff.req
            req.kv_migration_bytes += nbytes
            req.kv_migration_link = link
            self.migrations += 1
            self.migrated_bytes += nbytes
            self.bytes_by_link[link] = (
                self.bytes_by_link.get(link, 0) + nbytes)

    # -- single-engine driver surface ---------------------------------------

    @property
    def queue_depth(self) -> int:
        return self.prefill.queue_depth + self.decode.queue_depth

    @property
    def n_active(self) -> int:
        return self.prefill.n_active + self.decode.n_active

    @property
    def restarts(self) -> int:
        return self.prefill.restarts + self.decode.restarts

    @property
    def _evictions(self) -> int:
        return self.prefill._evictions + self.decode._evictions

    @property
    def config(self) -> SimpleNamespace:
        return SimpleNamespace(
            max_active=(self.prefill.config.max_active
                        + self.decode.config.max_active))

    @property
    def pool(self) -> SimpleNamespace:
        """Aggregate accounting for the driver's pool-utilization
        series (both pools' blocks count — a request holds blocks in
        exactly one of them at a time)."""
        p, d = self.prefill.pool, self.decode.pool
        merged = d.kv_bytes()
        for k, v in p.kv_bytes().items():
            if isinstance(v, int):
                merged[k] = merged[k] + v
        return SimpleNamespace(
            num_usable=p.num_usable + d.num_usable,
            blocks_in_use=p.blocks_in_use + d.blocks_in_use,
            blocks_free=p.blocks_free + d.blocks_free,
            kv_bytes=lambda m=merged: m,
        )

    def migration_summary(self) -> dict:
        return {
            "migrations": self.migrations,
            "migrated_bytes": self.migrated_bytes,
            "bytes_by_link": dict(self.bytes_by_link),
        }

    def describe(self) -> str:
        return (f"disagg(prefill={self.prefill.describe()}, "
                f"decode={self.decode.describe()})")
