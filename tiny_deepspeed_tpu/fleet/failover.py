# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Journal-replay failover: requests survive ENGINE loss, not just
process restart.

The PR-8 request journal already makes one engine's requests durable
across its own death (`ServingEngine.recover()` in a fresh process).
The fleet generalizes the reader: when replica A dies mid-trace — the
chaos `engine_kill` fault, or any real exception escalating out of its
tick — replica B replays A's journal and finishes A's requests.  Three
properties make the handoff exact and invisible to callers:

  * ids preserved — the journal carries them, and `recover()` bumps the
    shared id counter past everything the dead journal issued;
  * handles adopted — `recover(adopt=)` resets the callers' EXISTING
    Request objects to the committed prefix instead of minting new
    ones, so a `submit()`-returned handle keeps accumulating tokens
    through the failover;
  * token-identical — the (seed, position) sampling keys make the
    continuation a pure function of (params, prompt, seed); the tokens
    lost with the dead engine's uncommitted buffer re-decode to the
    same values (the headline fleet acceptance, pinned in
    tests/test_fleet.py at temperature 0 by argmax equality);
  * trace-correlated — the `trace_id` stamped at submit rides both the
    adopted handle and the journal's submit line, and the dead
    engine's `abandon()` / the sibling's `recover()` stamp
    replica-annotated `engine_lost` / `recovered` lifecycle events, so
    the request's spans before and after the failover land on the
    right per-replica tracks in `serving_chrome_trace` under ONE
    trace_id.

The sibling also RE-JOURNALS every adopted request into its own WAL
(recover()'s cross-journal path), so a second failure replays from the
sibling's journal alone — failover chains.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..serving.engine import Request, ServingEngine
from ..serving.journal import ServingKilled


class EngineKilled(ServingKilled):
    """A whole serving replica died — the chaos stand-in for an engine
    host going away (resilience/chaos.py `engine_kill`).  Like its base
    ServingKilled, the engine must NOT catch this and warm-restart: a
    dead replica has no engine to restart.  The FLEET router catches it
    one level up and replays the journal onto a sibling."""


def fail_over(dead: ServingEngine, sibling: ServingEngine, *,
              adopt: Optional[Dict[int, Request]] = None
              ) -> List[Request]:
    """Move a dead replica's in-flight requests onto `sibling`.

    `dead` is abandoned first (active windows closed into the
    restart-overhead component, queue cleared, journal file closed
    WITHOUT committing its buffer — on disk the WAL looks exactly as a
    SIGKILL would leave it), then the sibling replays it through the
    geometry-validated `recover()` path.  Returns the re-queued
    handles, adopted from `adopt` where ids match.  Raises ValueError
    when the dead replica has no journal — without a WAL there is
    nothing durable to replay, which is why the router requires
    journals on fleet replicas."""
    journal = dead.journal
    if journal is None:
        raise ValueError(
            "dead replica has no journal — its in-flight requests left "
            "no durable trace to replay onto a sibling; construct fleet "
            "replicas with journal="
        )
    path = journal.path
    dead.abandon()
    return sibling.recover(journal=path, adopt=adopt)
