# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""FleetRouter: the front door over N serving-engine replicas.

Dispatch is SLO-aware and least-loaded, scored per replica from numbers
the engines already measure:

  * load — queue depth plus fractional slot occupancy, PRICED by the
    replica's measured median decode wall per committed token
    (`_gap_p50`, the PR-8 shed price): a replica that serves tokens
    slowly counts its backlog as proportionally heavier;
  * pool headroom — allocated / usable paged-KV blocks;
  * health — the decode-health guard's quarantine and warm-restart
    counts: a replica that keeps poisoning slots or restarting is
    de-prioritized before it is dead.

Deadlines are honored AT DISPATCH: a request whose `deadline_s` no live
replica prices as meetable sheds at the door (terminal status "shed",
finish "shed:fleet_unmeetable") instead of burning a replica's queue
just to be shed there ticks later — the same measured gap price the
engines use, applied one level earlier.

Failover: any exception out of a replica's tick — the chaos
`engine_kill`, a `ServingKilled` from its journal, a restart-storm
RuntimeError — marks the replica dead and replays its journal onto the
best-scored live sibling (fleet/failover.py).  Callers' request handles
survive: `recover(adopt=)` resets the existing objects to the committed
prefix, so `submit()`-returned requests keep working through engine
loss.

The router exposes the single-engine driver surface (submit / tick /
drain / queue_depth / n_active / pool / config.max_active), so
`serving.driver.run_trace` drives a fleet exactly like one engine.
"""

from __future__ import annotations

import dataclasses
import threading
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace
from typing import Dict, List, Optional, Sequence, Tuple

from ..serving.engine import Request, ServingEngine
from .failover import fail_over

# health weight in the dispatch score: one quarantine/restart counts
# like half a queued request — enough to steer traffic away from a
# flapping replica without starving it outright
_HEALTH_WEIGHT = 0.5

# tenant-affinity bonus: the replica that last served a tenant scores
# this much lighter for that tenant's next request — its prefix cache
# already holds the tenant's system prompts (warm hits) and its
# scheduler already tracks the tenant's budget, but the bonus stays
# well under one queued request so real load imbalance still wins
_TENANT_AFFINITY = 0.25

# SLO-burn advisory weight: with an SLOTracker attached, a replica's
# recent bad-request fraction (telemetry/slo.py advise()) adds up to
# this much to its score — ADVISORY by design: it nudges dispatch away
# from a replica that is burning the budget, never vetoes it, and with
# no tracker attached scoring is byte-identical to pre-v15
_SLO_WEIGHT = 0.5


class _LockedLogger:
    """Serializes a shared MetricsLogger across concurrently ticking
    replicas: each record line must hit the file whole.  Everything
    else delegates."""

    def __init__(self, logger, lock: threading.Lock):
        self._logger = logger
        self._lock = lock

    def log(self, *a, **kw):
        with self._lock:
            return self._logger.log(*a, **kw)

    def log_meta(self, *a, **kw):
        with self._lock:
            return self._logger.log_meta(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._logger, name)


@dataclasses.dataclass
class Replica:
    """One engine behind the router.  `engine` is what the router
    ticks (possibly a ChaosServingEngine wrapper); `raw` is the
    underlying ServingEngine whose state the scores read."""

    id: int
    engine: object
    raw: ServingEngine
    alive: bool = True
    dispatched: int = 0


class _FleetPool:
    """Aggregate pool accounting over the LIVE replicas — the facade
    `run_trace`'s pool-utilization series reads."""

    def __init__(self, router: "FleetRouter"):
        self._router = router

    def _live(self):
        return [r.raw.pool for r in self._router.replicas if r.alive]

    @property
    def num_usable(self) -> int:
        return sum(p.num_usable for p in self._live()) or 1

    @property
    def blocks_in_use(self) -> int:
        return sum(p.blocks_in_use for p in self._live())

    @property
    def blocks_free(self) -> int:
        return sum(p.blocks_free for p in self._live())

    def kv_bytes(self) -> dict:
        """Summed resting footprint across live replicas (dtype from
        the first — replicas are homogeneous by construction)."""
        per = [p.kv_bytes() for p in self._live()]
        out = dict(per[0]) if per else {}
        for k in ("kv_block_bytes", "scale_bytes", "total_bytes"):
            out[k] = sum(d[k] for d in per)
        return out


class FleetRouter:
    """N serving replicas behind one SLO-aware front door.

    `engines` are pre-built (and pre-warmed, if the caller measures)
    ServingEngine instances or ChaosServingEngine wrappers; each gets
    its `replica_id` stamped from its position unless already set.
    Failover needs per-replica journals — replicas without one still
    serve, but their in-flight requests cannot replay if they die
    (fail_over raises, naming the gap).

    `telemetry` / `logger` are the ROUTER's: fleet_dispatch /
    fleet_failover / fleet_replicas_live gauges and the failover fault
    records.  Per-request and per-tick records come from the engines'
    own telemetry/logger (share one across the fleet and the records
    interleave, distinguished by their `replica_id` field)."""

    def __init__(self, engines: Sequence[object], *, telemetry=None,
                 logger=None, parallel: bool = False):
        if not engines:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: List[Replica] = []
        for i, e in enumerate(engines):
            raw = getattr(e, "engine", e)  # unwrap a chaos proxy
            if raw.replica_id is None:
                raw.replica_id = i
            self.replicas.append(Replica(id=i, engine=e, raw=raw))
        self.telemetry = telemetry
        self.logger = logger
        # parallel=True ticks the replicas on a thread pool: they are
        # independent engines (own pool/programs/journal), XLA releases
        # the GIL while a program runs, and a real fleet's replicas
        # never wait on each other — on a multi-core host this is where
        # replica-count throughput scaling actually comes from.  The
        # default stays sequential: deterministic tick interleaving for
        # tests and single-core boxes.  Shared-sink rules under
        # concurrency: the MetricsLogger is lock-wrapped below (whole
        # lines), telemetry Counters lock internally, Histogram.observe
        # is a GIL-atomic append — and GAUGES carry a replica label
        # (serve_queue_depth{replica=0}), so N engines writing one
        # registry each own their keys instead of last-writer-wins.
        self.parallel = bool(parallel)
        self._pool_exec: Optional[ThreadPoolExecutor] = None
        if self.parallel:
            self._pool_exec = ThreadPoolExecutor(
                max_workers=len(self.replicas),
                thread_name_prefix="fleet-tick")
            # a shared metrics sink must serialize whole lines once
            # replicas tick concurrently
            lock = threading.Lock()
            seen: Dict[int, _LockedLogger] = {}
            for r in self.replicas:
                lg = r.raw.logger
                if lg is not None:
                    r.raw.logger = seen.setdefault(
                        id(lg), _LockedLogger(lg, lock))
        self._registry: Dict[int, Tuple[Request, Replica]] = {}
        self._dispatched = 0
        self._failovers = 0
        self._door_sheds = 0
        self._ticks = 0
        # tenant -> replica id of the last dispatch (the affinity the
        # score rewards: that replica's prefix cache is warm for this
        # tenant's shared prompts)
        self._tenant_last: Dict[str, int] = {}
        # advisory SLO-burn state (attach_slo): consulted in _score
        self._slo = None
        self._update_gauges()

    # -- live plane / SLO wiring --------------------------------------------

    def attach_slo(self, tracker) -> None:
        """Fan an SLO tracker out to every replica (terminal requests
        observe into ONE budget) and keep it for the advisory dispatch
        hook in `_score`."""
        self._slo = tracker
        for r in self.replicas:
            r.raw.attach_slo(tracker)

    def attach_live(self, aggregator) -> None:
        """Fan a live-plane aggregator out to every replica: each
        engine pushes its per-tick registry snapshot (gauges carry the
        replica label), so one /metrics surface serves the fleet."""
        for r in self.replicas:
            r.raw.attach_live(aggregator)

    # -- dispatch -----------------------------------------------------------

    def _live(self) -> List[Replica]:
        return [r for r in self.replicas if r.alive]

    def _score(self, r: Replica,
               tenant: Optional[str] = None) -> Tuple[float, float, int]:
        """Dispatch score, lower = better.  Primary: backlog priced by
        the measured per-token decode wall, plus the health penalty,
        minus the tenant-affinity bonus when this replica last served
        `tenant` (its prefix cache is warm for that tenant's prompts);
        secondary: pool pressure; final tie-break: replica id (a cold
        even fleet fills deterministically, lowest id first)."""
        eng = r.raw
        gap = eng._gap_p50() or 0.0
        load = (eng.queue_depth
                + eng.n_active / max(1, eng.config.max_active))
        health = eng._quarantined + eng._restarts
        pool = eng.pool.blocks_in_use / eng.pool.num_usable
        primary = load * (1.0 + gap) + _HEALTH_WEIGHT * health
        if tenant is not None and self._tenant_last.get(tenant) == r.id:
            primary -= _TENANT_AFFINITY
        if self._slo is not None:
            # advisory burn consultation: a replica whose recent
            # terminals are burning the error budget scores heavier —
            # bounded (advise() is a fraction), never a veto
            primary += _SLO_WEIGHT * self._slo.advise(r.id)
        return (primary, pool, r.id)

    def _meets(self, r: Replica, max_new_tokens: int,
               deadline_s: Optional[float]) -> bool:
        """Can this replica plausibly serve `max_new_tokens` inside the
        deadline?  Priced from ITS measured median decode wall per
        committed token, exactly like the engine's own queue shedding
        (+1 for the prefill it must pay); a cold replica (no price yet)
        is optimistic — compile noise must not shed real traffic."""
        if deadline_s is None:
            return True
        gap = r.raw._gap_p50()
        return gap is None or (max_new_tokens + 1) * gap <= deadline_s

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               deadline_s: Optional[float] = None,
               seed: Optional[int] = None,
               tenant: Optional[str] = None) -> Request:
        """Dispatch one request to the best live replica — or shed it
        AT THE DOOR when no live replica prices its deadline as
        meetable, or (tenant-aware) when every live replica's door
        watermark for this tenant is already full (the handle returns
        already terminal, exactly like an engine watermark shed).
        Dispatch scoring is tenant-aware too: the replica that last
        served this tenant gets the prefix-affinity bonus."""
        live = self._live()
        if not live:
            raise RuntimeError("no live replicas to dispatch to")

        def door_shed(reason: str) -> Request:
            # shed without touching any queue; the least-loaded
            # replica's terminal path writes the record (its logger /
            # telemetry own the request stream)
            req = Request(list(prompt), int(max_new_tokens),
                          deadline_s=deadline_s, seed=seed,
                          tenant=tenant)
            best = min(live, key=self._score)
            best.raw._count("serve_submitted")
            best.raw._shed_req(req, reason)
            self._door_sheds += 1
            self._update_gauges()
            return req

        if tenant is not None and all(
                r.raw.tenant_queue_full(tenant) for r in live):
            # the abusive tenant's overflow terminates at the FLEET
            # door — no replica's shared queue absorbs it
            return door_shed("fleet_tenant_watermark")
        feasible = [r for r in live
                    if self._meets(r, max_new_tokens, deadline_s)]
        if not feasible:
            return door_shed("fleet_unmeetable")
        r = min(feasible, key=lambda rep: self._score(rep, tenant))
        req = r.engine.submit(prompt, max_new_tokens,
                              deadline_s=deadline_s, seed=seed,
                              tenant=tenant)
        r.dispatched += 1
        self._dispatched += 1
        if req.status is None:  # not shed at the replica's own door
            self._registry[req.id] = (req, r)
            if tenant is not None:
                self._tenant_last[tenant] = r.id
        self._update_gauges()
        return req

    # -- scheduling + failover ----------------------------------------------

    def tick(self) -> int:
        """One fleet step: tick every live replica that has work —
        sequentially by default, concurrently on the thread pool with
        `parallel=True` (replicas share nothing but the metrics sink,
        which is lock-wrapped).  A replica whose tick raises is failed
        over on the spot (its requests re-queue on a sibling THIS
        tick, always from the router's thread) and the rest of the
        fleet keeps serving."""
        busy = [r for r in self._live()
                if r.raw.queue_depth or r.raw.n_active]
        produced = 0
        if self.parallel and len(busy) > 1:
            futures = [(r, self._pool_exec.submit(r.engine.tick))
                       for r in busy]
            # join EVERY future before any failover: recover() mutates
            # the sibling's queue, which must not race its own tick
            failures = []
            for r, f in futures:
                try:
                    produced += f.result()
                except Exception as e:  # noqa: BLE001 - replica death
                    failures.append((r, e))
            # mark EVERY failure dead before the first replay: with two
            # deaths in one tick, the first failover must not pick the
            # other doomed replica as its sibling
            for r, _ in failures:
                r.alive = False
            for r, e in failures:
                self._fail_over(r, e)
        else:
            for r in busy:
                try:
                    produced += r.engine.tick()
                except Exception as e:  # noqa: BLE001 - replica death
                    self._fail_over(r, e)
        self._ticks += 1
        self._update_gauges()
        return produced

    def drain(self, max_ticks: Optional[int] = None) -> int:
        total = 0
        ticks = 0
        while self.queue_depth or self.n_active:
            total += self.tick()
            ticks += 1
            if max_ticks is not None and ticks > max_ticks:
                raise RuntimeError(
                    f"fleet drain exceeded {max_ticks} ticks with "
                    f"{self.queue_depth} queued"
                )
        return total

    def _fail_over(self, r: Replica, exc: BaseException) -> None:
        """Replica `r` died (`exc`): replay its journal onto the best
        live sibling, adopting the callers' handles.  With no live
        sibling left the exception propagates — there is nowhere for
        the requests to go, and pretending otherwise would hide total
        fleet loss."""
        r.alive = False
        live = self._live()
        if not live:
            raise exc
        sibling = min(live, key=self._score)
        adopted = {rid: req for rid, (req, rep) in self._registry.items()
                   if rep is r}
        recovered = fail_over(r.raw, sibling.raw, adopt=adopted)
        for req in recovered:
            self._registry[req.id] = (req, sibling)
        self._failovers += 1
        if self.logger is not None:
            self.logger.log_meta(
                kind="fault", fault="fleet_failover",
                at_step=self._ticks, replica_id=r.id,
                action=(f"replica {r.id} died "
                        f"({type(exc).__name__}: {exc}); journal "
                        f"replayed onto replica {sibling.id}, "
                        f"{len(recovered)} request(s) re-queued"),
            )

    # -- single-engine driver surface ---------------------------------------

    @property
    def queue_depth(self) -> int:
        return sum(r.raw.queue_depth for r in self._live())

    @property
    def n_active(self) -> int:
        return sum(r.raw.n_active for r in self._live())

    @property
    def restarts(self) -> int:
        return sum(r.raw.restarts for r in self.replicas)

    @property
    def failovers(self) -> int:
        return self._failovers

    @property
    def _evictions(self) -> int:
        return sum(r.raw._evictions for r in self.replicas)

    @property
    def config(self) -> SimpleNamespace:
        """The aggregate the driver's occupancy series divides by:
        total live decode slots."""
        return SimpleNamespace(max_active=sum(
            r.raw.config.max_active for r in self._live()) or 1)

    @property
    def pool(self) -> _FleetPool:
        return _FleetPool(self)

    def describe(self) -> str:
        live = self._live()
        return (f"fleet({len(live)}/{len(self.replicas)} replicas live: "
                + "; ".join(r.raw.describe() for r in live) + ")")

    def dispatch_counts(self) -> Dict[int, int]:
        """{replica id: requests dispatched to it} — what the
        least-loaded test and the bench summary read."""
        return {r.id: r.dispatched for r in self.replicas}

    def prefix_stats(self) -> Optional[Dict]:
        """Fleet-wide shared-prefix outcomes: counters summed over
        live replicas, hit rate re-derived from the summed tokens
        (None when no replica runs the cache)."""
        per = [s for s in (r.raw.prefix_stats() for r in self._live())
               if s is not None]
        if not per:
            return None
        out = {k: sum(s[k] for s in per)
               for k in ("hits", "misses", "blocks_aliased",
                         "prefill_tokens_avoided", "prompt_tokens",
                         "cached_blocks", "tree_evictions",
                         "pool_saved_bytes")}
        out["hit_rate"] = round(
            out["prefill_tokens_avoided"]
            / max(1, out["prompt_tokens"]), 4)
        return out

    def tenant_stats(self) -> Optional[Dict]:
        """Per-tenant scheduler accounting summed across live replicas
        (weights come from the first replica reporting the tenant —
        replicas are homogeneous by construction)."""
        agg: Dict[str, Dict] = {}
        for r in self._live():
            st = r.raw.tenant_stats()
            if not st:
                continue
            for name, d in st.items():
                if name not in agg:
                    agg[name] = dict(d)
                    continue
                cur = agg[name]
                for k in ("queued", "admitted_tokens", "sheds",
                          "budget_granted"):
                    if k in d:
                        cur[k] = cur.get(k, 0) + d[k]
        for d in agg.values():
            if "budget_granted" in d:
                d["budget_utilization"] = round(
                    d["admitted_tokens"]
                    / max(d["budget_granted"], 1e-9), 4)
        return agg or None

    def _update_gauges(self) -> None:
        if self.telemetry is None:
            return
        t = self.telemetry
        t.gauge("fleet_dispatch", float(self._dispatched))
        t.gauge("fleet_failover", float(self._failovers))
        t.gauge("fleet_replicas_live", float(len(self._live())))
