# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Fleet serving tier: N engine replicas behind an SLO-aware router.

The serving package (`tiny_deepspeed_tpu/serving/`) is one engine — one
pool, one journal, one SLO policy.  Real deployments run fleets: this
package composes N `ServingEngine` replicas into one front door, the way
the TPU-vs-GPU serving analysis lays out (PAPERS.md arXiv:2605.25645).

  * `router`   — FleetRouter: SLO-aware least-loaded dispatch over the
                 replicas (queue depth, pool headroom, the measured
                 median decode-tick price, per-replica health), door
                 shedding for deadlines no replica can meet, and the
                 failover trigger when a replica dies mid-tick.
  * `failover` — journal-replay failover: a dead replica's write-ahead
                 log replays onto a sibling via the existing
                 `ServingEngine.recover()` path — ids preserved, the
                 callers' request handles adopted, greedy outputs
                 token-identical to an uninterrupted run.
  * `disagg`   — DisaggEngine: prefill and decode split onto separate
                 engines with a priced paged-KV block migration between
                 their pools (`migrate` / the engine export/import
                 hooks), the ICI-vs-DCN cost of each handoff measured
                 by the `wire_link_split` granule logic.

Everything here is host-side orchestration over the SAME compiled
serving programs — no new device code, and a 1-replica fleet runs the
exact single-engine tick.
"""

from .disagg import DisaggEngine, migration_link
from .failover import EngineKilled, fail_over
from .router import FleetRouter

__all__ = [
    "FleetRouter", "DisaggEngine", "EngineKilled", "fail_over",
    "migration_link",
]
