# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Declarative pipeline tick tables: interleaved (virtual-stage) and
zero-bubble (B/W split) schedules as STATIC programs.

The GPipe and 1F1B executors in `pipeline.py` hard-code their schedules
as closed-form index arithmetic inside the tick scan.  That stops
scaling the moment the schedule has structure the formula cannot carry:
virtual stages (each physical stage owns V non-adjacent layer chunks,
Megatron-LM's bubble reducer) and backward-split scheduling (dgrad B on
the critical path, wgrad W as bubble filler — the zero-bubble family,
arXiv:2412.14374).  This module builds those schedules OFFLINE as a
(tick, stage) -> {F/B/W, chunk, microbatch} table plus a static stash
slot map, so the executor (`pipeline.spmd_pipeline_table`) is a dumb
table interpreter and the schedule itself is a pure, testable object —
`build_schedule`'s PipeSlot client validates it once per engine build.

Everything here is numpy/python: no jax import, no device, no compile.
`tests/test_pipeline_schedule.py` pins warmup/steady/cooldown shapes,
the analytic 1F1B bubble (S-1)/(M+S-1), and the measured bubble ordering
1f1b > interleaved > zbub without touching a mesh.

Scheduling model (unit ticks, the occupancy ledger `bubble_frac` reads):

  * one op per (tick, stage); F, B and W each cost one tick
  * chunk c of C = S*V lives on stage c % S (local index v = c // S);
    forward hops ride the +1 ring, cotangents the -1 ring, one tick
  * F(c,j) needs F(c-1,j) arrived; B(C-1,j) needs F(C-1,j) stashed
    (the head runs inside the final chunk's backward); B(c,j) needs
    B(c+1,j)'s cotangent; W(c,j) needs B(c,j) (same stage, no hop)
  * the table is built by event-driven greedy list scheduling with
    priorities B > F > W: B is the critical path, F drains toward the
    loss (highest chunk first), W is pure filler that soaks warmup /
    cooldown bubbles.  For V=1 without the split this reproduces the
    textbook 1F1B table exactly — T = 2(M+S-1) ticks, bubble
    (S-1)/(M+S-1) — which is the regression anchor for the whole
    builder.

`bubble_frac` is the idle fraction of the (T x S) tick grid.  Ticks are
schedule slots, not equal wall time — the gauge measures the *schedule*,
the A/B bench arm measures the wall.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

# op codes in the (tick, stage) table — also the lax.switch branch index
# order in pipeline.spmd_pipeline_table (idle first so padding is a no-op)
OP_IDLE = 0
OP_F = 1
OP_B = 2
OP_W = 3

OP_NAMES = {OP_IDLE: "-", OP_F: "F", OP_B: "B", OP_W: "W"}


@dataclasses.dataclass(frozen=True)
class PipeProgram:
    """A compiled pipeline schedule: the static tick table the executor
    interprets plus its occupancy ledger.

    All (n_ticks, stages) int32 arrays; -1 means "none" in slot columns.

      op      OP_IDLE / OP_F / OP_B / OP_W
      vchunk  local chunk index v on this stage (global chunk v*S+stage)
      mb      microbatch index j
      aslot   activation stash slot the op reads (F input / B,W recompute)
      cslot   cotangent stash slot B/W reads (-1: final chunk, head-seeded)
      recv_f  slot an arriving forward activation parks into this tick
      recv_b  slot an arriving backward cotangent parks into this tick

    `ka` / `kc` size the two stash rings; `busy` is per-stage scheduled
    ticks; `bubble_frac` = 1 - sum(busy) / (n_ticks * stages).
    """

    stages: int
    virtual: int
    microbatches: int
    split_w: bool
    n_ticks: int
    ka: int
    kc: int
    op: np.ndarray
    vchunk: np.ndarray
    mb: np.ndarray
    aslot: np.ndarray
    cslot: np.ndarray
    recv_f: np.ndarray
    recv_b: np.ndarray
    busy: np.ndarray
    bubble_frac: float

    @property
    def chunks(self) -> int:
        return self.stages * self.virtual

    def describe(self) -> str:
        kind = "zbub" if self.split_w else (
            "interleaved" if self.virtual > 1 else "1f1b")
        return (f"pipe={kind}:{self.virtual}[s={self.stages} "
                f"m={self.microbatches} t={self.n_ticks} "
                f"bubble={self.bubble_frac:.3f}]")

    def render(self) -> str:
        """ASCII tick table (stages x ticks), for docs and debugging:
        `F0.2` = forward, local chunk 0, microbatch 2."""
        rows = []
        for s in range(self.stages):
            cells = []
            for t in range(self.n_ticks):
                o = int(self.op[t, s])
                if o == OP_IDLE:
                    cells.append("....")
                else:
                    cells.append(f"{OP_NAMES[o]}{int(self.vchunk[t, s])}."
                                 f"{int(self.mb[t, s])}")
            rows.append(f"s{s}: " + " ".join(cells))
        return "\n".join(rows)


def _validate_geometry(s: int, v: int, m: int,
                       n_layer: Optional[int]) -> None:
    if s < 2:
        raise ValueError(
            f"pipeline table needs >= 2 stages, got {s} (a 1-stage "
            f"'pipeline' is a plain scan — use the non-pipelined path)")
    if v < 1:
        raise ValueError(f"virtual stages must be >= 1, got {v}")
    if m < 1:
        raise ValueError(f"microbatches must be >= 1, got {m}")
    if n_layer is not None and n_layer % (s * v):
        raise ValueError(
            f"n_layer={n_layer} not divisible by stages*virtual="
            f"{s}*{v}={s * v} (each of the {s * v} chunks must hold the "
            f"same number of layers)")


def build_pipe_program(
    s: int,
    v: int,
    m: int,
    *,
    split_w: bool = False,
    n_layer: Optional[int] = None,
) -> PipeProgram:
    """Build the (tick, stage) program for S physical stages, V virtual
    chunks per stage, M microbatches; `split_w` enables the zero-bubble
    B/W split.  Pure python — raises ValueError on bad geometry."""
    _validate_geometry(s, v, m, n_layer)
    c_total = s * v

    # completion tick of each op, keyed (kind, chunk, microbatch)
    t_f = np.full((c_total, m), -1, np.int64)
    t_b = np.full((c_total, m), -1, np.int64)
    t_w = np.full((c_total, m), -1, np.int64)

    # per-stage chunk lists: stage s owns global chunks s, s+S, ...
    stage_chunks = [list(range(st, c_total, s)) for st in range(s)]

    sched = []  # (tick, stage, opcode, chunk, mb)
    n_ops = c_total * m * (3 if split_w else 2)
    done = 0
    t = 0
    cap = 4 * c_total * m + 8 * (s + v) + 16
    while done < n_ops:
        t += 1
        if t > cap:  # pragma: no cover - guards builder bugs, not inputs
            raise RuntimeError(
                f"pipeline schedule did not converge in {cap} ticks "
                f"(s={s} v={v} m={m} split_w={split_w})")
        for st in range(s):
            best = None  # (priority tuple, opcode, chunk, mb)
            for c in stage_chunks[st]:
                for j in range(m):
                    if t_f[c, j] < 0:
                        # in-order per chunk; upstream chunk arrived
                        if j > 0 and t_f[c, j - 1] < 0:
                            break
                        if c > 0 and not (0 <= t_f[c - 1, j] < t):
                            break
                        # B beats F beats W; F drains toward the loss
                        # (highest chunk first), oldest microbatch first
                        key = (1, -c, j)
                        if best is None or key < best[0]:
                            best = (key, OP_F, c, j)
                        break  # only the first unscheduled j is a candidate
                for j in range(m):
                    if t_b[c, j] < 0:
                        if j > 0 and t_b[c, j - 1] < 0:
                            break
                        if t_f[c, j] < 0:
                            break
                        if c < c_total - 1 and not (0 <= t_b[c + 1, j] < t):
                            break
                        key = (0, j, -c)
                        if best is None or key < best[0]:
                            best = (key, OP_B, c, j)
                        break
                if split_w:
                    for j in range(m):
                        if t_w[c, j] < 0:
                            if not (0 <= t_b[c, j] < t):
                                break
                            key = (2, j, -c)
                            if best is None or key < best[0]:
                                best = (key, OP_W, c, j)
                            break
            if best is None:
                continue
            _, opc, c, j = best
            {OP_F: t_f, OP_B: t_b, OP_W: t_w}[opc][c, j] = t
            sched.append((t, st, opc, c, j))
            done += 1

    n_ticks = t
    op = np.zeros((n_ticks, s), np.int32)
    vchunk = np.zeros((n_ticks, s), np.int32)
    mbt = np.zeros((n_ticks, s), np.int32)
    aslot = np.full((n_ticks, s), -1, np.int32)
    cslot = np.full((n_ticks, s), -1, np.int32)
    recv_f = np.full((n_ticks, s), -1, np.int32)
    recv_b = np.full((n_ticks, s), -1, np.int32)
    busy = np.zeros((s,), np.int64)
    for tt, st, opc, c, j in sched:
        op[tt - 1, st] = opc
        vchunk[tt - 1, st] = c // s
        mbt[tt - 1, st] = j
        busy[st] += 1

    # -- static stash allocation: interval-graph coloring per stage ------
    # activation (c,j): parked at the forward arrival (the F tick itself
    # for chunk 0's injection), read by F and again by the recompute in
    # B (and W when split); the slot frees the tick AFTER its last read
    # (an arrival parks before the op runs, so same-tick reuse collides)
    def color(intervals):
        """intervals: list of (start, end, key) per stage, inclusive
        ticks.  Returns ({key: slot}, n_slots)."""
        slot_of = {}
        free_at = []  # slot -> first tick it is free again
        for start, end, key in sorted(intervals):
            for sl, fa in enumerate(free_at):
                if fa <= start:
                    free_at[sl] = end + 1
                    slot_of[key] = sl
                    break
            else:
                slot_of[key] = len(free_at)
                free_at.append(end + 1)
        return slot_of, len(free_at)

    ka = kc = 0
    for st in range(s):
        a_iv, c_iv = [], []
        for c in stage_chunks[st]:
            for j in range(m):
                a_start = t_f[c, j] if c == 0 else t_f[c - 1, j] + 1
                a_end = t_w[c, j] if split_w else t_b[c, j]
                a_iv.append((int(a_start), int(a_end), (c, j)))
                if c < c_total - 1:
                    c_start = t_b[c + 1, j] + 1
                    c_end = t_w[c, j] if split_w else t_b[c, j]
                    c_iv.append((int(c_start), int(c_end), (c, j)))
        a_slot, n_a = color(a_iv)
        c_slot, n_c = color(c_iv)
        ka, kc = max(ka, n_a), max(kc, n_c)
        for c in stage_chunks[st]:
            for j in range(m):
                sl = a_slot[(c, j)]
                for tb in (t_f[c, j], t_b[c, j]) + (
                        (t_w[c, j],) if split_w else ()):
                    aslot[tb - 1, st] = sl
                if c > 0:
                    recv_f[t_f[c - 1, j], st] = sl  # arrival tick - 1 idx
                if c < c_total - 1:
                    cl = c_slot[(c, j)]
                    for tb in (t_b[c, j],) + (
                            (t_w[c, j],) if split_w else ()):
                        cslot[tb - 1, st] = cl
                    recv_b[t_b[c + 1, j], st] = cl

    bubble = 1.0 - float(busy.sum()) / float(n_ticks * s)
    return PipeProgram(
        stages=s, virtual=v, microbatches=m, split_w=split_w,
        n_ticks=n_ticks, ka=max(ka, 1), kc=max(kc, 1),
        op=op, vchunk=vchunk, mb=mbt, aslot=aslot, cslot=cslot,
        recv_f=recv_f, recv_b=recv_b, busy=busy,
        bubble_frac=bubble,
    )


def analytic_1f1b_bubble(s: int, m: int) -> float:
    """The textbook 1F1B idle fraction (S-1)/(M+S-1) — what the builder
    must reproduce at V=1 without the B/W split."""
    return (s - 1) / (m + s - 1)


def chunk_permutation(n_layer: int, s: int,
                      v: int) -> Tuple[np.ndarray, np.ndarray]:
    """Layer permutation realizing virtual stages on a pipe-sharded
    stacked array.

    Canonical layer l belongs to global chunk g = l // (n_layer/(S*V));
    chunk g lives on stage g % S at local index g // S.  `perm` reorders
    the canonical layer axis so a plain P(pipe) shard of the permuted
    array hands stage s exactly its chunks, contiguously by local index:
    permuted position p = s*(L/S) + (g//S)*Lc + (l % Lc) holds canonical
    layer perm[p].  `inv` undoes it (dstacked = dperm[inv]).  Identity
    when V == 1 — callers skip the reshuffle entirely then."""
    _validate_geometry(s, v, 1, n_layer)
    lc = n_layer // (s * v)
    perm = np.empty(n_layer, np.int64)
    for st in range(s):
        for vv in range(v):
            g = vv * s + st
            for i in range(lc):
                perm[st * (n_layer // s) + vv * lc + i] = g * lc + i
    inv = np.empty_like(perm)
    inv[perm] = np.arange(n_layer)
    return perm, inv
