# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Device mesh construction + multi-host initialization.

Replaces the reference's process-group bring-up
(`dist.init_process_group('nccl', init_method='env://')`, reference
example/ddp/train.py:19, torchrun rendezvous) with the TPU equivalents:

  * `init_distributed()` — `jax.distributed.initialize()` when running
    multi-host (a no-op on one host).  The reference is single-node only
    (README.md:70 TODO "multi-node"); this framework is multi-host-safe from
    the start: the same mesh code spans ICI within a slice and DCN across
    slices.
  * `make_mesh(axis_names=..., shape=...)` — a `jax.sharding.Mesh` over all
    visible devices.  Axis convention:
        "data"  — batch / ZeRO sharding axis (always present)
        "model" — tensor-parallel axis (optional)
        "seq"   — sequence/context parallel axis (optional, ring attention)
    Collectives ride ICI because mesh axes are laid out over the physical
    device order jax exposes.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_distributed(**kwargs) -> None:
    """Multi-host bring-up.  Safe to call unconditionally, BEFORE any other
    JAX backend use (like the reference calls init_process_group first,
    ddp/train.py:19 — torchrun env:// rendezvous becomes
    jax.distributed.initialize auto-configuration on Cloud TPU).

    Single-process runs (no multi-host env, no kwargs, not on a pod) skip
    initialization — jax.distributed.initialize would otherwise block
    waiting for a coordinator.
    """
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if is_init():
            return
    else:
        # jax builds without the predicate (e.g. 0.4.37): the global state
        # object's client is the same signal
        state = getattr(jax._src.distributed, "global_state", None)
        if state is not None and getattr(state, "client", None) is not None:
            return
    multi_host_env = any(
        os.environ.get(v)
        for v in (
            "JAX_COORDINATOR_ADDRESS",     # explicit coordinator
            "COORDINATOR_ADDRESS",
            "TPU_WORKER_HOSTNAMES",        # Cloud TPU pod runtime
            "MEGASCALE_COORDINATOR_ADDRESS",
        )
    ) or kwargs
    single = os.environ.get("TPU_WORKER_HOSTNAMES", "localhost") in (
        "localhost", "127.0.0.1", ""
    ) and not (kwargs or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if multi_host_env and not single:
        jax.distributed.initialize(**kwargs)


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Tuple[str, ...] = ("data",),
    devices=None,
) -> Mesh:
    """Mesh over all devices; default one "data" axis spanning everything.

    On a multi-slice/multi-host topology (devices carrying distinct
    slice_index / process_index), the device grid is laid out hybrid: the
    slow DCN network carries the leading "data" axis (gradient reductions
    amortize over the whole step) while every other axis — "model", "seq",
    "expert", "pipe", whose collectives sit on the critical path — stays
    inside a slice on ICI.  The reference is single-node only (its
    README.md:70 TODO "multi-node"); here the same mesh code spans both.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    shape = tuple(int(s) for s in shape)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh shape {shape} != device count {len(devices)}"
        )
    grid = _device_grid(shape, axis_names, devices)
    return Mesh(grid, axis_names)


def _n_granules(devices) -> Tuple[int, str]:
    """(number of DCN granules, granule attr name) for these devices.

    Granules must be equal-sized for a hybrid layout (mesh_utils builds one
    ICI mesh per granule); uneven subsets report 1 so callers fall back to
    the flat reshape.  A UNIFORM slice_index means all devices share one ICI
    domain — report 1 granule immediately rather than falling through to
    process_index, which would wrongly treat ICI-connected hosts of a
    single-slice pod as DCN granules (ADVICE r1)."""
    from collections import Counter

    for attr in ("slice_index", "process_index"):
        if hasattr(devices[0], attr):
            counts = Counter(getattr(d, attr) for d in devices)
            if len(counts) > 1 and len(set(counts.values())) == 1:
                return len(counts), attr
            if attr == "slice_index" and len(counts) == 1:
                return 1, ""
    return 1, ""


def _device_grid(shape, axis_names, devices) -> np.ndarray:
    """Device ndarray for Mesh: hybrid ICI x DCN when the devices span
    multiple slices/processes and the data axis can absorb them; plain
    reshape (single-granule, or indivisible data axis) otherwise."""
    n_gran, attr = _n_granules(devices)
    data_ix = axis_names.index("data") if "data" in axis_names else 0
    if n_gran > 1 and shape[data_ix] % n_gran == 0:
        from jax.experimental import mesh_utils

        ici = list(shape)
        dcn = [1] * len(shape)
        ici[data_ix] = shape[data_ix] // n_gran
        dcn[data_ix] = n_gran
        try:
            return mesh_utils.create_hybrid_device_mesh(
                ici, dcn, devices,
                process_is_granule=(attr == "process_index"),
            )
        except Exception:
            # some topologies cannot realize the per-granule ICI shape;
            # a flat reshape still yields a working (if suboptimal) mesh
            # rather than failing mesh construction outright (ADVICE r1)
            pass
    return np.asarray(devices).reshape(shape)


import dataclasses
from typing import Optional as _Optional


@dataclasses.dataclass(frozen=True)
class ParallelContext:
    """How a model forward should lay activations on the mesh.

    The reference has no equivalent — its modes only vary backward-hook
    collectives.  Here the context carries the mesh and axis names so the
    model can (a) run Pallas kernels per-shard under shard_map (XLA cannot
    auto-partition custom calls) and (b) shard the sequence axis for
    ring-attention context parallelism.
    """

    mesh: Mesh
    data_axis: str = "data"
    seq_axis: _Optional[str] = None
    model_axis: _Optional[str] = None
    expert_axis: _Optional[str] = None
    pipe_axis: _Optional[str] = None
    pipe_microbatches: int = 0
    # sequence-parallel attention mechanism: "ring" (ppermute K/V rotation,
    # O(T/n) memory — parallel/ring_attention.py) or "ulysses" (all-to-all
    # head/sequence reshard, DeepSpeed-Ulysses — parallel/ulysses.py)
    seq_impl: str = "ring"
    # {stacked leaf name: in-scan PartitionSpec} — the tensor/expert
    # placements of each per-layer block weight AFTER the leading layer
    # axis is sliced off.  Consumed by the fp8 gather path (_bw): the
    # constraint pins the pre-dequant f8 tensor to its gathered layout so
    # GSPMD moves f8 bytes, not the dequantized f32 (without it the
    # partitioner fuses the dequant multiply shard-side and gathers full
    # precision).  None outside an engine.
    stacked_specs: _Optional[dict] = None
    # ZeRO-3 layer-ahead weight-gather prefetch depth (engine
    # gather_prefetch=).  Informational since the scheduler refactor:
    # the model no longer branches on it — the engine builds the gather
    # slot's executor (parallel/schedule.GatherPrefetchScan or the
    # composed machine) and passes it through model.apply(sched=);
    # kept on the context for introspection/compat.
    gather_prefetch: int = 0
    # hierarchical 2-hop gather: that many consecutive ranks per
    # resting-precision intra-group hop, compute dtype across groups
    # (mirrors grad_comm_groups; needs gather_prefetch >= 2, pure DP)
    gather_groups: _Optional[int] = None
    # {stacked leaf name: in-scan SHARDED PartitionSpec} — each per-layer
    # block weight's resting ZeRO layout after the leading layer axis is
    # sliced off; the prefetched scan's source layout for gathers and the
    # target layout for per-layer dW cotangents (in-loop reduce-scatter)
    stacked_shard_specs: _Optional[dict] = None

    @property
    def is_multi_device(self) -> bool:
        return self.mesh is not None and self.mesh.devices.size > 1

    @property
    def seq_parallel(self) -> bool:
        return self.seq_axis is not None and self.mesh.shape[self.seq_axis] > 1

    @property
    def tensor_parallel(self) -> bool:
        return (
            self.model_axis is not None
            and self.mesh.shape[self.model_axis] > 1
        )

    @property
    def expert_parallel(self) -> bool:
        return (
            self.expert_axis is not None
            and self.mesh.shape[self.expert_axis] > 1
        )

    @property
    def pipe_parallel(self) -> bool:
        return (
            self.pipe_axis is not None and self.mesh.shape[self.pipe_axis] > 1
        )


def granule_map(devices) -> Optional[dict]:
    """{logical device id: DCN granule index} for a device sequence in
    MESH-FLAT order (pass `mesh.devices.flatten()`) — the id space a
    compiled program's replica_groups use, which is what lets
    `utils/hlo_comm.wire_link_split` classify each collective's wire as
    intra-slice (ICI) or cross-slice (DCN).  None when the devices form
    a single granule (one slice / one process — no DCN to cross)."""
    devices = list(devices)
    n_gran, attr = _n_granules(devices)
    if n_gran <= 1:
        return None
    gran_ids = sorted({getattr(d, attr) for d in devices})
    ix = {g: i for i, g in enumerate(gran_ids)}
    return {i: ix[getattr(d, attr)] for i, d in enumerate(devices)}


def granule_geometry(granule_of: Optional[dict], n: int) -> tuple:
    """(n_granules, ici) of a granule map over an n-rank data axis — the
    link hierarchy the DCN-aware "auto" comm sizing keys on
    (parallel/schedule.auto_comm_plan).  A None / empty map is the flat
    single-slice mesh: (1, n).  `ici` is the intra-granule rank count
    when the granules split `n` evenly, else `n` (an uneven map gets no
    2-hop sizing — the schedule-level validators own the loud refusal)."""
    if not granule_of:
        return 1, n
    n_gran = len(set(granule_of.values()))
    if n_gran <= 1 or n % n_gran:
        return max(n_gran, 1), n
    return n_gran, n // n_gran


def mesh_descriptor(mesh: Mesh) -> dict:
    """JSON-safe identity of a mesh's shape: axis names/sizes, device and
    host counts.  Persisted in checkpoint meta sidecars so an elastic
    resume can compare the checkpoint's topology with the current one
    (resilience/elastic.py) and name BOTH in its refusal message."""
    return {
        "axes": {str(k): int(v) for k, v in mesh.shape.items()},
        "n_devices": int(mesh.devices.size),
        "n_processes": int(jax.process_count()),
    }


def describe_mesh(desc: Optional[dict]) -> str:
    """Human-readable one-liner for a mesh_descriptor (or unknown)."""
    if not desc:
        return "<unknown mesh (no checkpoint meta)>"
    axes = "×".join(
        f"{k}={v}" for k, v in desc.get("axes", {}).items()
    ) or "?"
    return f"{axes} ({desc.get('n_devices', '?')} devices)"


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(axis))
