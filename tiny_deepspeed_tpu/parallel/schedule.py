# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""One composable in-scan collective scheduler.

Until this module the repo carried FOUR separate custom_vjp "tap"
mechanisms riding the block scan — the bucketed grad-release tap (PR 3),
the prefetched weight-gather scan (PR 4), the per-layer health probe
(PR 5), and the monolithic quantized grad schedule (PR 2) — and they
pairwise refused.  Here each engine mode declares its per-layer work as
composable SLOTS:

  GatherSlot — ZeRO-3 weight gathers: prefetch depth K, optional 2-hop
               groups, optional hpZ secondary partition (gathers stay
               intra-slice; ZeRO++ arXiv:2306.10209).
  GradSlot   — gradient releases: bucket count, collective codec
               (fp32/int8/fp8 + error-feedback residual slices), 2-hop
               groups.
  ProbeSlot  — per-layer health (the layer_health_tap).

`build_schedule` validates the composition ONCE (the single loud refusal
path, `ScheduleConflictError`, names the conflicting slot) and picks a
lowering:

  "probe"      — the probe row rides the plain GSPMD scan (legacy
                 program, HLO byte-identical).
  "bucket"     — the GradBucketTap nested scan (legacy, byte-identical).
  "quant_mono" — the monolithic quantized schedule (legacy,
                 byte-identical).
  "prefetch"   — the GatherPrefetchScan custom_vjp (legacy,
                 byte-identical).
  "composed"   — ANY multi-slot combination: ONE custom_vjp
                 (`composed_step`) emits the merged schedule into the
                 forward and remat-backward scan bodies inside a
                 shard_map manual region over the data axis — explicit
                 per-layer weight gathers (prefetched, optionally
                 intra-slice under hpZ), per-bucket grad collectives
                 released inside the backward scan, and the health
                 probe riding every layer.  This is the real DeepSpeed
                 hot path in one program: ZeRO-3 + gather prefetch +
                 bucketed quantized grads + per-layer health
                 simultaneously.

The model seam is ONE hook: `model.apply(..., sched=...)` receives an
executor with `.scan(block, stacked, x, unroll=)` — the grad_tap= /
health_probe= / pctx.gather_prefetch special cases are gone.

hpZ (secondary weight partitioning): with `hpz=True` the engine holds a
full compute-dtype (bf16/fp8) replica of the block weights WITHIN each
DCN granule (slice): one top-level inter-slice all-gather per step
rebuilds the secondary partition from the global fp32 ZeRO-3 shards, and
every in-scan forward/backward gather then runs over the intra-slice
group only — `dcn_wire_bytes` for in-scan gathers drops to ~zero
(measured by utils/hlo_comm.wire_link_split, the PR-14 ledger).  The
fp32 optimizer shards stay global ZeRO-3.  The secondary partition is
stashed as a backward residual — the deliberate HBM cost of hpZ (one
compute-dtype model replica per slice, PROFILE.md).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .comm import (
    DEFAULT_BLOCK, GRAD_COMM_MODES, _dequant_rows, _hier_groups,
    as_wire, bucket_layout, from_wire, padded_size, quantize_blockwise,
    quantized_grad_sync,
)


class ScheduleConflictError(ValueError):
    """THE refusal path for slot combinations the scheduler cannot emit.

    Every message names the conflicting SLOT (gather/grad/probe), not a
    legacy knob — callers composing programmatically see which slot to
    drop."""


# ---------------------------------------------------------------------------
# slot declarations
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class GatherSlot:
    """Per-layer weight gathers (ZeRO-3).  `prefetch` = gathered layers
    held live (1 = on-demand, 2 = double buffer ...); `groups` = 2-hop
    hierarchical gather inner size (legacy prefetch lowering only);
    `hpz` = gathers run intra-slice from the secondary partition."""
    prefetch: int = 1
    groups: Optional[int] = None
    hpz: bool = False
    # secondary-rebuild codec (qwZ, ZeRO++): "fp32" gathers the stacked
    # compute dtypes; int8/fp8 moves blockwise-quantized payload + scales
    # over the inter-slice hop and dequantizes once per granule
    hpz_mode: str = "fp32"

    def describe(self) -> str:
        s = f"gather_prefetch={self.prefetch}"
        if self.groups:
            s += f"(2-hop inner={self.groups})"
        if self.hpz:
            s += "+hpz"
            if self.hpz_mode != "fp32":
                s += f"[{self.hpz_mode}]"
        return s


@dataclasses.dataclass(frozen=True)
class GradSlot:
    """Gradient releases: `buckets` layer buckets (+ non-block tail),
    collective codec `mode` with `block`-sized absmax scales and optional
    error-feedback residual slices; `groups` = 2-hop hierarchical
    schedule inner size (monolithic AND composed lowerings — every
    quantized release inside composed_step passes it down to
    quantized_grad_sync's inner/outer split)."""
    buckets: int = 1
    mode: str = "fp32"
    block: int = DEFAULT_BLOCK
    groups: Optional[int] = None
    error_feedback: bool = True
    # composed ZeRO-3 tail codec: "fp32" keeps the differentiable
    # gather's full-precision transpose reduce-scatter; int8/fp8 routes
    # the tail cotangents through the blockwise quantized sync with its
    # own error-feedback residual slice (stages 0-2 already quantize the
    # tail via `mode` — this knob exists only where the tail would
    # otherwise be the last fp32 collective)
    tail_mode: str = "fp32"

    def describe(self) -> str:
        s = f"grad_buckets={self.buckets},grad_comm={self.mode}"
        if self.groups:
            s += f"(2-hop inner={self.groups})"
        if self.mode != "fp32" and not self.error_feedback:
            s += "(no-ef)"
        if self.tail_mode != "fp32":
            s += f",tail_comm={self.tail_mode}"
        return s


@dataclasses.dataclass(frozen=True)
class ProbeSlot:
    """Per-layer health probe (engine telemetry layers mode)."""
    kind: str = "layer_health"

    def describe(self) -> str:
        return "health"


@dataclasses.dataclass(frozen=True)
class PipeSlot:
    """Table-driven pipeline schedule (parallel/pipe_schedule.py):
    `kind` = "interleaved" (virtual stages, combined backward) or "zbub"
    (zero-bubble B/W split); `virtual` chunks per physical stage.  The
    validated Schedule carries the compiled tick program alongside —
    the engine's step interprets it via pipeline.spmd_pipeline_table."""
    kind: str = "interleaved"
    virtual: int = 1
    stages: int = 0
    microbatches: int = 0

    def describe(self) -> str:
        return (f"pipe={self.kind}:{self.virtual}"
                f"[m={self.microbatches}]")


# ---------------------------------------------------------------------------
# --sched spec parsing (examples/common.py, ONE translation site)
# ---------------------------------------------------------------------------

def parse_sched_spec(spec: str) -> Dict[str, Any]:
    """Parse a `--sched` composition string into engine kwargs.

    e.g. "gather_prefetch=2,grad_buckets=4,grad_comm=int8,health,hpz"
    -> {"gather_prefetch": 2, "grad_buckets": 4, "grad_comm": "int8",
        "telemetry_layers": True, "hpz": True}.

    `grad_buckets`, `gather_groups` and `grad_comm` also accept the
    literal "auto" — resolved by `auto_comm_plan` against the mesh's
    DCN granule map at engine build.  `grad_comm_tail` / `hpz_comm`
    extend the codec vocabulary to the composed ZeRO-3 tail release and
    the hpZ secondary rebuild.

    `pipe=KIND[:V]` selects the pipeline schedule slot: `pipe=gpipe`,
    `pipe=1f1b`, `pipe=interleaved:2` (V virtual chunks per stage,
    default 2), `pipe=zbub[:V]` (zero-bubble B/W split, default V=1) —
    translated to `pipeline_schedule` / `pipeline_virtual` engine kwargs.

    `telemetry_layers` is not an engine kwarg — the caller upgrades its
    Telemetry to layers=True (examples/common.py does)."""
    out: Dict[str, Any] = {}
    int_keys = ("gather_prefetch", "gather_groups", "grad_buckets",
                "grad_comm_groups", "grad_comm_block")
    auto_ok = ("gather_groups", "grad_buckets", "grad_comm")
    mode_keys = ("grad_comm", "grad_comm_tail", "hpz_comm")
    pipe_kinds = ("gpipe", "1f1b", "interleaved", "zbub")
    for part in (p.strip() for p in spec.split(",") if p.strip()):
        if part == "health":
            out["telemetry_layers"] = True
            continue
        if part == "hpz":
            out["hpz"] = True
            continue
        if "=" not in part:
            raise ValueError(
                f"--sched element {part!r} is not 'key=value', 'health' "
                f"or 'hpz'"
            )
        key, val = (s.strip() for s in part.split("=", 1))
        if key == "pipe":
            kind, _, vtxt = val.partition(":")
            if kind not in pipe_kinds:
                raise ValueError(
                    f"--sched pipe must be one of {pipe_kinds} "
                    f"(optionally KIND:V), got {val!r}"
                )
            out["pipeline_schedule"] = kind
            if vtxt:
                out["pipeline_virtual"] = int(vtxt)
            elif kind == "interleaved":
                out["pipeline_virtual"] = 2
            continue
        if val == "auto" and key in auto_ok:
            out[key] = "auto"
        elif key in int_keys:
            out[key] = int(val)
        elif key in mode_keys:
            if val not in GRAD_COMM_MODES:
                raise ValueError(
                    f"--sched {key} must be one of {GRAD_COMM_MODES}, "
                    f"got {val!r}"
                )
            out[key] = val
        else:
            raise ValueError(f"unknown --sched key {key!r}")
    return out


# ---------------------------------------------------------------------------
# DCN-aware "auto" comm sizing + the tune_e2e plan bridge
# ---------------------------------------------------------------------------

def auto_comm_plan(*, n_shard: int, n_layer: int, shapes=None,
                   granule_of=None, block: int = DEFAULT_BLOCK,
                   max_buckets: int = 8,
                   overhead_tol: float = 0.10) -> Dict[str, Any]:
    """Resolve the "auto" comm knobs from the link hierarchy + modeled
    bytes — the DCN-aware sizing policy (ZeRO++ arXiv:2306.10209,
    EQuARX arXiv:2506.17615: quantized/bucketed collectives pay exactly
    when sized against the real link topology).

    Policy (each rule checkable against the measured
    `wire_bytes_by_link` split, tests/test_schedule.py):

      * grad_comm — "int8" whenever there IS a gradient collective
        (n_shard > 1): halves-to-quarters the wire on every link and the
        error-fed stochastic rounding keeps parity; "fp32" on a single
        rank (the collective does not exist).
      * grad_buckets — the LARGEST divisor of n_layer (capped at
        `max_buckets`, and at max(2, max_buckets // n_granules) on a
        hybrid mesh: every bucket sync crosses DCN, and DCN latency is
        per-collective) whose per-bucket padding keeps the modeled quant
        wire within `overhead_tol` of the monolithic sync.  More buckets
        = more backward overlap window; the tolerance is what stops tiny
        buckets from paying padding + scale overhead for it.
      * gather_inner — the intra-granule rank count (`ici`) on a hybrid
        mesh, so a 2-hop gather's fat first hop stays on ICI; None on a
        flat mesh (a 2-hop over uniform links moves the same bytes
        twice).  `build_schedule` applies it ONLY when the composition
        lowers to the single-slot prefetch program — the composed
        machine refuses 2-hop groups, so "auto" resolves to flat there
        instead of tripping the ScheduleConflictError.

    Pure function of static geometry (unit-testable without a mesh);
    returns the resolved knobs plus the modeled bytes behind them."""
    from .mesh import granule_geometry
    from .comm import modeled_wire_bytes

    n_gran, ici = granule_geometry(granule_of, n_shard)
    plan: Dict[str, Any] = {
        "n_granules": n_gran,
        "grad_comm": "int8" if n_shard > 1 else "fp32",
        "grad_buckets": 1,
        "gather_inner": (ici if n_gran > 1 and 2 <= ici < n_shard
                         and n_shard % ici == 0 else None),
    }
    if n_shard <= 1 or n_layer <= 1 or not shapes:
        return plan
    cap = max_buckets if n_gran <= 1 else max(2, max_buckets // n_gran)
    divisors = [k for k in range(1, min(n_layer, cap) + 1)
                if n_layer % k == 0]
    block_elems = sum(
        int(np.prod(s.shape)) for nm, s in shapes.items()
        if nm.startswith("h.")
    )
    if not block_elems:
        return plan
    mode = plan["grad_comm"]
    base = modeled_wire_bytes(block_elems, n_shard, mode, block=block)
    budget = (1.0 + overhead_tol) * base["quant_wire_bytes"]
    best_k, best_wire = 1, base["quant_wire_bytes"]
    for k in divisors:
        per = modeled_wire_bytes(
            block_elems // k, n_shard, mode, block=block
        )
        wire_k = k * per["quant_wire_bytes"]
        if wire_k <= budget:
            best_k, best_wire = k, wire_k
    plan["grad_buckets"] = best_k
    plan["modeled"] = {
        "grad_wire_bytes": float(best_wire),
        "grad_wire_bytes_monolithic": float(base["quant_wire_bytes"]),
        "fp32_allreduce_wire_bytes": base["fp32_allreduce_wire_bytes"],
        # flat DP: every grad collective spans all granules, so its
        # whole wire bills to DCN under the ledger's conservative
        # crossing rule (utils/hlo_comm.wire_link_split)
        "dcn_frac_est": 1.0 if n_gran > 1 else 0.0,
    }
    return plan


# the comm knobs a tune_e2e / auto plan may carry, in engine-kwarg
# spelling — ONE list shared by the bench comm phase, the AOT plan
# round-trip, and the tests
COMM_PLAN_KEYS = ("grad_comm", "grad_buckets", "grad_comm_tail",
                  "gather_groups", "gather_prefetch", "hpz", "hpz_comm")


def comm_plan_engine_kwargs(plan: Dict[str, Any]) -> Dict[str, Any]:
    """Filter a persisted tune_e2e plan down to the engine kwargs it
    carries (the AOT-cache round-trip seam: bench stores the winning
    plan via RuntimeAutoTuner.store_plan; a later run feeds it straight
    back into Zero3(**comm_plan_engine_kwargs(plan)))."""
    return {k: plan[k] for k in COMM_PLAN_KEYS
            if k in plan and plan[k] is not None}


# ---------------------------------------------------------------------------
# per-layer health probe (ProbeSlot; engine telemetry layers mode, ISSUE 5)
# ---------------------------------------------------------------------------

def _act_stats(x) -> jax.Array:
    """(2,) f32: [sum of squares, non-finite element count] of one layer's
    output activation.  Sums run over the LOGICAL array, so under sharded
    activations XLA inserts the cross-shard psum and every rank reports
    the same global numbers (the health_vector convention).  Inside a
    shard_map manual region the sums are LOCAL — the composed lowering
    psums the collected stats once at the end."""
    xf = x.astype(jnp.float32)
    return jnp.stack([
        jnp.sum(jnp.square(xf)),
        jnp.sum((~jnp.isfinite(xf)).astype(jnp.float32)),
    ])


@jax.custom_vjp
def layer_health_tap(x, probe):
    """Identity on `x`; the (4,) f32 `probe`'s COTANGENT smuggles this
    layer's health stats out of the step — [act sq-sum, act non-finite
    count, d(act) sq-sum, d(act) non-finite count].

    The GradBucketTap trick pointed at observability instead of
    collectives: the engine differentiates the loss w.r.t. a zeros
    (n_layer, 4) probe that rides the stacked scan tree (one (4,) row per
    layer, like the per-layer dropout keys), each layer's block output
    passes through this tap, and the "gradient" of the probe comes back
    as the per-layer activation/activation-gradient stats — computed
    INSIDE the compiled step, per layer, with no scan restructuring and
    no extra host transfers.  The first-NaN layer is read off the stats
    in one step instead of by bisection.  Forward stats are recomputed
    bit-exactly by the remat backward (they live inside the block's
    jax.checkpoint), so the fwd residual costs 2 floats per layer."""
    return x


def _lht_fwd(x, probe):
    return x, _act_stats(x)


def _lht_bwd(stats, g):
    return g, jnp.concatenate([stats, _act_stats(g)])


layer_health_tap.defvjp(_lht_fwd, _lht_bwd)

# probe row width: [act_sq, act_nonfinite, dact_sq, dact_nonfinite]
LAYER_PROBE_WIDTH = 4


class ProbeScan:
    """Probe-only lowering: the (n_layer, 4) probe rides the stacked scan
    tree (the model's block_fn taps every layer's output when the
    "health_probe" row is present) and the scan itself stays the plain
    GSPMD lax.scan — byte-identical to the pre-scheduler program."""

    def __init__(self, probe):
        self.probe = probe

    def scan(self, block, stacked, x, unroll=1):
        stacked = dict(stacked, health_probe=self.probe)

        def scan_body(x, bp):
            return block(x, bp), None

        x, _ = jax.lax.scan(scan_body, x, stacked, unroll=unroll)
        return x


# ---------------------------------------------------------------------------
# bucketed backward-overlapped release (GradSlot legacy lowering, ISSUE 3)
# ---------------------------------------------------------------------------

def _make_tap(reduce_fn):
    """Identity-forward custom_vjp whose BACKWARD runs `reduce_fn` on the
    cotangent: `reduce_fn(grad_chunk_tree, extras) -> (reduced_chunk_tree,
    extras_cotangent)`.  The reduced tree must match the chunk's leaf
    dtypes exactly (custom_vjp checks the bwd output against the primal
    avals); the extras cotangent is the smuggling channel — e.g. the new
    error-feedback residual rides out of the backward as the "gradient"
    of the residual slice that rode in."""
    @jax.custom_vjp
    def tap(chunk, extras):
        return chunk

    def fwd(chunk, extras):
        return chunk, extras

    def bwd(extras, g):
        return reduce_fn(g, extras)

    tap.defvjp(fwd, bwd)
    return tap


class GradBucketTap:
    """Per-bucket gradient release inside the model's layer scan.

    Built by the engine INSIDE its shard_map manual region over the data
    axis and handed to `model.apply(..., sched=self)`.  The model's
    layer loop calls `scan(block, stacked, x, unroll=...)`: the stacked
    (L, ...) leaves reshape to (K, L/K, ...), an outer lax.scan runs over
    the K buckets with the layer scan inside, and each bucket's param
    slice passes through an identity `custom_vjp` whose backward runs
    this bucket's gradient collective.  That places the reduce for bucket
    k INSIDE the backward scan body — issued while buckets k-1..0 still
    have backward compute in flight for XLA's latency-hiding scheduler /
    collective pipeliner to overlap — the reference's per-parameter
    backward-hook all-reduce (reference ddp/module.py:36-78) and its
    unshipped "communication bucketing" TODO (reference README.md:66-71),
    expressed in XLA terms.

    `extras` is a dict of per-bucket float32 side inputs, every leaf with
    leading dim K, sliced by the outer scan and fed through the tap:

      "res"  — (K, bucket_pad) error-feedback residual slices; the tap's
               cotangent for it IS the new residual (smuggled out of the
               backward through the vjp).
      "acc"  — accumulated-gradient prefix chunks (grad accumulation:
               the first A-1 microbatches sum locally, the final
               microbatch's taps add the prefix before the one collective
               per bucket).
      "rng"  — stochastic-rounding key rows BITCAST to f32 (an integer
               tap input would need a float0 cotangent; a 2-word bitcast
               keeps the tap all-float).

    Integer leaves of the stacked tree itself (the per-layer dropout
    keys) stay OUTSIDE the tap for the same float0 reason."""

    def __init__(self, n_buckets: int, reduce_fn, extras=None):
        self.n_buckets = int(n_buckets)
        self._tap = _make_tap(reduce_fn)
        self.extras = extras or {}

    def scan(self, block, stacked, x, unroll=1):
        """Drop-in replacement for the model's plain layer scan: same
        (x, stacked) -> x contract, buckets of layers instead of single
        layers as the outer iteration."""
        k = self.n_buckets

        def resh(a):
            return a.reshape((k, a.shape[0] // k) + a.shape[1:])

        stacked_b = jax.tree.map(resh, stacked)

        def bucket_body(carry, xs):
            bp, ex = xs
            tappable = {
                n: v for n, v in bp.items()
                if jnp.issubdtype(v.dtype, jnp.floating)
            }
            tapped = self._tap(tappable, ex)
            bp = dict(bp, **tapped)

            def layer(c, lp):
                return block(c, lp), None

            c, _ = jax.lax.scan(layer, carry, bp, unroll=unroll)
            return c, None

        x, _ = jax.lax.scan(bucket_body, x, (stacked_b, self.extras))
        return x


# ---------------------------------------------------------------------------
# ZeRO-3 layer-ahead weight-gather prefetch (GatherSlot legacy lowering,
# ISSUE 4)
# ---------------------------------------------------------------------------

class GatherPrefetchScan:
    """Layer-ahead weight-gather prefetch for the ZeRO-3 block scan.

    Under plain ZeRO-3 the per-layer all-gather is GSPMD-implicit: the
    scan slices layer k's sharded weights and the partitioner gathers
    them AT THE TOP of body k — serialized in front of layer k's compute
    (DeepSpeed ships stage-3 parameter prefetch for exactly this cost;
    ZeRO++ qwZ quantizes the same gathers).  This scan makes the gather
    explicit and moves it one-plus layers AHEAD: body k issues layer
    k+(K-1)'s gather (a sharding constraint to the gathered layout — or
    the 2-hop shard_map schedule under `groups`) while layer k computes
    from the double buffer carried through the scan, so the latency-
    hiding scheduler can overlap gather wire with block compute.  At most
    K layers' gathered weights are live (K=2 = classic double buffer).

    The SAME structure runs on the backward: the whole prefetched stack
    is an identity-story `custom_vjp` (the GradBucketTap machinery, the
    symmetric twin on the forward/weight side) whose bwd is a reverse
    scan over layers — recompute layer k's block from the stashed input
    activation (remat, policy "nothing": only the L per-layer activations
    are saved, same as the plain remat stash) while prefetching layer
    k-(K-1), and constraining each layer's dW to the sharded slice spec
    so the grad reduce-scatter stays in-loop too.  Integer leaves of the
    stacked tree (the per-layer dropout keys) cross the custom_vjp
    boundary bitcast to f32 (the PR-3 tap rule: no float0 cotangents),
    and ride the scan un-prefetched — they are replicated scalars, there
    is no wire to hide.

    `groups=m` (engine `gather_groups`) runs the hierarchical 2-hop
    gather, mirroring `grad_comm_groups`: hop 1 all-gathers each leaf's
    shards WITHIN m consecutive ranks at the resting precision (f8 when
    the leaf is `gather_quant`-quantized), dequantizes the group chunk
    once, hop 2 all-gathers the compute-dtype chunks ACROSS groups —
    "fp8 intra-group, bf16 inter-group" on a bf16-compute model.  Leaves
    the ZeRO layout left replicated (norm weights on small models,
    biases, scales) skip the shard_map: they have no shards to gather.

    Cost model: each pass (fwd, and the bwd re-forward) issues K-1 extra
    clamped end-of-scan gathers — (L+K-1)/L of the on-demand gather wire
    (priced in utils/profiling.comm_report); `utils/hlo_comm.
    overlap_report` measures the placement (`gather_overlap_frac`)."""

    def __init__(self, depth: int, mesh, gather_specs, shard_specs, *,
                 groups: Optional[int] = None, data_axis: str = "data",
                 compute_dtype=jnp.bfloat16):
        if depth < 2:
            raise ValueError(
                f"GatherPrefetchScan needs depth >= 2 (depth-1 layers of "
                f"lookahead), got {depth}"
            )
        self.depth = int(depth)
        self.mesh = mesh
        self.gather_specs = dict(gather_specs or {})
        self.shard_specs = dict(shard_specs or {})
        self.groups = int(groups) if groups else None
        self.data_axis = data_axis
        self.cd = compute_dtype

    # -- one layer's gather --------------------------------------------------

    def _shard_dim(self, name: str) -> Optional[int]:
        """Index of the ZeRO data-sharded dim in the SLICED leaf, or None
        when the layout left it replicated (nothing to gather)."""
        spec = self.shard_specs.get(name)
        if spec is None:
            return None
        for i, ax in enumerate(spec):
            if ax == self.data_axis or (
                isinstance(ax, tuple) and self.data_axis in ax
            ):
                return i
        return None

    def _dequant_names(self, sliced) -> Tuple[str, ...]:
        """Leaves the 2-hop gather dequantizes between hops: quantized
        (a '#scale' partner exists) AND data-sharded (they go through the
        shard_map; replicated leaves never enter it)."""
        if not self.groups:
            return ()
        return tuple(sorted(
            n for n in sliced
            if n + "#scale" in sliced and self._shard_dim(n) is not None
        ))

    def _gather(self, sliced):
        """One layer's float leaves, sharded slice -> gathered block-param
        tree.  Flat path: a sharding constraint per leaf to its gathered
        spec (f8 + scale kept; the block's `_bw` dequantizes after the
        gather, exactly the on-demand fp8 contract).  2-hop path: explicit
        shard_map all-gathers; quantized leaves come back DEQUANTIZED in
        compute dtype with their scales dropped (hop 2 moved the
        dequantized chunks)."""
        if not self.groups:
            out = {}
            for name, v in sliced.items():
                spec = self.gather_specs.get(name)
                if spec is not None:
                    v = jax.lax.with_sharding_constraint(
                        v, NamedSharding(self.mesh, spec))
                out[name] = v
            return out

        n = self.mesh.shape[self.data_axis]
        inner = self.groups
        intra, inter = _hier_groups(n, inner)
        cd = self.cd
        dq = set(self._dequant_names(sliced))
        sharded, dims, scales, out = {}, {}, {}, {}
        for name, v in sliced.items():
            if name.endswith("#scale") and name[: -len("#scale")] in dq:
                continue  # consumed by its weight's inter-hop dequant
            d = self._shard_dim(name)
            if d is None:
                out[name] = v  # replicated at rest: no shards to gather
                continue
            sharded[name] = v
            dims[name] = d
            if name in dq:
                scales[name] = sliced[name + "#scale"]
        if not sharded:
            return out

        def local(vals, scs):
            res = {}
            for name, v in vals.items():
                dim = dims[name]
                g1 = jax.lax.all_gather(
                    v, self.data_axis, axis=dim, tiled=True,
                    axis_index_groups=intra)
                s = scs.get(name)
                if s is not None:
                    # dequantize ONCE per group chunk; hop 2 moves the
                    # compute-dtype values (fp8 intra, bf16 inter)
                    g1 = g1.astype(cd) * s.astype(cd)
                res[name] = jax.lax.all_gather(
                    g1, self.data_axis, axis=dim, tiled=True,
                    axis_index_groups=inter)
            return res

        vspecs = {
            name: P(*(self.data_axis if i == dims[name] else None
                      for i in range(v.ndim)))
            for name, v in sharded.items()
        }
        sspecs = {name: P() for name in scales}
        ospecs = {name: P() for name in sharded}
        gathered = jax.shard_map(
            local, mesh=self.mesh, in_specs=(vspecs, sspecs),
            out_specs=ospecs, check_vma=False,
        )(sharded, scales)
        out.update(gathered)
        return out

    def _pullback(self, dwg, sfk):
        """Map the block-vjp cotangent (gathered structure) back onto the
        sliced stacked-tree structure.  Flat path: identity.  2-hop path:
        the dequant multiply lived inside the gather, so dequantized
        leaves' compute-dtype cotangents pull back through it here
        (d_f8 = dw * scale, cast; scale cotangent zero — it is
        stop-gradiented upstream by stacked_compute_params)."""
        dq = self._dequant_names(sfk)
        if not dq:
            return dict(dwg)
        out = dict(dwg)
        for name in dq:
            s = sfk[name + "#scale"]
            out[name] = (
                dwg[name].astype(jnp.float32) * s.astype(jnp.float32)
            ).astype(sfk[name].dtype)
            out[name + "#scale"] = jnp.zeros_like(s)
        return out

    def _constrain_shard(self, name: str, g):
        """Pin one layer's dW cotangent to the sharded slice layout so the
        grad reduce-scatter is emitted INSIDE the backward scan body (the
        on-demand path's property, kept)."""
        spec = self.shard_specs.get(name)
        if spec is None:
            return g
        return jax.lax.with_sharding_constraint(
            g, NamedSharding(self.mesh, spec))

    # -- the scan ------------------------------------------------------------

    def scan(self, block, stacked, x, unroll=1):
        """Drop-in replacement for the model's plain layer scan: same
        (x, stacked) -> x contract, with layer k+(K-1)'s gather issued in
        body k on the forward AND the reverse (remat backward) scan."""
        fkeys = sorted(
            n for n, v in stacked.items()
            if not jnp.issubdtype(v.dtype, jnp.integer)
        )
        ikeys = sorted(n for n in stacked if n not in set(fkeys))
        idtypes = {n: stacked[n].dtype for n in ikeys}
        L = int(jax.tree.leaves(stacked)[0].shape[0])
        look = self.depth - 1
        if look >= L:
            raise ValueError(
                f"gather_prefetch={self.depth} holds more layers than the "
                f"model has (n_layer={L})"
            )

        def slice_f(sf, i):
            return {
                n: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
                for n, a in sf.items()
            }

        def int_slices(si_b, i):
            return {
                n: jax.lax.bitcast_convert_type(
                    jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
                    idtypes[n])
                for n, a in si_b.items()
            }

        def init_buf(sf, idxs):
            slots = [self._gather(slice_f(sf, i)) for i in idxs]
            return jax.tree.map(lambda *xs: jnp.stack(xs), *slots)

        def shift_in(buf, new):
            return jax.tree.map(
                lambda b, nw: jnp.concatenate([b[1:], nw[None]]), buf, new)

        def fwd_scan(sf, si_b, x0, stash):
            buf = init_buf(sf, list(range(look)))

            def body(carry, k):
                x, buf = carry
                # issue layer k+look's gather FIRST; nothing in this body
                # consumes it, so its wire can hide behind block(k)
                nxt = self._gather(
                    slice_f(sf, jnp.minimum(k + look, L - 1)))
                w = jax.tree.map(lambda b: b[0], buf)
                y = block(x, dict(w, **int_slices(si_b, k)))
                return (y, shift_in(buf, nxt)), (x if stash else None)

            (y, _), xs = jax.lax.scan(
                body, (x0, buf), jnp.arange(L), unroll=unroll)
            return y, xs

        @jax.custom_vjp
        def run(sf, si_b, x0):
            y, _ = fwd_scan(sf, si_b, x0, stash=False)
            return y

        def run_fwd(sf, si_b, x0):
            y, xs = fwd_scan(sf, si_b, x0, stash=True)
            # residuals: the SHARDED stacked tree (no copy) + the L
            # per-layer input activations — the plain remat stash
            return y, (sf, si_b, xs)

        def run_bwd(res, dy):
            sf, si_b, xs = res
            buf = init_buf(sf, [L - 1 - i for i in range(look)])

            def body(carry, inp):
                dx, buf = carry
                x_k, k = inp
                nxt = self._gather(
                    slice_f(sf, jnp.maximum(k - look, 0)))
                w = jax.tree.map(lambda b: b[0], buf)
                ints = int_slices(si_b, k)

                def f(x_, wf):
                    return block(x_, dict(wf, **ints))

                # remat: recompute layer k's block from the stashed input
                _, vjp = jax.vjp(f, x_k, w)
                dx_new, dwg = vjp(dx)
                dw = self._pullback(dwg, slice_f(sf, k))
                dw = {n: self._constrain_shard(n, g)
                      for n, g in dw.items()}
                return (dx_new, shift_in(buf, nxt)), dw

            (dx, _), dws = jax.lax.scan(
                body, (dy, buf), (xs, jnp.arange(L)), reverse=True,
                unroll=unroll)
            return dws, jax.tree.map(jnp.zeros_like, si_b), dx

        run.defvjp(run_fwd, run_bwd)
        return run(
            {n: stacked[n] for n in fkeys},
            {n: jax.lax.bitcast_convert_type(stacked[n], jnp.float32)
             for n in ikeys},
            x,
        )


# ---------------------------------------------------------------------------
# hpZ group geometry
# ---------------------------------------------------------------------------

def hpz_groups(granule_of: Dict[int, int], n: int):
    """(intra, inter, ici, n_gran) axis_index_groups for hpZ over a data
    axis of `n` ranks whose DCN granule is `granule_of[rank]`
    (parallel/mesh.granule_map on the mesh-flat order, or the CPU
    emulation override).

    Requires equal-sized CONTIGUOUS granules (rank r in granule r//ici) —
    the layout `make_mesh` builds (DCN carries the leading data axis).
    intra = the ranks of one slice (the in-scan gather group, ICI only);
    inter = same intra-position ranks across slices (the ONE top-level
    secondary-partition rebuild, the only DCN hop)."""
    grans = [granule_of.get(r) for r in range(n)]
    if any(g is None for g in grans):
        raise ScheduleConflictError(
            f"gather slot (hpz): granule map covers {sorted(granule_of)} "
            f"but the data axis has ranks 0..{n - 1}"
        )
    n_gran = len(set(grans))
    if n_gran < 2:
        raise ScheduleConflictError(
            "gather slot (hpz): the mesh has a single DCN granule — "
            "every gather is already intra-slice; hpz would only add "
            "a redundant secondary partition"
        )
    if n % n_gran:
        raise ScheduleConflictError(
            f"gather slot (hpz): {n_gran} granules must evenly divide "
            f"the data axis ({n} ranks)"
        )
    ici = n // n_gran
    if grans != [r // ici for r in range(n)]:
        raise ScheduleConflictError(
            f"gather slot (hpz): granules must be contiguous equal "
            f"blocks of the data axis (expected rank r in granule "
            f"r//{ici}, got {grans})"
        )
    intra = [[g * ici + l for l in range(ici)] for g in range(n_gran)]
    inter = [[g * ici + l for g in range(n_gran)] for l in range(ici)]
    return intra, inter, ici, n_gran


# ---------------------------------------------------------------------------
# the compiled Schedule + builder
# ---------------------------------------------------------------------------

_LOWERINGS = ("plain", "probe", "bucket", "quant_mono", "prefetch",
              "composed")


@dataclasses.dataclass
class Schedule:
    """A validated slot composition + its chosen lowering.  Built once at
    engine construction by `build_schedule`; the engine routes its step
    through the matching executor (`bucketed_step`, `monolithic_quant_
    step`, `composed_step`) or passes the executor object straight into
    `model.apply(sched=...)` (probe / prefetch lowerings)."""
    gather: Optional[GatherSlot] = None
    grad: Optional[GradSlot] = None
    probe: Optional[ProbeSlot] = None
    pipe: Optional[PipeSlot] = None
    # the compiled tick table (pipe_schedule.PipeProgram) when a pipe
    # slot is declared — validated once here, interpreted per step by
    # pipeline.spmd_pipeline_table; its bubble_frac is the telemetry
    # gauge's source of truth
    pipe_program: Optional[object] = None
    lowering: str = "plain"
    # grad-slot geometry (parallel/comm.bucket_layout) when a grad slot
    # is declared; None otherwise
    layout: Optional[dict] = None
    # error-feedback residual row length (0 = no residual): composed
    # ZeRO-3 with a fp32 tail drops the tail slice (the tail
    # reduce-scatters at full precision through the differentiable
    # gather's transpose); a quantized tail (GradSlot.tail_mode) keeps
    # its own slice, laid out after the bucket slices like stages 0-2
    residual_len: int = 0
    # hpZ geometry: (intra, inter, ici, n_gran) or None
    hpz_geom: Optional[tuple] = None
    # the resolved auto_comm_plan when any knob arrived as "auto"
    # (observability: bench/telemetry report what the policy picked)
    auto_plan: Optional[dict] = None

    @property
    def slots(self):
        return [s for s in (self.gather, self.grad, self.probe,
                            self.pipe)
                if s is not None]

    def describe(self) -> str:
        """Composition string — stable across knob spellings; used by
        engine.describe() and the bench `_config_fingerprint` sched arm."""
        if not self.slots:
            return "plain"
        return "+".join(s.describe() for s in self.slots) + \
            f"@{self.lowering}"


def build_schedule(
    *, model, stage: int, n_shard: int, busy_axes, accum_steps: int,
    scan_unroll, grad_comm: str = "fp32",
    grad_comm_block: int = DEFAULT_BLOCK,
    grad_comm_groups: Optional[int] = None,
    grad_comm_error_feedback: bool = True, grad_buckets: int = 1,
    grad_comm_tail: str = "fp32",
    gather_prefetch: int = 0, gather_groups: Optional[int] = None,
    hpz: bool = False, hpz_comm: str = "fp32",
    granule_of: Optional[Dict[int, int]] = None,
    telemetry_layers: bool = False, pipeline: bool = False,
    pipe_schedule: Optional[str] = None, pipe_stages: int = 0,
    pipe_virtual: int = 1, pipe_microbatches: int = 0,
) -> Schedule:
    """Translate engine knobs into slot declarations, validate the
    composition ONCE, and pick the lowering.

    `grad_comm`, `grad_buckets` and `gather_groups` may arrive as the
    literal "auto": resolved here by `auto_comm_plan` against the DCN
    granule map before slots are declared (the resolved plan rides the
    Schedule as `auto_plan`).

    Legacy single-slot requests lower to their pre-scheduler programs
    (HLO byte-identical, pinned by tests/test_schedule.py); any genuine
    composition lowers to the merged `composed_step` machine.  Genuinely
    inexpressible combinations raise `ScheduleConflictError` naming the
    conflicting SLOT."""
    n_layer = int(
        getattr(getattr(model, "config", None), "n_layer", 0) or 0
    )
    gq = bool(getattr(getattr(model, "config", None), "gather_quant",
                      None))

    # ---- resolve "auto" knobs against the link hierarchy -------------------
    auto_plan = None
    if "auto" in (grad_comm, grad_buckets, gather_groups):
        try:
            shapes = model.param_shapes()
        except Exception:
            shapes = None
        auto_plan = auto_comm_plan(
            n_shard=n_shard, n_layer=n_layer, shapes=shapes,
            granule_of=granule_of, block=int(grad_comm_block),
        )
        if grad_comm == "auto":
            grad_comm = auto_plan["grad_comm"]
        if grad_buckets == "auto":
            # bucketing exists to pipeline the QUANTIZED syncs; a plain
            # fp32 all-reduce program has no bucket machinery to size
            grad_buckets = (auto_plan["grad_buckets"]
                            if grad_comm != "fp32" else 1)
        if gather_groups == "auto":
            # the 2-hop gather only exists in the single-slot prefetch
            # lowering; under any composition "auto" means flat, not a
            # ScheduleConflictError
            legacy_prefetch = (
                gather_prefetch > 1 and not hpz
                and not telemetry_layers
                and grad_comm == "fp32"
                and (grad_buckets in (0, 1))
            )
            gather_groups = (auto_plan["gather_inner"]
                             if legacy_prefetch else None)

    # ---- tail / hpz codec preconditions (loud, before slots settle) --------
    if grad_comm_tail not in GRAD_COMM_MODES:
        raise ValueError(
            f"grad_comm_tail must be one of {GRAD_COMM_MODES}, "
            f"got {grad_comm_tail!r}"
        )
    if hpz_comm not in GRAD_COMM_MODES:
        raise ValueError(
            f"hpz_comm must be one of {GRAD_COMM_MODES}, "
            f"got {hpz_comm!r}"
        )
    if hpz_comm != "fp32" and not hpz:
        raise ValueError(
            "hpz_comm quantizes the hpZ secondary rebuild; it needs "
            "hpz=True"
        )
    if grad_comm_tail != "fp32":
        if stage < 3:
            raise ValueError(
                "grad_comm_tail is a ZeRO-3 knob: at stages 0-2 the "
                "non-block tail already syncs through the grad_comm "
                "codec — drop grad_comm_tail or set grad_comm="
            )
        if grad_comm == "fp32":
            raise ValueError(
                "grad_comm_tail composes with a quantized grad slot "
                "(the tail shares the codec machinery and the residual "
                "row); set grad_comm='int8'/'fp8' first"
            )

    # ---- declare slots from the knobs --------------------------------------
    gather = None
    if hpz or gather_prefetch > 1:
        gather = GatherSlot(
            prefetch=max(int(gather_prefetch) or 0, 1),
            groups=gather_groups, hpz=bool(hpz),
            hpz_mode=str(hpz_comm),
        )
    grad = None
    if grad_buckets > 1 or grad_comm != "fp32":
        grad = GradSlot(
            buckets=max(int(grad_buckets), 1), mode=grad_comm,
            block=int(grad_comm_block), groups=grad_comm_groups,
            error_feedback=bool(grad_comm_error_feedback),
            tail_mode=str(grad_comm_tail),
        )
    probe = ProbeSlot() if telemetry_layers else None
    # ZeRO-3 with a grad slot needs the explicit in-region gathers even
    # when no prefetch was asked for: declare the on-demand gather slot
    # (prefetch=1) implicitly — the lift of the old "stages 0-2" refusal
    if stage >= 3 and grad is not None and gather is None:
        gather = GatherSlot(prefetch=1)

    # ---- pipe slot: table-driven schedules validate + compile here ---------
    if pipe_schedule in ("interleaved", "zbub"):
        pipe = PipeSlot(
            kind=pipe_schedule, virtual=max(int(pipe_virtual), 1),
            stages=int(pipe_stages),
            microbatches=int(pipe_microbatches) or int(pipe_stages),
        )
        # the table executor runs the whole loss inside its own
        # partial-manual scan: the in-scan gather/grad/probe machinery
        # of the composed step does not exist there (yet) — refuse each
        # pair by name rather than silently dropping a slot
        for other in (s for s in (gather, grad, probe) if s is not None):
            raise ScheduleConflictError(
                f"pipe slot ({pipe.describe()}) conflicts with the "
                f"{other.describe()} slot: the table-driven pipeline "
                f"computes its gradients explicitly inside the tick "
                f"scan, which does not thread the in-scan "
                f"release/gather/probe machinery — drop one of the "
                f"two slots"
            )
        if not getattr(model, "supports_pipe_table", False):
            raise ScheduleConflictError(
                f"pipe slot ({pipe.describe()}): "
                f"{type(model).__name__} does not support table-driven "
                f"pipeline schedules (supports_pipe_table=False — e.g. "
                f"the MoE aux loss would need to ride every F tick and "
                f"replay in W's re-linearization); use "
                f"pipeline_schedule='1f1b'"
            )
        busy = [ax for ax in busy_axes
                if ax is not None and ax != "pipe"]
        if busy:
            raise ScheduleConflictError(
                f"pipe slot ({pipe.describe()}): the table executor is "
                f"manual over the pipe axis only (data stays GSPMD) — "
                f"it does not compose with active axes {busy}; use "
                f"pipeline_schedule='1f1b' for seq parallelism"
            )
        if n_layer and n_layer % (pipe.stages * pipe.virtual):
            raise ScheduleConflictError(
                f"pipe slot ({pipe.describe()}): n_layer={n_layer} not "
                f"divisible by stages*virtual="
                f"{pipe.stages}*{pipe.virtual}"
            )
        from .pipe_schedule import build_pipe_program
        try:
            prog = build_pipe_program(
                pipe.stages, pipe.virtual, pipe.microbatches,
                split_w=(pipe.kind == "zbub"),
                n_layer=n_layer or None,
            )
        except ValueError as e:
            raise ScheduleConflictError(
                f"pipe slot ({pipe.describe()}): {e}"
            ) from e
        return Schedule(pipe=pipe, pipe_program=prog, lowering="pipe")

    if gather is None and grad is None and probe is None:
        return Schedule(lowering="plain")

    # ---- single-feature inert fallbacks (1-device data axis) ---------------
    if n_shard <= 1:
        if grad is not None:
            warnings.warn(
                f"grad slot ({grad.describe()}) is inert on a 1-device "
                "data axis (there is no gradient collective); running "
                "the exact unscheduled path", stacklevel=3,
            )
            grad = None
        if gather is not None:
            warnings.warn(
                f"gather slot ({gather.describe()}) is inert on a "
                "1-device data axis (there is no weight gather); running "
                "the on-demand path", stacklevel=3,
            )
            gather = None
        if probe is None:
            return Schedule(lowering="plain")

    slots = [s for s in (gather, grad, probe) if s is not None]
    # a bucketed grad slot over fp8-quantized stacked leaves must run
    # the composed machine even solo: the legacy tap would put e4m3
    # cotangents on the bucket collectives (the refusal this PR lifts),
    # while the composed backward accumulates dW in f32 before release
    multi = (len(slots) > 1
             or (gather is not None
                 and (gather.hpz or gather.prefetch == 1))
             or (grad is not None and grad.buckets > 1 and gq))

    # ---- composition validation (the ONE refusal path) ---------------------
    if multi:
        if accum_steps > 1:
            raise ScheduleConflictError(
                f"the composed schedule "
                f"({'+'.join(s.describe() for s in slots)}) does not "
                f"support accum_steps={accum_steps} yet — prefix "
                f"microbatches would bypass the probe/gather slots; "
                f"drop a slot or set accum_steps=1"
            )
        if gather is not None and gather.groups:
            raise ScheduleConflictError(
                f"gather slot: the 2-hop gather (gather_groups="
                f"{gather.groups}) is only emitted by the single-slot "
                f"prefetch lowering; it conflicts with "
                f"{'+'.join(s.describe() for s in slots if s is not gather)}"
            )
        if grad is not None and n_layer and n_layer % grad.buckets:
            raise ValueError(
                f"grad_buckets={grad.buckets} must divide "
                f"n_layer={n_layer} (equal layers per bucket is what "
                "keeps the buckets size-balanced and the scan body "
                "uniform)"
            )
        # MoE-style models sit out: their scan carries an aux-loss
        # accumulator the merged scan bodies do not thread
        for s, flag in ((gather, "gather_prefetch_capable"),
                        (grad, "grad_bucket_capable"),
                        (probe, "layer_health_capable")):
            if s is not None and not getattr(model, flag, False):
                raise ScheduleConflictError(
                    f"{type(model).__name__} cannot run the "
                    f"{s.describe()} slot through the composed scan "
                    f"({flag}=False — e.g. the MoE scan carries an "
                    f"aux-loss accumulator the merged scan body does "
                    f"not thread)"
                )


    # ---- slot-level validation ---------------------------------------------
    busy = [ax for ax in busy_axes if ax is not None]
    if probe is not None:
        if pipeline:
            raise ValueError(
                "telemetry layers mode rides the layer scan; it does "
                "not compose with the pipeline forward "
                "(pipeline_parallel / pipeline_schedule='1f1b')"
            )
        if not getattr(model, "layer_health_capable", False):
            raise ValueError(
                f"{type(model).__name__} does not thread the per-layer "
                "health probe through its layer scan "
                "(layer_health_capable=False)"
            )
        if not n_layer:
            raise ValueError(
                "telemetry layers mode needs a layered model "
                "(config.n_layer)"
            )
    if grad is not None:
        if grad.mode not in GRAD_COMM_MODES:
            raise ValueError(
                f"grad_comm must be one of {GRAD_COMM_MODES}, "
                f"got {grad.mode!r}"
            )
        if busy:
            raise ValueError(
                f"the grad slot needs a pure data-parallel mesh (the "
                f"explicit schedule replays the model inside a shard_map "
                f"over the data axis); active axes: {busy}"
            )
        if grad.buckets > 1 and not getattr(
                model, "grad_bucket_capable", False):
            raise ValueError(
                f"{type(model).__name__} does not thread the bucketed "
                "grad-release tap through its layer scan "
                "(grad_bucket_capable=False)"
            )
        if grad.groups is not None and (
            grad.groups < 2 or grad.groups >= n_shard
            or n_shard % grad.groups
        ):
            raise ValueError(
                f"grad_comm_groups={grad.groups} must be a proper "
                f"divisor of the data-axis size {n_shard} (>= 2)"
            )
    if gather is not None:
        if stage < 3:
            raise ValueError(
                "the gather slot (gather_prefetch / hpz) requires ZeRO-3 "
                "(stages 0-2 keep params replicated/gathered once — "
                "there is no per-layer weight gather to schedule)"
            )
        if not getattr(model, "gather_prefetch_capable", False):
            raise ValueError(
                f"{type(model).__name__} does not thread the scheduled "
                "weight-gather scan through its layer loop "
                "(gather_prefetch_capable=False)"
            )
        if busy:
            raise ValueError(
                f"the gather slot needs a pure data-parallel mesh; "
                f"active axes: {busy}"
            )
        if scan_unroll is True or scan_unroll not in (1, False):
            raise ValueError(
                "the gather slot rides the layer scan; it cannot "
                "combine with scan_unroll != 1"
            )
        if n_layer and gather.prefetch > n_layer:
            raise ValueError(
                f"gather_prefetch={gather.prefetch} holds more layers "
                f"than the model has (n_layer={n_layer})"
            )
        if gather.groups is not None and (
            gather.groups < 2 or gather.groups >= n_shard
            or n_shard % gather.groups
        ):
            raise ValueError(
                f"gather_groups={gather.groups} must be a proper "
                f"divisor of the data-axis size {n_shard} (>= 2)"
            )

    # ---- hpZ geometry -------------------------------------------------------
    geom = None
    if gather is not None and gather.hpz:
        if granule_of is None:
            raise ScheduleConflictError(
                "gather slot (hpz): no DCN granule map — the mesh spans "
                "a single slice/process (parallel/mesh.granule_map "
                "returned None) and no granule_of= override was given"
            )
        geom = hpz_groups(granule_of, n_shard)

    # ---- pick the lowering --------------------------------------------------
    layout = None
    residual_len = 0
    if grad is not None:
        shapes = model.param_shapes()
        stack_dims = [s.shape[0] for nm, s in shapes.items()
                      if nm.startswith("h.")]
        if grad.buckets > 1 and not stack_dims:
            raise ValueError(
                "grad_buckets needs a stacked-block model (no 'h.*' "
                "leaves to bucket by layer)"
            )
        if grad.buckets > 1 or multi:
            layout = bucket_layout(
                shapes, stack_dims[0], grad.buckets, n_shard, grad.block
            )
        if grad.mode != "fp32" and grad.error_feedback:
            if layout is not None:
                residual_len = grad.buckets * layout["bucket_pad"]
                if stage < 3 or grad.tail_mode != "fp32":
                    residual_len += layout["tail_pad"]
                # composed ZeRO-3 with a fp32 tail: the non-block tail
                # reduce-scatters at full precision through the
                # differentiable gather's transpose — no tail residual
                # slice.  grad_comm_tail routes it through the quantized
                # sync instead, with its own error-feedback slice laid
                # out after the bucket slices (the stages-0-2 layout).
            else:
                total = sum(int(np.prod(s.shape))
                            for s in shapes.values())
                residual_len = padded_size(total, n_shard, grad.block)

    if multi:
        lowering = "composed"
    elif probe is not None:
        lowering = "probe"
    elif grad is not None:
        lowering = "bucket" if grad.buckets > 1 else "quant_mono"
    elif gather is not None:
        lowering = "prefetch"
    else:
        lowering = "plain"
    return Schedule(gather=gather, grad=grad, probe=probe,
                    lowering=lowering, layout=layout,
                    residual_len=residual_len, hpz_geom=geom,
                    auto_plan=auto_plan)


# ---------------------------------------------------------------------------
# step executors — legacy single-slot lowerings (moved from engine.py,
# traced programs unchanged: the pre-scheduler HLO pins hold)
# ---------------------------------------------------------------------------

def monolithic_quant_step(eng, state, idx, targets, rng, scale):
    """The grad_comm != "fp32" gradient phase (quant_mono lowering):
    local grads + explicit quantized collectives inside a shard_map over
    the data axis (parallel/comm.py module docstring for the schedule).

    The model replays with pctx=None — each device sees its batch
    shard and the full (replicated) params, exactly the SingleDevice
    forward — so no sharding constraint inside the manual region
    (the MoE pure-DP dispatch contract).  Microbatches accumulate
    LOCALLY and sync once: quantizing every microbatch would compound
    rounding error accum_steps-fold and multiply the collectives.

    Returns (loss scaled+replicated, grads reduced/UNSCALED in param
    dtypes, new (n, pad) residual or None)."""
    from . import comm as qcomm

    n = eng.n_shard
    mode = eng.grad_comm
    block = eng.grad_comm_block
    inner = eng.grad_comm_groups
    accum = eng.accum_steps
    params = state.params
    residual = state.grad_residual
    model = eng.model
    # stochastic-rounding stream (int8): fresh per step via the
    # optimizer counter, decorrelated per device inside the region
    qkey = None
    if mode == "int8":
        qkey = jax.random.fold_in(
            jax.random.PRNGKey(0x6C51), state.opt_state["step"]
        )
    has_res, has_rng = residual is not None, rng is not None
    has_qk, has_sc = qkey is not None, scale is not None

    def local(p, ix, tg, *rest):
        rest = list(rest)
        res = rest.pop(0) if has_res else None
        r = rest.pop(0) if has_rng else None
        qk = rest.pop(0) if has_qk else None
        sc = rest.pop(0) if has_sc else None
        di = jax.lax.axis_index("data")
        if r is not None:
            # per-device fold: masks stay independent across batch
            # shards (the GSPMD path draws one global mask stream)
            r = jax.random.fold_in(r, di)
        if qk is not None:
            qk = jax.random.fold_in(qk, di)

        def lloss(p_, ix_, tg_, r_):
            kw = {"rng": r_} if r_ is not None else {}
            loss = model.apply(p_, ix_, tg_, pctx=None, **kw)
            return loss * sc if sc is not None else loss

        if accum == 1:
            loss_l, g = jax.value_and_grad(lloss)(p, ix, tg, r)
        else:
            def body(carry, mb):
                al, ag = carry
                ix_, tg_, mb_i = mb
                mb_r = (jax.random.fold_in(r, mb_i)
                        if r is not None else None)
                l, g_ = jax.value_and_grad(lloss)(p, ix_, tg_, mb_r)
                ag = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32), ag, g_
                )
                return (al + l, ag), None

            zg = jax.tree.map(
                lambda q: jnp.zeros(q.shape, jnp.float32), p
            )
            (loss_l, g), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zg),
                (ix, tg, jnp.arange(accum)),
            )
            loss_l = loss_l / accum
            g = jax.tree.map(
                lambda a, q: (a / accum).astype(q.dtype), g, p
            )
        if sc is not None:
            # unscale BEFORE the quantized sync: the residual must
            # carry true gradient units or a dynamic-scale change
            # between steps corrupts the compensation
            g = jax.tree.map(
                lambda x: (x.astype(jnp.float32)
                           * (1.0 / sc)).astype(x.dtype), g
            )
        res_row = res[0] if res is not None else None
        g_red, res_new = qcomm.quantized_grad_sync(
            g, res_row, "data", n, mode, block=block, rng=qk,
            inner=inner,
        )
        outs = [jax.lax.pmean(loss_l, "data"), g_red]
        if res is not None:
            outs.append(res_new[None])
        return tuple(outs)

    pspec = jax.tree.map(lambda _: P(), params)
    bspec = P(None, "data") if accum > 1 else P("data")
    in_specs = [pspec, bspec, bspec]
    args = [params, idx, targets]
    for cond, spec, val in (
        (has_res, P("data"), residual), (has_rng, P(), rng),
        (has_qk, P(), qkey), (has_sc, P(), scale),
    ):
        if cond:
            in_specs.append(spec)
            args.append(val)
    out_specs = [P(), jax.tree.map(lambda _: P(), params)]
    if has_res:
        out_specs.append(P("data"))
    out = jax.shard_map(
        local, mesh=eng.mesh, in_specs=tuple(in_specs),
        out_specs=tuple(out_specs), check_vma=False,
    )(*args)
    if has_res:
        return out
    return out[0], out[1], None


def bucketed_step(eng, state, idx, targets, rng, scale):
    """The grad_buckets > 1 gradient phase (bucket lowering): per-bucket
    release inside the backward scan (GradBucketTap).

    Like monolithic_quant_step, everything runs inside a shard_map
    over the data axis with the model replayed pctx=None (replicated
    params, local batch shard).  The K layer buckets reduce INSIDE
    the backward scan body — the tap's custom_vjp emits each bucket's
    collective as soon as that bucket's grads are final, while
    earlier buckets' backward compute is still in flight for the
    scheduler to hide the wire behind.  The non-block tail
    (wte/wpe/ln_f/lm_head) reduces once after value_and_grad: its
    grads finalize only when the whole backward is over (wte last of
    all), so there is no window to chase.

    grad_comm="fp32" buckets pmean in compute dtype (what the GSPMD
    all-reduce moves — comm_report round-4 finding); int8/fp8 buckets
    run the quantized schedule with per-bucket error-feedback
    residual slices laid out [b0 | ... | bK-1 | tail] in
    TrainState.grad_residual (the new residual is smuggled out of the
    backward as the tap's cotangent for the slice that rode in).
    Microbatches accumulate LOCALLY and the buckets fire only on the
    final microbatch — the accumulated prefix rides into the taps as
    the "acc" extra, so the one collective per bucket reduces the
    full mean gradient.

    Returns (loss scaled+replicated, grads reduced/UNSCALED in param
    dtypes, new (n, pad) residual or None)."""
    from . import comm as qcomm

    n = eng.n_shard
    mode = eng.grad_comm
    blk = eng.grad_comm_block
    inner = eng.grad_comm_groups
    accum = eng.accum_steps
    kb = eng.grad_buckets
    lay = eng._bucket_layout
    bpad = lay["bucket_pad"]
    lb = lay["layers_per_bucket"]
    tail_names = lay["tail_names"]
    params = state.params
    residual = state.grad_residual
    model = eng.model
    cd = getattr(
        getattr(model, "config", None), "compute_dtype", jnp.float32
    )
    qkey = None
    if mode == "int8":
        qkey = jax.random.fold_in(
            jax.random.PRNGKey(0x6C51), state.opt_state["step"]
        )
    has_res, has_rng = residual is not None, rng is not None
    has_qk, has_sc = qkey is not None, scale is not None

    def local(p, ix, tg, *rest):
        rest = list(rest)
        res = rest.pop(0) if has_res else None
        r = rest.pop(0) if has_rng else None
        qk = rest.pop(0) if has_qk else None
        sc = rest.pop(0) if has_sc else None
        di = jax.lax.axis_index("data")
        if r is not None:
            r = jax.random.fold_in(r, di)
        if qk is not None:
            qk = jax.random.fold_in(qk, di)
        res_row = res[0] if res is not None else None
        bres = res_row[: kb * bpad] if res_row is not None else None
        tres = res_row[kb * bpad:] if res_row is not None else None
        bkeys = tkey = None
        if qk is not None:
            keys = jax.random.split(qk, kb + 1)
            # per-bucket stochastic-rounding keys ride through the tap
            # bitcast to f32 (integer tap inputs would need float0
            # cotangents); the tail keeps its key directly
            bkeys = jax.lax.bitcast_convert_type(
                keys[:kb], jnp.float32
            )
            tkey = keys[kb]

        def bucket_reduce(g, ex):
            """Tap backward: ONE bucket's collective, emitted inside
            the backward scan body."""
            ex_cot = {}
            gf = jax.tree.map(lambda a: a.astype(jnp.float32), g)
            if "acc" in ex:
                # final microbatch: fold in the locally-accumulated
                # prefix so the single sync reduces the full mean grad
                gf = jax.tree.map(
                    lambda a, b: (a + b) / accum, gf, ex["acc"]
                )
                ex_cot["acc"] = jax.tree.map(
                    jnp.zeros_like, ex["acc"]
                )
            if "scale" in ex:
                # unscale BEFORE the sync: the residual must carry
                # true gradient units (the monolithic_quant_step
                # rule).  The scale rides the extras rather than the
                # closure — a custom_vjp bwd rule must not capture
                # tracers
                gf = jax.tree.map(
                    lambda a: a * (1.0 / ex["scale"]), gf
                )
                ex_cot["scale"] = jnp.zeros_like(ex["scale"])
            key = None
            if "rng" in ex:
                key = jax.lax.bitcast_convert_type(
                    ex["rng"], jnp.uint32
                )
                ex_cot["rng"] = jnp.zeros_like(ex["rng"])
            if mode == "fp32":
                # compute-dtype pmean: the same bytes the GSPMD
                # all-reduce moves (it commutes the reduction with
                # the grad's f32 cast — comm_report round-4)
                red = jax.tree.map(
                    lambda a, o: jax.lax.pmean(
                        a.astype(o.dtype), "data"
                    ), gf, g,
                )
            else:
                red, new_r = qcomm.quantized_grad_sync(
                    gf, ex.get("res"), "data", n, mode, block=blk,
                    rng=key, inner=inner,
                )
                if "res" in ex:
                    ex_cot["res"] = new_r
            red = jax.tree.map(
                lambda a, o: a.astype(o.dtype), red, g
            )
            return red, ex_cot

        def tapped_loss(p_, bres_, ix_, tg_, r_, acc=None):
            extras = {}
            if bres_ is not None:
                extras["res"] = bres_.reshape(kb, bpad)
            if acc is not None:
                extras["acc"] = acc
            if bkeys is not None:
                extras["rng"] = bkeys
            if sc is not None:
                extras["scale"] = jnp.full((kb,), sc, jnp.float32)
            tap = GradBucketTap(kb, bucket_reduce, extras)
            kw = {"rng": r_} if r_ is not None else {}
            loss = model.apply(
                p_, ix_, tg_, pctx=None, sched=tap, **kw
            )
            return loss * sc if sc is not None else loss

        def run_final(ix_, tg_, r_, acc=None):
            if bres is not None:
                loss_l, (gp, new_b) = jax.value_and_grad(
                    tapped_loss, argnums=(0, 1)
                )(p, bres, ix_, tg_, r_, acc)
            else:
                loss_l, gp = jax.value_and_grad(tapped_loss)(
                    p, None, ix_, tg_, r_, acc
                )
                new_b = None
            return loss_l, gp, new_b

        if accum == 1:
            loss_l, gp, new_bres = run_final(ix, tg, r)
        else:
            def body(carry, mb):
                al, ag = carry
                ix_, tg_, mb_i = mb
                mb_r = (jax.random.fold_in(r, mb_i)
                        if r is not None else None)

                def plain(p_, ix2, tg2, r2):
                    kw = {"rng": r2} if r2 is not None else {}
                    loss = model.apply(p_, ix2, tg2, pctx=None, **kw)
                    return loss * sc if sc is not None else loss

                l, g_ = jax.value_and_grad(plain)(p, ix_, tg_, mb_r)
                ag = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32), ag, g_
                )
                return (al + l, ag), None

            zg = jax.tree.map(
                lambda q: jnp.zeros(q.shape, jnp.float32), p
            )
            (al, ag), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zg),
                (ix[:-1], tg[:-1], jnp.arange(accum - 1)),
            )
            # accumulated h.* prefix, chunked (K, L/K, ...) under the
            # STACKED-tree keys the taps see
            acc_blocks = {
                nm[len("h."):]: ag[nm].reshape(
                    (kb, lb) + ag[nm].shape[1:]
                )
                for nm in ag if nm.startswith("h.")
            }
            mb_r = (jax.random.fold_in(r, accum - 1)
                    if r is not None else None)
            loss_f, gp, new_bres = run_final(
                ix[-1], tg[-1], mb_r, acc=acc_blocks
            )
            loss_l = (al + loss_f) / accum
            gp = dict(gp)
            for nm in tail_names:
                # the taps folded the prefix in for h.*; the tail
                # leaves get it here, before their own sync below
                gp[nm] = (
                    (ag[nm] + gp[nm].astype(jnp.float32)) / accum
                ).astype(gp[nm].dtype)

        # tail bucket: one sync after the backward completes
        tail = {
            nm: gp[nm].astype(jnp.float32) for nm in tail_names
        }
        if sc is not None:
            tail = jax.tree.map(lambda a: a * (1.0 / sc), tail)
        if mode == "fp32":
            tail_red = jax.tree.map(
                lambda a: jax.lax.pmean(a.astype(cd), "data"), tail
            )
            new_tres = None
        else:
            tail_red, new_tres = qcomm.quantized_grad_sync(
                tail, tres, "data", n, mode, block=blk, rng=tkey,
                inner=inner,
            )
        gp = dict(gp)
        for nm in tail_names:
            gp[nm] = tail_red[nm]
        grads = jax.tree.map(
            lambda a, q: a.astype(q.dtype), gp, params
        )
        outs = [jax.lax.pmean(loss_l, "data"), grads]
        if has_res:
            outs.append(jnp.concatenate([new_bres, new_tres])[None])
        return tuple(outs)

    pspec = jax.tree.map(lambda _: P(), params)
    bspec = P(None, "data") if accum > 1 else P("data")
    in_specs = [pspec, bspec, bspec]
    args = [params, idx, targets]
    for cond, spec, val in (
        (has_res, P("data"), residual), (has_rng, P(), rng),
        (has_qk, P(), qkey), (has_sc, P(), scale),
    ):
        if cond:
            in_specs.append(spec)
            args.append(val)
    out_specs = [P(), jax.tree.map(lambda _: P(), params)]
    if has_res:
        out_specs.append(P("data"))
    out = jax.shard_map(
        local, mesh=eng.mesh, in_specs=tuple(in_specs),
        out_specs=tuple(out_specs), check_vma=False,
    )(*args)
    if has_res:
        return out
    return out[0], out[1], None


# ---------------------------------------------------------------------------
# the composed lowering: ONE custom_vjp, every slot in one scan program
# ---------------------------------------------------------------------------

def composed_step(eng, state, idx, targets, rng, scale):
    """Merged-schedule gradient phase: every declared slot emitted into
    ONE forward + remat-backward scan pair inside a shard_map manual
    region over the data axis.

    Structure (all explicit — no GSPMD-implicit collectives inside):

      top level   stacked compute tree derived from the f32 masters via
                  jax.vjp(model.stacked_compute_params, params) — cast /
                  fp8-quantize once per step, pullback applied to the
                  released grads at the end (the 1F1B seam pattern).
      region      ZeRO-3: stacked + tail leaves enter SHARDED (each rank
                  its slice); stages 0-2: replicated.  The non-block
                  tail gathers through a DIFFERENTIABLE lax.all_gather,
                  so its grads come back pre-reduce-scattered via the
                  transpose (ZeRO-3) or release explicitly (stages 0-2).
      fwd scan    nested buckets x layers; body k issues layer
                  k+(prefetch-1)'s explicit all-gather (intra-slice
                  under hpZ, from the secondary partition built by ONE
                  top-of-region inter-slice gather), computes the block
                  (health-tapped when the probe slot is on), stashes the
                  layer input (plain remat stash).
      bwd scan    reverse nested scans: recompute each block from the
                  stash, prefetch reverse gathers, accumulate per-layer
                  dW in f32, and at each bucket boundary release the
                  bucket's collective (fp32 pmean or the int8/fp8
                  error-fed quantized schedule) INSIDE the outer scan
                  body — loop-resident grad wire next to loop-resident
                  gather wire, the full-compose acceptance.  Probe
                  cotangents collect as scan ys.  Under ZeRO-3 the
                  released full grads slice back to this rank's
                  canonical shard so the optimizer stays global ZeRO-3.

    Returns (loss, grads [param dtypes; sharded under ZeRO-3],
    new residual or None, probe stats (L, 4) or None)."""
    sched = eng._schedule
    model = eng.model
    mesh = eng.mesh
    n = eng.n_shard
    ax = "data"
    gather = sched.gather
    grad = sched.grad
    probe_on = sched.probe is not None
    stage3 = eng.stage >= 3
    cfgm = getattr(model, "config", None)
    L = int(getattr(cfgm, "n_layer"))
    dropout_p = float(getattr(cfgm, "dropout", 0.0) or 0.0)
    kb = grad.buckets if grad is not None else 1
    lb = L // kb
    mode = grad.mode if grad is not None else "fp32"
    blk = grad.block if grad is not None else DEFAULT_BLOCK
    # 2-hop hierarchical release: every quantized sync below (bucket,
    # quantized tail, stage<3 tail) passes the SAME inner split down to
    # quantized_grad_sync — the composed counterpart of the monolithic
    # lowering's grad_comm_groups schedule
    inner = grad.groups if grad is not None else None
    lay = sched.layout
    bpad = lay["bucket_pad"] if lay is not None else 0
    tail_names = sorted(nm for nm in state.params
                        if not nm.startswith("h."))
    look = (gather.prefetch - 1) if gather is not None else 0
    hpz = bool(gather is not None and gather.hpz)
    if hpz:
        intra, inter, ici, n_gran = sched.hpz_geom
    else:
        intra = inter = None
        ici = n_gran = 1
    # quantized tail release (ZeRO-3 only — build_schedule validated);
    # fp32 keeps the differentiable gather's transpose byte-identical
    tmode = grad.tail_mode if grad is not None else "fp32"
    tail_q = stage3 and tmode != "fp32"
    # hpZ rebuild codec (qwZ): fp32 = compute-dtype passthrough
    hq = gather.hpz_mode if hpz else "fp32"

    params = state.params
    residual = state.grad_residual
    # masters -> compute-dtype stacked tree at TOP level (cast /
    # fp8-quantize once per step, logical GSPMD semantics — global absmax
    # scales even when the shard axis crosses the reduced dims); the
    # pullback maps released stacked cotangents onto the f32 masters
    stacked_full, stacked_vjp = jax.vjp(
        model.stacked_compute_params, params
    )
    fkeys = sorted(stacked_full)  # all float (ints join inside: dropout)
    rel_keys = [nm for nm in fkeys if not nm.endswith("#scale")]
    sdtypes = {nm: stacked_full[nm].dtype for nm in fkeys}

    # per-leaf data-shard dim in the STACKED (L, ...) array (None =
    # replicated at rest, nothing to gather / slice)
    def _spec_dim(spec):
        if spec is None:
            return None
        for i, a in enumerate(spec):
            if a == ax or (isinstance(a, tuple) and ax in a):
                return i
        return None

    sdim = {}
    st_spec = {}
    for nm in fkeys:
        spec = eng._shard_spec.get("h." + nm) if stage3 else None
        sdim[nm] = _spec_dim(spec)
        st_spec[nm] = (spec if spec is not None and sdim[nm] is not None
                       else P())
    tdim = {}
    t_spec = {}
    for nm in tail_names:
        spec = eng._param_spec_rest.get(nm)
        tdim[nm] = _spec_dim(spec) if stage3 else None
        t_spec[nm] = spec if stage3 and spec is not None else P()
    tailp = {nm: params[nm] for nm in tail_names}

    qkey = None
    if mode == "int8" or (tail_q and tmode == "int8"):
        qkey = jax.random.fold_in(
            jax.random.PRNGKey(0x6C51), state.opt_state["step"]
        )
    has_res = residual is not None
    has_rng = rng is not None
    has_qk = qkey is not None
    has_sc = scale is not None
    block_fn = model.block_fn(None)
    # honor the model's scan_unroll on the inner layer scans (the legacy
    # bucket lowering does via GradBucketTap.scan; a gather slot already
    # forces scan_unroll == 1 at build_schedule), clamped to the
    # per-bucket scan length
    _u = getattr(cfgm, "scan_unroll", 1)
    unroll = lb if _u is True else max(1, min(int(_u or 1), lb))

    def local(sf, tp, ix, tg, *rest):
        rest = list(rest)
        res = rest.pop(0) if has_res else None
        r = rest.pop(0) if has_rng else None
        qk = rest.pop(0) if has_qk else None
        sc = rest.pop(0) if has_sc else None
        di = jax.lax.axis_index(ax)
        if r is not None:
            # per-device fold: masks stay independent across batch
            # shards (the explicit-schedule convention)
            r = jax.random.fold_in(r, di)
        if qk is not None:
            qk = jax.random.fold_in(qk, di)
        res_row = res[0] if res is not None else None
        bres = res_row[: kb * bpad] if res_row is not None else None
        tres = res_row[kb * bpad:] if (res_row is not None
                                       and (not stage3 or tail_q)) \
            else None
        bkeys = tkey = None
        if qk is not None:
            keys_q = jax.random.split(qk, kb + 1)
            bkeys = jax.lax.bitcast_convert_type(
                keys_q[:kb], jnp.float32
            )
            tkey = keys_q[kb]
        dkeys = None
        emb_key = None
        if r is not None and dropout_p:
            dk = jax.random.split(r, L + 1)
            emb_key = dk[0]
            dkeys = jax.lax.bitcast_convert_type(dk[1:], jnp.float32)
        si = {"dropout_rng": dkeys} if dkeys is not None else {}
        sidt = {"dropout_rng": jnp.uint32}

        # ---- the ONE custom_vjp: merged fwd/bwd scan schedule ----------
        def slice_k(tree, k):
            return {
                nm: jax.lax.dynamic_index_in_dim(a, k, 0, keepdims=False)
                for nm, a in tree.items()
            }

        def int_slices(si_, k):
            return {
                nm: jax.lax.bitcast_convert_type(
                    jax.lax.dynamic_index_in_dim(
                        a, k, 0, keepdims=False), sidt[nm])
                for nm, a in si_.items()
            }

        def unperm(x, d):
            """Undo the (intra-position, granule) interleave of the hpZ
            two-stage gather: one local transpose restores canonical
            rank-ascending shard order."""
            s = x.shape
            x = x.reshape(
                s[:d] + (ici, n_gran, s[d] // (ici * n_gran)) + s[d + 1:]
            )
            x = jnp.swapaxes(x, d, d + 1)
            return x.reshape(s)

        def build_sec(sf_):
            """hpZ secondary partition: ONE inter-slice all-gather per
            leaf turns each rank's global 1/n shard into its slice's
            1/ici shard — the only DCN hop; every in-scan gather below
            then stays intra-slice.

            hpz_comm != "fp32" (qwZ, ZeRO++ arXiv:2306.10209): instead
            of compute-dtype leaves, ONE concatenated blockwise-
            quantized payload + its f32 scales cross the inter-slice
            hop (two gathers over the same groups), dequantized once
            per granule and split back per leaf — ~4x fewer rebuild
            DCN bytes under fp8.  Runs inside the custom_vjp forward
            only, so the weight rounding is straight-through for
            gradients (d_sf releases explicitly in the backward)."""
            out = {}
            if hq != "fp32":
                names = [nm for nm in sorted(sf_)
                         if sdim[nm] is not None]
                sizes = [int(np.prod(sf_[nm].shape)) for nm in names]
                for nm in sf_:
                    if sdim[nm] is None:
                        out[nm] = sf_[nm]
                if names:
                    flat = jnp.concatenate([
                        sf_[nm].astype(jnp.float32).reshape(-1)
                        for nm in names
                    ])
                    pad = -flat.shape[0] % DEFAULT_BLOCK
                    if pad:
                        flat = jnp.concatenate(
                            [flat, jnp.zeros((pad,), jnp.float32)])
                    # round-to-nearest (rng=None) even for int8: a
                    # deterministic weight replica per step — dither
                    # buys nothing without an error-feedback loop
                    q, s = quantize_blockwise(flat, hq, DEFAULT_BLOCK)
                    qg = jax.lax.all_gather(
                        as_wire(q), ax, axis_index_groups=inter)
                    sg = jax.lax.all_gather(
                        s.reshape(1, -1), ax,
                        axis_index_groups=inter, tiled=True)
                    vals = _dequant_rows(
                        from_wire(qg, hq),
                        sg.reshape(n_gran, -1))  # (n_gran, P) f32
                    off = 0
                    for nm, sz in zip(names, sizes):
                        v = sf_[nm]
                        d = sdim[nm]
                        seg = vals[:, off:off + sz].reshape(
                            (n_gran,) + v.shape)
                        out[nm] = jnp.concatenate(
                            [seg[i] for i in range(n_gran)], axis=d
                        ).astype(v.dtype)
                        off += sz
                return out
            for nm, v in sf_.items():
                d = sdim[nm]
                if d is None:
                    out[nm] = v
                    continue
                out[nm] = jax.lax.all_gather(
                    v, ax, axis=d, tiled=True,
                    axis_index_groups=inter)
            return out

        def gather_k(src, k):
            """Layer k's full weights from the gather source (the
            sharded stacked tree, or the hpZ secondary partition)."""
            w = slice_k(src, k)
            if gather is None:
                return w
            out = {}
            for nm, v in w.items():
                d = sdim[nm]
                if d is None:
                    out[nm] = v
                    continue
                # the layer axis is sliced off: the shard dim shifts -1
                g = jax.lax.all_gather(
                    v, ax, axis=d - 1, tiled=True,
                    axis_index_groups=intra)
                out[nm] = unperm(g, d - 1) if hpz else g
            return out

        def shard_slice(nm, g, lead=1):
            """This rank's canonical 1/n shard of a released full
            gradient — keeps the optimizer layout global ZeRO-3
            whatever the gather slot did (hpZ included).  `lead` is the
            number of leading stack dims on `g` standing in for the
            sliced-off layer axis (1 for (lb, ...) bucket stacks, 0 for
            a single layer's dW)."""
            d = sdim[nm]
            if d is None:
                return g
            d = d - 1 + lead
            size = g.shape[d] // n
            return jax.lax.dynamic_slice_in_dim(g, di * size, size, d)

        def init_buf(src, idxs):
            slots = [gather_k(src, i) for i in idxs]
            return jax.tree.map(lambda *xs: jnp.stack(xs), *slots)

        def shift_in(buf, new):
            return jax.tree.map(
                lambda b, nw: jnp.concatenate([b[1:], nw[None]]),
                buf, new)

        def fwd_pass(sf_, si_, probe_, x0, stash):
            src = build_sec(sf_) if hpz else sf_
            buf = init_buf(src, list(range(look))) if look else ()

            def body_inner(carry, k):
                x, buf = carry
                if look:
                    # issue layer k+look's gather FIRST; nothing in
                    # this body consumes it, so its wire hides behind
                    # block(k)
                    nxt = gather_k(src, jnp.minimum(k + look, L - 1))
                    w = jax.tree.map(lambda b: b[0], buf)
                    buf = shift_in(buf, nxt)
                else:
                    w = gather_k(src, k)
                bp = dict(w, **int_slices(si_, k))
                if probe_ is not None:
                    bp["health_probe"] = jax.lax.dynamic_index_in_dim(
                        probe_, k, 0, keepdims=False)
                y = block_fn(x, bp)
                return (y, buf), (x if stash else None)

            def body_outer(carry, ks):
                return jax.lax.scan(body_inner, carry, ks,
                                    unroll=unroll)

            (y, _), xs = jax.lax.scan(
                body_outer, (x0, buf),
                jnp.arange(L).reshape(kb, lb))
            return y, xs, src

        def make_run():
            @jax.custom_vjp
            def run(sf_, si_, ops_, x0):
                y, _, _ = fwd_pass(sf_, si_, ops_.get("probe"), x0,
                                   stash=False)
                return y

            def run_fwd(sf_, si_, ops_, x0):
                y, xs, src = fwd_pass(sf_, si_, ops_.get("probe"), x0,
                                      stash=True)
                # residuals: sharded stacked tree + the (kb, lb) layer-
                # input stash (plain remat) + the gather source — sf
                # itself when not hpZ (free), the secondary partition
                # under hpZ (the deliberate per-slice replica HBM cost)
                return y, (sf_, si_, ops_, xs, src)

            def run_bwd(resid, dy):
                sf_, si_, ops_, xs, src = resid
                probe_ = ops_.get("probe")
                buf = (init_buf(src, [L - 1 - i for i in range(look)])
                       if look else ())

                def body_inner(carry, inp):
                    dx, buf = carry
                    x_k, k = inp
                    if look:
                        nxt = gather_k(src, jnp.maximum(k - look, 0))
                        w = jax.tree.map(lambda b: b[0], buf)
                        buf = shift_in(buf, nxt)
                    else:
                        w = gather_k(src, k)
                    ints = int_slices(si_, k)
                    wf = dict(w)
                    if probe_ is not None:
                        wf["health_probe"] = \
                            jax.lax.dynamic_index_in_dim(
                                probe_, k, 0, keepdims=False)

                    def f(x_, wd):
                        return block_fn(x_, dict(wd, **ints))

                    # remat: recompute layer k from the stashed input
                    _, vjp = jax.vjp(f, x_k, wf)
                    dx_new, dwf = vjp(dx)
                    dprobe_k = (dwf.pop("health_probe")
                                if probe_ is not None else None)
                    if grad is not None:
                        # accumulate in f32; the bucket boundary below
                        # runs the ONE collective per bucket
                        dws = {nm: dwf[nm].astype(jnp.float32)
                               for nm in rel_keys}
                    else:
                        # no grad slot: per-layer fp32 release keeps
                        # the grad wire in-loop like the GSPMD path
                        dws = {}
                        for nm in rel_keys:
                            g32 = dwf[nm].astype(jnp.float32)
                            if "scale" in ops_:
                                g32 = g32 * (1.0 / ops_["scale"])
                            red = jax.lax.pmean(
                                g32.astype(dwf[nm].dtype), ax)
                            dws[nm] = shard_slice(
                                nm, red, lead=0).astype(sdtypes[nm])
                    ys = (dws, dprobe_k) if probe_ is not None \
                        else (dws,)
                    return (dx_new, buf), ys

                def body_outer(carry, inp):
                    xs_b, ks_b, res_b, key_b = inp
                    carry, ys = jax.lax.scan(
                        body_inner, carry, (xs_b, ks_b), reverse=True,
                        unroll=unroll)
                    dws_b = ys[0]
                    dprobe_b = ys[1] if probe_ is not None else None
                    new_res_b = jnp.zeros((0,), jnp.float32)
                    if grad is not None:
                        # bucket release: one collective, emitted inside
                        # this outer scan body — the backward for buckets
                        # k-1..0 is still ahead, so the scheduler can
                        # hide the wire (the grad slot's point)
                        gf = {nm: dws_b[nm] for nm in rel_keys}
                        if "scale" in ops_:
                            gf = jax.tree.map(
                                lambda a: a * (1.0 / ops_["scale"]), gf
                            )
                        key = None
                        if key_b is not None:
                            key = jax.lax.bitcast_convert_type(
                                key_b, jnp.uint32)
                        if mode == "fp32":
                            red = {
                                nm: jax.lax.pmean(
                                    gf[nm].astype(sdtypes[nm]), ax)
                                for nm in rel_keys
                            }
                        else:
                            red, new_res_b = quantized_grad_sync(
                                gf, res_b if "res" in ops_ else None,
                                ax, n, mode, block=blk, rng=key,
                                inner=inner,
                            )
                            if new_res_b is None:
                                new_res_b = jnp.zeros((0,), jnp.float32)
                        dws_b = {
                            nm: shard_slice(
                                nm, red[nm].astype(jnp.float32)
                            ).astype(sdtypes[nm])
                            for nm in rel_keys
                        }
                    outs = (dws_b, dprobe_b, new_res_b)
                    return carry, outs

                ks = jnp.arange(L).reshape(kb, lb)
                res_rows = (ops_["res"] if "res" in ops_
                            else jnp.zeros((kb, 0), jnp.float32))
                key_rows = (ops_["rng"] if "rng" in ops_
                            else None)
                inp = (xs, ks, res_rows,
                       key_rows if key_rows is not None
                       else jnp.zeros((kb, 0), jnp.float32))
                if key_rows is None:
                    # thread a dummy so the scan xs structure is static;
                    # body ignores it when the codec needs no key
                    def body_outer_nokey(carry, inp_):
                        xs_b, ks_b, res_b, _ = inp_
                        return body_outer(carry, (xs_b, ks_b, res_b,
                                                  None))
                    (dx, _), outs = jax.lax.scan(
                        body_outer_nokey, (dy, buf), inp, reverse=True)
                else:
                    (dx, _), outs = jax.lax.scan(
                        body_outer, (dy, buf), inp, reverse=True)
                dws_all, dprobe_all, new_res_all = outs
                d_sf = {}
                for nm in fkeys:
                    if nm in dws_all:
                        a = dws_all[nm]
                        d_sf[nm] = a.reshape((L,) + a.shape[2:])
                    else:
                        # '#scale' leaves: stop-gradiented upstream by
                        # stacked_compute_params — zero, not released
                        d_sf[nm] = jnp.zeros_like(sf_[nm])
                d_ops = {}
                if "probe" in ops_:
                    d_ops["probe"] = dprobe_all.reshape(L, -1)
                if "res" in ops_:
                    d_ops["res"] = new_res_all
                if "rng" in ops_:
                    d_ops["rng"] = jnp.zeros_like(ops_["rng"])
                if "scale" in ops_:
                    d_ops["scale"] = jnp.zeros_like(ops_["scale"])
                d_si = jax.tree.map(jnp.zeros_like, si_)
                return d_sf, d_si, d_ops, dx.astype(x0_dtype)

            run.defvjp(run_fwd, run_bwd)
            return run

        x0_dtype = getattr(cfgm, "compute_dtype", jnp.float32)
        run = make_run()

        ops = {}
        if probe_on:
            ops["probe"] = jnp.zeros((L, LAYER_PROBE_WIDTH),
                                     jnp.float32)
        if bres is not None:
            ops["res"] = bres.reshape(kb, bpad)
        if bkeys is not None:
            ops["rng"] = bkeys
        if sc is not None:
            ops["scale"] = jnp.full((), sc, jnp.float32)

        def tail_full(tp_):
            if not stage3:
                return tp_
            out = {}
            for nm, v in tp_.items():
                d = tdim[nm]
                # DIFFERENTIABLE gather: the transpose (psum_scatter)
                # reduce-scatters the tail grads back to the shards
                out[nm] = (jax.lax.all_gather(v, ax, axis=d, tiled=True)
                           if d is not None else v)
            return out

        def make_qtail():
            """Quantized ZeRO-3 tail release (grad_comm_tail): the same
            forward gather as tail_full, but the transpose's implicit
            fp32 reduce-scatter is replaced by ONE explicit error-fed
            quantized sync of the full tail cotangents — the composed
            program's last fp32 grad collective, now on the codec.  The
            residual / rng / scale ride the `tex_` extras (a custom_vjp
            bwd rule must not capture tracers); the new residual exits
            as the residual's cotangent, the composed machine's
            standard trick (ops_["res"])."""
            @jax.custom_vjp
            def qtail(tp_, tex_):
                return tail_full(tp_)

            def qtail_fwd(tp_, tex_):
                return tail_full(tp_), (tex_,)

            def qtail_bwd(resid, dy):
                (tex_,) = resid
                inv_ = (1.0 / tex_["scale"]) if "scale" in tex_ else 1.0
                g32 = {nm: dy[nm].astype(jnp.float32) * inv_
                       for nm in tail_names}
                key = None
                if "rng" in tex_:
                    key = jax.lax.bitcast_convert_type(
                        tex_["rng"], jnp.uint32)
                red, new_tr = quantized_grad_sync(
                    g32, tex_.get("res"), ax, n, tmode, block=blk,
                    rng=key, inner=inner,
                )
                # mean full grads -> each rank's canonical 1/n shard
                # for the leaves the ZeRO layout shards; replicated
                # leaves (tdim None) keep the full mean — exactly the
                # fp32 release's psum/(inv/n) semantics
                di_ = jax.lax.axis_index(ax)
                d_tp = {}
                for nm, a in dy.items():
                    d = tdim[nm]
                    gr = red[nm]
                    if d is not None:
                        size = gr.shape[d] // n
                        gr = jax.lax.dynamic_slice_in_dim(
                            gr, di_ * size, size, d)
                    d_tp[nm] = gr.astype(a.dtype)
                d_tex = {}
                if "res" in tex_:
                    d_tex["res"] = new_tr
                if "rng" in tex_:
                    d_tex["rng"] = jnp.zeros_like(tex_["rng"])
                if "scale" in tex_:
                    d_tex["scale"] = jnp.zeros_like(tex_["scale"])
                return d_tp, d_tex

            qtail.defvjp(qtail_fwd, qtail_bwd)
            return qtail

        if tail_q:
            tex = {}
            if tres is not None:
                tex["res"] = tres
            if tkey is not None:
                tex["rng"] = jax.lax.bitcast_convert_type(
                    tkey, jnp.float32)
            if sc is not None:
                tex["scale"] = jnp.full((), sc, jnp.float32)
            qtail = make_qtail()

            def tapped_loss_qt(tp_, tex_, sf_, ops_, ix_, tg_):
                tf = qtail(tp_, tex_)
                x = model.embed(tf, ix_, None)
                if emb_key is not None:
                    from ..models.gpt2 import _dropout
                    x = _dropout(x, emb_key, dropout_p)
                y = run(sf_, si, ops_, x)
                loss = model.head(tf, y, tg_, None)
                return loss * sc if sc is not None else loss

            loss_l, (g_tail, d_tex, d_sf, g_ops) = jax.value_and_grad(
                tapped_loss_qt, argnums=(0, 1, 2, 3)
            )(tp, tex, sf, ops, ix, tg)
            # g_tail is final (mean, unscaled, sliced); the new tail
            # residual exits as the extras' cotangent
            new_tres = d_tex.get("res")
        else:
            def tapped_loss(tp_, sf_, ops_, ix_, tg_):
                tf = tail_full(tp_)
                x = model.embed(tf, ix_, None)
                if emb_key is not None:
                    from ..models.gpt2 import _dropout
                    x = _dropout(x, emb_key, dropout_p)
                y = run(sf_, si, ops_, x)
                loss = model.head(tf, y, tg_, None)
                return loss * sc if sc is not None else loss

            loss_l, (g_tail, d_sf, g_ops) = jax.value_and_grad(
                tapped_loss, argnums=(0, 1, 2)
            )(tp, sf, ops, ix, tg)

        # ---- tail release ------------------------------------------------
        if tail_q:
            pass  # released inside qtail's backward (above)
        elif stage3:
            # sharded leaves' grads arrived pre-reduce-scattered (the
            # all_gather transpose psums each shard); leaves the ZeRO
            # layout left REPLICATED at rest (tdim None — small norms /
            # biases whose dims the axis does not divide) never crossed
            # a gather, so their cotangent is still this rank's LOCAL
            # gradient and needs the explicit psum.  Both then: sum ->
            # mean, unscale.
            inv = (1.0 / sc) if sc is not None else 1.0
            out = {}
            for nm, a in g_tail.items():
                g32 = a.astype(jnp.float32)
                if tdim[nm] is None:
                    g32 = jax.lax.psum(g32, ax)
                out[nm] = (g32 * (inv / n)).astype(a.dtype)
            g_tail = out
            new_tres = None
        else:
            tail = {nm: g_tail[nm].astype(jnp.float32)
                    for nm in tail_names}
            if sc is not None:
                tail = jax.tree.map(lambda a: a * (1.0 / sc), tail)
            cd = getattr(cfgm, "compute_dtype", jnp.float32)
            if mode == "fp32":
                tail_red = jax.tree.map(
                    lambda a: jax.lax.pmean(a.astype(cd), ax), tail
                )
                new_tres = None
            else:
                tail_red, new_tres = quantized_grad_sync(
                    tail, tres, ax, n, mode, block=blk, rng=tkey,
                    inner=inner,
                )
            g_tail = {nm: tail_red[nm].astype(g_tail[nm].dtype)
                      for nm in tail_names}

        outs = [jax.lax.pmean(loss_l, ax), g_tail, d_sf]
        if probe_on:
            # local (batch-shard) sums -> the global numbers every rank
            # reports (the health_vector convention).  The backward ran
            # on the LOCAL batch-shard mean loss (n x the global-mean
            # cotangent per shard), so the dact sq-sum column carries
            # n^2 vs the plain probe lowering's global-loss convention —
            # normalized here so composed and single-slot engines report
            # the same LAYER_FIELDS numbers (non-finite counts and the
            # forward act columns are scale-free)
            stats = jax.lax.psum(g_ops["probe"], ax)
            stats = stats.at[:, 2].multiply(1.0 / (n * n))
            outs.append(stats)
        if has_res:
            new_row = g_ops["res"].reshape(-1)
            if new_tres is not None:
                new_row = jnp.concatenate([new_row, new_tres])
            outs.append(new_row[None])
        return tuple(outs)

    # ---- shard_map plumbing -------------------------------------------------
    st_in = {nm: st_spec[nm] for nm in fkeys}
    t_in = {nm: t_spec[nm] for nm in tail_names}
    bspec = P("data")
    in_specs = [st_in, t_in, bspec, bspec]
    args = [stacked_full, tailp, idx, targets]
    for cond, spec, val in (
        (has_res, P("data"), residual), (has_rng, P(), rng),
        (has_qk, P(), qkey), (has_sc, P(), scale),
    ):
        if cond:
            in_specs.append(spec)
            args.append(val)
    out_specs = [P(), t_in, st_in]
    if probe_on:
        out_specs.append(P())
    if has_res:
        out_specs.append(P("data"))
    out = jax.shard_map(
        local, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=tuple(out_specs), check_vma=False,
    )(*args)
    out = list(out)
    loss = out.pop(0)
    g_tail = out.pop(0)
    d_stacked = out.pop(0)
    layer_probe = out.pop(0) if probe_on else None
    new_residual = out.pop(0) if has_res else state.grad_residual

    # pull the released stacked cotangents back onto the f32 masters
    # (cast / fp8-quantize transpose; '#scale' zeros through the
    # stop_gradient) and merge the tail grads
    grads = stacked_vjp(d_stacked)[0]
    grads = dict(grads)
    for nm in tail_names:
        grads[nm] = g_tail[nm].astype(params[nm].dtype)
    grads = jax.tree.map(
        lambda g, q: g.astype(q.dtype), grads, params
    )
    return loss, grads, new_residual, layer_probe
