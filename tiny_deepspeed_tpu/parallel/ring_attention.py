# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Ring attention: causal attention over a sequence-sharded mesh axis.

ABSENT from the reference (SURVEY §2.20, §5.7: max context = block_size 1024,
no sequence/context parallelism of any kind) but first-class here: long
sequences shard over a "seq" mesh axis; each device holds a (B, H, T/n, Dh)
shard of Q/K/V and K/V blocks rotate around the ring via `ppermute` while
each device accumulates its queries' attention with an online (flash-style)
running max/sum softmax.  Communication rides ICI neighbor links — the
all-gather of full K/V never materializes, so attention memory stays O(T/n)
per device and context length scales linearly with the ring size.

Causality at block granularity: K/V blocks strictly *ahead* of the local
query block contribute nothing (masked), the diagonal block is lower-
triangular, blocks behind are unmasked.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG = -1e30  # finite -inf stand-in: avoids NaN from (-inf) - (-inf)


def ring_attention_local(q, k, v, *, axis_name: str, axis_size: int):
    """Per-shard body (call inside shard_map over `axis_name`).

    q, k, v: (B, H, Tl, Dh) local sequence shards.  Returns (B, H, Tl, Dh).
    """
    b, h, tl, d = q.shape
    scale = 1.0 / math.sqrt(d)
    my = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32)
    q_pos = my * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 0)

    o0 = jnp.zeros((b, h, tl, d), jnp.float32)
    l0 = jnp.zeros((b, h, tl, 1), jnp.float32)
    m0 = jnp.full((b, h, tl, 1), _NEG, jnp.float32)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, i):
        o, l, m, kc, vc = carry
        src = (my - i) % axis_size  # global block id of kc/vc
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = src * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 1)
        mask = q_pos >= k_pos  # (tl, tl) causal at global positions
        s = jnp.where(mask, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, l, m_new, kc, vc), None

    # remat the BODY: differentiating a scan stashes each step's residuals,
    # and this body's are the (Tl, Tl) score/probability matrices — at
    # T=32k/ring=8 that is axis_size x (B, H, 4096, 4096) f32, a ~26 GB
    # stack that defeats the O(T/n) memory claim (first seen on the
    # round-4 TPU-topology compile).  checkpoint saves only the step
    # inputs (the rotating K/V carries, O(n * Tl * d) total) and recomputes
    # scores in the backward — the standard ring-attention backward, which
    # re-runs the ring's ppermutes for the recompute.
    (o, l, _, _, _), _ = jax.lax.scan(
        jax.checkpoint(step), (o0, l0, m0, k, v), jnp.arange(axis_size)
    )
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = "seq",
                   batch_axis=None, head_axis=None):
    """shard_map entry: q/k/v (B, H, T, Dh) with T sharded over `seq_axis`
    (optionally B over `batch_axis` and H over `head_axis` — heads split
    across a tensor-parallel axis compose freely with the sequence ring)."""
    n = mesh.shape[seq_axis]
    spec = P(batch_axis, head_axis, seq_axis, None)
    fn = functools.partial(
        ring_attention_local, axis_name=seq_axis, axis_size=n
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
