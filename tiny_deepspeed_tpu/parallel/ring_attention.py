# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Ring attention: causal attention over a sequence-sharded mesh axis.

ABSENT from the reference (SURVEY §2.20, §5.7: max context = block_size 1024,
no sequence/context parallelism of any kind) but first-class here: long
sequences shard over a "seq" mesh axis; each device holds a (B, H, T/n, Dh)
shard of Q/K/V and K/V blocks rotate around the ring via `ppermute` while
each device accumulates its queries' attention with an online (flash-style)
softmax merge.  Communication rides ICI neighbor links — the all-gather of
full K/V never materializes, so attention memory stays O(T/n) per device and
context length scales linearly with the ring size.

Causality at chunk granularity: K/V chunks strictly *ahead* of the local
query chunk contribute nothing (skipped — no kernel launched), the diagonal
chunk is ordinary causal attention at local coordinates, chunks behind are
fully unmasked.

Two implementations share that structure:

  * TPU (round 5): the per-chunk local step runs the hand-written FA2
    Pallas kernel (ops/flash_fa2.py chunk entries — causal for the
    peeled diagonal, unmasked for interior chunks) under an explicit
    custom_vjp.  The forward merges per-chunk (o, lse) pairs in
    logsumexp space; the backward re-runs the ring calling the kernel's
    dq/dkv passes per chunk with the GLOBAL merged stats, rotating
    f32 dk/dv accumulators around the ring alongside K/V — the standard
    ring-attention backward.  Residuals are O(T/n) per device (q/k/v/o
    + one (BH, 1, Tl) lse), so the round-4 memory proof (T=65536 on 8
    chips) carries over with the chunk compute now MXU-tiled instead of
    VPU-bound jnp (round-4 verdict item 3).
  * elsewhere (CPU test mesh / shapes past the kernel's VMEM bound / the
    pipeline's partial-manual region, where a Pallas custom call cannot
    be auto-partitioned over the still-GSPMD data axis): the original
    jnp online-softmax scan, body rematerialized so differentiating it
    never stashes the (Tl, Tl) score matrices.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG = -1e30  # finite -inf stand-in: avoids NaN from (-inf) - (-inf)


def _ring_jnp(q, k, v, *, axis_name: str, axis_size: int):
    """jnp online-softmax ring body (the non-Pallas fallback path).

    ONE implementation for MHA and GQA: q folds to (B, KVH, G, Tl, Dh)
    — G=1 when the head counts match — so the rotating K/V always move
    at kv_heads (the same wire saving as the kernel path, in the
    fallback dialect) and there is a single scan body to maintain."""
    b, h, tl, d = q.shape
    hkv = k.shape[1]
    g = h // hkv
    o = _ring_jnp_gqa(q.reshape(b, hkv, g, tl, d), k, v,
                      axis_name=axis_name, axis_size=axis_size)
    return o.reshape(b, h, tl, d)


def _ring_jnp_gqa(qg, k, v, *, axis_name: str, axis_size: int):
    """Grouped-query jnp ring: qg (B, KVH, G, Tl, Dh), k/v (B, KVH, Tl,
    Dh) rotating unexpanded, online (flash-style) running max/sum
    softmax merged across chunks.

    The scan BODY is rematerialized: differentiating the scan would
    stash each step's (Tl, Tl)-per-head score/probability matrices — at
    T=32k/ring=8 a ~26 GB stack that defeats the O(T/n) memory claim
    (first seen on the round-4 TPU-topology compile).  checkpoint saves
    only the step inputs (the rotating K/V carries, O(n * Tl * d)
    total) and recomputes scores in the backward — the standard
    ring-attention backward, which re-runs the ring's ppermutes for the
    recompute."""
    b, hkv, g, tl, d = qg.shape
    scale = 1.0 / math.sqrt(d)
    my = jax.lax.axis_index(axis_name)

    qf = qg.astype(jnp.float32)
    q_pos = my * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 0)

    o0 = jnp.zeros((b, hkv, g, tl, d), jnp.float32)
    l0 = jnp.zeros((b, hkv, g, tl, 1), jnp.float32)
    m0 = jnp.full((b, hkv, g, tl, 1), _NEG, jnp.float32)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, i):
        o, l, m, kc, vc = carry
        src = (my - i) % axis_size
        s = jnp.einsum(
            "bkgqd,bktd->bkgqt", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = src * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 1)
        mask = q_pos >= k_pos
        s = jnp.where(mask[None, None, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask[None, None, None], jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.einsum(
            "bkgqt,bktd->bkgqd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, l, m_new, kc, vc), None

    (o, l, _, _, _), _ = jax.lax.scan(
        jax.checkpoint(step), (o0, l0, m0, k, v), jnp.arange(axis_size)
    )
    return (o / jnp.maximum(l, 1e-30)).astype(qg.dtype)


# ---------------------------------------------------------------------------
# FA2-kernel ring (TPU path)
# ---------------------------------------------------------------------------

def _rot(x, axis_name, axis_size):
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    return jax.lax.ppermute(x, axis_name, perm)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_fa2(q, k, v, axis_name, axis_size):
    """Kernel-backed ring attention on local (B, H, Tl, Dh) shards."""
    o, _ = _ring_fa2_fwd(q, k, v, axis_name, axis_size)
    return o


def _ring_fa2_fwd(q, k, v, axis_name, axis_size):
    from ..ops.flash_fa2 import fa2_chunk_fwd

    b, h, tl, d = q.shape
    kvh = k.shape[1]          # GQA: K/V rotate UNEXPANDED at kv_heads
    group = h // kvh
    bh = b * h
    qf = q.reshape(bh, tl, d)
    kf = k.reshape(b * kvh, tl, d)
    vf = v.reshape(b * kvh, tl, d)
    my = jax.lax.axis_index(axis_name)

    # peeled diagonal: global offsets equal -> plain causal at local coords
    o0, lse0 = fa2_chunk_fwd(qf, kf, vf, causal=True, group=group)
    o_run, lse_run = o0.astype(jnp.float32), lse0  # (bh,tl,d), (bh,1,tl)

    def step(carry, i):
        o_run, lse_run, kc, vc = carry
        kc = _rot(kc, axis_name, axis_size)
        vc = _rot(vc, axis_name, axis_size)
        # after i rotations this device holds chunk (my - i) mod n; it
        # contributes iff my - i >= 0 (a strictly-behind chunk — fully
        # unmasked); wrapped-around chunks are SKIPPED, no kernel run
        # (the jnp path spends a full masked matmul on them)

        def compute(_):
            o_c, lse_c = fa2_chunk_fwd(qf, kc, vc, causal=False,
                                       group=group)
            return o_c.astype(jnp.float32), lse_c

        def skip(_):
            return (jnp.zeros((bh, tl, d), jnp.float32),
                    jnp.full((bh, 1, tl), _NEG, jnp.float32))

        o_c, lse_c = jax.lax.cond(i <= my, compute, skip, None)
        # logsumexp-space merge of chunk-normalized partials
        lse_new = jnp.logaddexp(lse_run, lse_c)
        w_run = jnp.exp(lse_run - lse_new).swapaxes(1, 2)  # (bh, tl, 1)
        w_c = jnp.exp(lse_c - lse_new).swapaxes(1, 2)
        return (o_run * w_run + o_c * w_c, lse_new, kc, vc), None

    if axis_size > 1:
        (o_run, lse_run, _, _), _ = jax.lax.scan(
            step, (o_run, lse_run, kf, vf), jnp.arange(1, axis_size))

    o = o_run.astype(q.dtype).reshape(b, h, tl, d)
    return o, (q, k, v, o, lse_run)


def _ring_fa2_bwd(axis_name, axis_size, res, g):
    from ..ops.flash_fa2 import fa2_chunk_dkv, fa2_chunk_dq

    q, k, v, o, lse = res
    b, h, tl, d = q.shape
    kvh = k.shape[1]
    group = h // kvh
    bh = b * h
    flat = lambda x: x.reshape(bh, tl, d)
    qf, of, do = flat(q), flat(o), flat(g)
    kf = k.reshape(b * kvh, tl, d)
    vf = v.reshape(b * kvh, tl, d)
    di = jnp.sum(do.astype(jnp.float32) * of.astype(jnp.float32),
                 axis=-1)[:, None, :]  # (bh, 1, tl) f32
    my = jax.lax.axis_index(axis_name)

    # diagonal contributions, then re-run the ring with the k/v chunks
    # AND their f32 dk/dv accumulators rotating together: the chunk on a
    # device and the gradient being accumulated FOR that chunk travel as
    # one, so after a full cycle each device holds its own chunk's
    # complete dk/dv (comm = 2x the forward's k/v bytes, the f32 ledger
    # price of exact accumulation — all of it at kv_heads under GQA).
    dq0 = fa2_chunk_dq(qf, kf, vf, do, lse, di, causal=True, group=group)
    dk0, dv0 = fa2_chunk_dkv(qf, kf, vf, do, lse, di, causal=True,
                             group=group)
    dq_run = dq0.astype(jnp.float32)
    dka, dva = dk0.astype(jnp.float32), dv0.astype(jnp.float32)

    def step(carry, i):
        kc, vc, dka, dva, dq_run = carry
        kc = _rot(kc, axis_name, axis_size)
        vc = _rot(vc, axis_name, axis_size)
        dka = _rot(dka, axis_name, axis_size)
        dva = _rot(dva, axis_name, axis_size)

        def compute(_):
            dq_c = fa2_chunk_dq(qf, kc, vc, do, lse, di, causal=False,
                                group=group)
            dk_c, dv_c = fa2_chunk_dkv(qf, kc, vc, do, lse, di,
                                       causal=False, group=group)
            return (dq_c.astype(jnp.float32), dk_c.astype(jnp.float32),
                    dv_c.astype(jnp.float32))

        def skip(_):
            zkv = jnp.zeros((b * kvh, tl, d), jnp.float32)
            return jnp.zeros((bh, tl, d), jnp.float32), zkv, zkv

        dq_c, dk_c, dv_c = jax.lax.cond(i <= my, compute, skip, None)
        return (kc, vc, dka + dk_c, dva + dv_c, dq_run + dq_c), None

    if axis_size > 1:
        (_, _, dka, dva, dq_run), _ = jax.lax.scan(
            step, (kf, vf, dka, dva, dq_run), jnp.arange(1, axis_size))
        # the accumulators sit one rotation short of home: finish the cycle
        dka = _rot(dka, axis_name, axis_size)
        dva = _rot(dva, axis_name, axis_size)

    return (dq_run.astype(q.dtype).reshape(b, h, tl, d),
            dka.astype(k.dtype).reshape(b, kvh, tl, d),
            dva.astype(v.dtype).reshape(b, kvh, tl, d))


_ring_fa2.defvjp(_ring_fa2_fwd, _ring_fa2_bwd)


def ring_attention_local(q, k, v, *, axis_name: str, axis_size: int,
                         allow_kernel: bool = True):
    """Per-shard body (call inside shard_map over `axis_name`).

    q, k, v: (B, H, Tl, Dh) local sequence shards.  Returns (B, H, Tl, Dh).
    Routes to the FA2-kernel ring on TPU when the chunk's K/V panels fit
    the kernel's VMEM budget (Tl*Dh within the FA2_MAX_T bound — T=65536
    on an 8-ring is Tl=8192, comfortably inside); jnp fallback elsewhere.
    `allow_kernel=False` forces the jnp body — the pipeline's partial-
    manual region passes it because a Pallas custom call there cannot be
    auto-partitioned over the still-GSPMD data axis (it would force a
    per-chunk batch all-gather, the same hazard ops/attention.py's
    `local_fn` note records for Ulysses-in-pipe).
    """
    from ..ops.attention_pallas import FA2_MAX_T
    from ..ops.dispatch import kernel_target
    from ..ops.flash_fa2 import fa2_gqa_supported

    tl, d = q.shape[2], q.shape[3]
    group = q.shape[1] // k.shape[1]  # GQA: k/v arrive at kv_heads
    if allow_kernel and kernel_target() == "tpu" \
            and tl * d <= FA2_MAX_T * 64 \
            and fa2_gqa_supported(tl, d, group):
        return _ring_fa2(q, k, v, axis_name, axis_size)
    return _ring_jnp(q, k, v, axis_name=axis_name, axis_size=axis_size)


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = "seq",
                   batch_axis=None, head_axis=None,
                   allow_kernel: bool = True):
    """shard_map entry: q/k/v (B, H, T, Dh) with T sharded over `seq_axis`
    (optionally B over `batch_axis` and H over `head_axis` — heads split
    across a tensor-parallel axis compose freely with the sequence ring).
    `allow_kernel=False` forces the jnp body (attn_impl=
    "standard_attention" keeps its kernel-free meaning under the ring)."""
    n = mesh.shape[seq_axis]
    spec = P(batch_axis, head_axis, seq_axis, None)
    fn = functools.partial(
        ring_attention_local, axis_name=seq_axis, axis_size=n,
        allow_kernel=allow_kernel,
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
