# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""ZeRO engines: DDP / ZeRO-1 / ZeRO-2 / ZeRO-3 as sharding strategies.

This file replaces the reference's entire zero/{ddp,zero1,zero2,zero3}
package family (wrapper.py + module.py + optim.py + utils.py per mode,
reference core/zero/) — ~1,100 LoC of per-mode re-derived modules injecting
NCCL calls into backward callbacks — with ONE engine parameterized by a
sharding strategy.  The mapping:

  reference mechanism                        TPU-native expression here
  -----------------------------------------  --------------------------------
  DDP: per-param async all-reduce in bwd      batch sharded over mesh "data";
  callback + wait (ddp/module.py:36-78)       params replicated -> XLA emits
                                              the grad all-reduce and overlaps
                                              it with the dx matmuls (latency-
                                              hiding scheduler).
  ZeRO-1: grad reduce-to-owner + owner        optimizer state laid out sharded
  steps + param broadcast                     (NamedSharding); update compute
  (zero1/module.py:17-24, optim.py:25-34)     partitions to the shard, new
                                              params constrained replicated ->
                                              all-gather.
  ZeRO-2: + non-owner grads dropped           grads constrained to the sharded
  (zero2/module.py:26-36 — a 1-elem           spec right after value_and_grad
  placeholder hack, "impossible in            -> XLA turns the all-reduce into
  pytorch, maybe solved by plugin C++")       reduce-scatter; full grads never
                                              materialize.  The hack vanishes.
  ZeRO-3: params broadcast-on-demand per      params *live* sharded; the scan
  layer, broken in the reference              over stacked blocks slices one
  (zero3/module.py:17-46, SURVEY §2.18:       layer then XLA all-gathers just
  NameError, rank-0 falsy, frees discarded)   that layer's shards inside the
                                              loop (fwd and, via remat, bwd) —
                                              the design the reference
                                              attempted, but correct.
  per-param `bwd_sync` grad-accum gating      explicit microbatch axis +
  (ddp/wrapper.py:25-33)                      lax.scan accumulation; collective
                                              cost paid once per step.
  cache rank map placement                    partition_tensors table exposed
  (zero/utils/partition.py)                   as `engine.rank_map` (ownership
                                              report / API parity); physical
                                              layout is even axis-sharding
                                              (SPMD) — see partition.py note.

Quirk decisions (SURVEY §8): reference DDP *sums* grads across ranks and never
divides (quirk #1); here the loss is the mean over the GLOBAL batch, so grads
are the true global gradient — DDP-vs-single-device parity becomes exact
instead of lr-rescaled.  Recorded in tests/test_engine.py
(test_stage_trains_and_matches_single_device).

Dynamic grad-sync (the reference's per-iteration `require_backward_grad_sync`
toggle, ddp/wrapper.py:25-33): engines of the same stage with different
`accum_steps` produce and accept the SAME TrainState (identical shardings),
so per-iteration sync policy = choosing which already-jitted engine to step
with this iteration; no re-jit, no state conversion
(tests/test_engine.py::test_engines_share_state_dynamic_accum).  A
data-dependent toggle *inside* one compiled step is deliberately not offered:
under XLA it would force both program paths into every step."""

from __future__ import annotations

import dataclasses
import warnings
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import make_mesh, ParallelContext
from .partition import partition_tensors

try:
    from flax import struct as _struct

    @_struct.dataclass
    class TrainState:
        params: Dict[str, Any]
        opt_state: Dict[str, Any]
        # dynamic loss-scale state ({"scale": f32, "good": i32}) when the
        # engine runs with loss_scale="dynamic"; None (no pytree leaves)
        # otherwise, so existing states/checkpoints keep their structure
        scaler: Any = None
        # dropout mask stream base key (derived from the init seed) when the
        # model has dropout > 0; None otherwise.  Carried in the STATE — not
        # as a jit closure constant — so checkpoint-restore resumes the
        # original run's mask stream without re-init (round-3 advice: a
        # restored state stepping on a fresh engine replayed the
        # constructor's hard-coded base)
        dropout_base: Any = None
        # quantized-grad-comm error feedback (parallel/comm.py): the flat
        # per-device quantization error carried to next step, global shape
        # (n_dev, padded_elems) sharded over "data"; None (no leaves)
        # unless grad_comm is int8/fp8 with error feedback on
        grad_residual: Any = None
except Exception:  # pragma: no cover - flax always present in this image
    TrainState = None


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------

def _leaf_spec(name: str, shape, n_dev: int, axis: str = "data",
               reserved: Optional[Dict[int, str]] = None,
               prefer_dim: Optional[int] = None) -> P:
    """Even axis-sharding rule for one tensor.

    `reserved` pre-places mesh axes on specific dims (tensor/expert
    parallelism); the ZeRO data-axis shard then goes on the largest
    *remaining* axis divisible by the mesh size.  Tensors from the stacked
    block ("h.*") never shard the leading (n_layer,) axis — the scan slices
    it, and keeping it unsharded is what makes XLA's all-gather happen
    per-layer *inside* the loop (the ZeRO-3 gather-on-demand).  Indivisible /
    small tensors replicate.

    `prefer_dim` overrides the largest-axis walk when that dim is free and
    divisible.  Used by the fp8 gather (engine passes the IN dim for
    quant-eligible leaves): an OUT-dim shard is exactly aligned with the
    per-out-channel dequant scale, so the SPMD partitioner dequantizes
    shard-side for free and all-gathers bf16 — the f8 wire saving only
    exists when the shard axis and the scale axis differ (round-5
    TPU-HLO measurement, PROFILE.md finding 5).
    """
    if not shape:
        return P()
    spec = [None] * len(shape)
    for dim, ax in (reserved or {}).items():
        spec[dim] = ax
    if n_dev > 1:
        best = None
        if (prefer_dim is not None and spec[prefer_dim] is None
                and shape[prefer_dim] % n_dev == 0
                and shape[prefer_dim] >= n_dev):
            best = prefer_dim
        else:
            start = 1 if name.startswith("h.") and len(shape) > 1 else 0
            for ax in range(start, len(shape)):
                if spec[ax] is None and shape[ax] % n_dev == 0 \
                        and shape[ax] >= n_dev:
                    if best is None or shape[ax] > shape[best]:
                        best = ax
        if best is not None:
            spec[best] = axis
    while spec and spec[-1] is None:  # P(None, ...) normalizes to P()
        spec.pop()
    return P(*spec)


def _param_spec_tree(
    shapes: Dict[str, Any], n_dev: int,
    reserved: Optional[Dict[str, Dict[int, str]]] = None,
    prefer_dims: Optional[Dict[str, int]] = None,
) -> Dict[str, P]:
    reserved = reserved or {}
    prefer_dims = prefer_dims or {}
    return {
        n: _leaf_spec(n, s.shape, n_dev, reserved=reserved.get(n),
                      prefer_dim=prefer_dims.get(n))
        for n, s in shapes.items()
    }


def _opt_spec_tree(opt_shapes, param_specs: Dict[str, P], sharded: bool,
                   base_specs: Optional[Dict[str, P]] = None):
    """Sharding tree matching the optimizer-state structure.

    Per-param slots (m/v/velocity/vmax, shaped like the param) inherit the
    param's full ZeRO spec when `sharded`, else the base (tensor-parallel
    placement only) spec; the global step counter replicates.
    """
    table = param_specs if sharded else (base_specs or {})

    def spec_for(path, leaf):
        names = [p.key for p in path if isinstance(p, jax.tree_util.DictKey)]
        # path looks like ('state', '<param name>', 'm')
        for key in names:
            if key in table and len(table[key]) <= len(leaf.shape):
                return table[key]
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, opt_shapes)


def _to_shardings(tree_of_specs, mesh: Mesh):
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        tree_of_specs,
        is_leaf=lambda x: isinstance(x, P),
    )


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

class ZeroEngine:
    """Training engine; subclasses pin the ZeRO stage.

    API parity with the reference wrappers + sharded optimizers
    (e.g. `Zero2(model, partition_table)` + `Zero2AdamW(...)`,
    reference zero2/wrapper.py:16-48, zero2/optim.py): here the pair is
    fused — `Zero2(model, optimizer, mesh).init(key)` then
    `state, loss = engine.step(state, batch)`.
    """

    stage: int = 0
    data_parallel: bool = True

    def __init__(
        self,
        model,
        optimizer,
        mesh: Optional[Mesh] = None,
        accum_steps: int = 1,
        evenness_priority: float = 0.0,
        donate: bool = True,
        seq_parallel: int = 1,
        seq_impl: str = "ring",
        tensor_parallel: int = 1,
        expert_parallel: int = 1,
        pipeline_parallel: int = 1,
        pipeline_microbatches: Optional[int] = None,
        pipeline_schedule: str = "gpipe",
        pipeline_virtual: int = 1,
        grad_clip: Optional[float] = None,
        loss_scale=None,
        loss_scale_growth_interval: int = 2000,
        offload_opt_state: bool = False,
        offload_prefetch: int = 2,
        telemetry=None,
        grad_comm: str = "fp32",
        grad_comm_block: int = 256,
        grad_comm_groups: Optional[int] = None,
        grad_comm_error_feedback: bool = True,
        grad_buckets: int = 1,
        grad_comm_tail: str = "fp32",
        gather_prefetch: int = 0,
        gather_groups: Optional[int] = None,
        hpz: bool = False,
        hpz_comm: str = "fp32",
        hpz_granule_of: Optional[Dict[int, int]] = None,
    ):
        """seq_parallel > 1 carves a "seq" mesh axis out of the devices:
        tokens shard over it and attention runs as a ppermute ring
        (context parallelism) or, with seq_impl="ulysses", as the
        DeepSpeed-Ulysses all-to-all head/sequence reshard (two
        collectives + the plain local kernel; needs n_head/tp divisible
        by the seq size).  tensor_parallel > 1 carves a "model" axis:
        Megatron-style intra-layer sharding per the model's `tp_rules()`.
        expert_parallel > 1 carves an "expert" axis: MoE expert sharding per
        `ep_rules()`.  pipeline_parallel > 1 carves a "pipe" axis: the
        stacked transformer blocks partition into S contiguous stages and
        microbatches flow through a GPipe ppermute pipeline
        (parallel/pipeline.py; `pipeline_microbatches` defaults to S).
        All compose with every ZeRO stage (the data axis keeps the ZeRO
        semantics); all are absent from the reference (SURVEY §2.20).

        pipeline_schedule: "gpipe" (default — forward-all-then-backward-all
        via autodiff, O(M) in-flight activations) or "1f1b" (combined
        fwd/bwd tick schedule, O(S) in-flight — raise microbatches to
        amortize the bubble without the activation bill; MoE aux loss,
        dropout, fp8 weight gather, and ring/Ulysses sequence
        parallelism all compose — see pipeline.py::spmd_pipeline_1f1b).
        "interleaved[:V]" and "zbub[:V]" run the table-driven executor
        instead (pipeline.py::spmd_pipeline_table): each stage holds V
        virtual model chunks (pipeline_virtual, or the ':V' suffix) and
        a static (tick, stage) -> {F/B/W, chunk, microbatch} program
        compiled by parallel/pipe_schedule.py drives a lax.switch per
        tick; "zbub" further splits backward into dgrad (critical path)
        and wgrad (bubble filler).  Both cut the pipeline bubble below
        1F1B's (S-1)/(M+S-1) — measured by the `bubble_frac` gauge —
        but compose with fewer knobs: the composed scheduler's pipe
        slot names each unsupported pairing (ScheduleConflictError).

        grad_clip: clip gradients to this global L2 norm (computed across
        every leaf; under ZeRO-2/3 the per-leaf square-sums run on the
        sharded grads and XLA inserts the psum).  loss_scale: None (off),
        a float (static scaling), or "dynamic" — scale the loss before
        backward, unscale grads after; dynamic keeps {scale, good-step
        count} in TrainState.scaler, halves the scale and SKIPS the
        optimizer step on non-finite grads, and doubles it after
        `loss_scale_growth_interval` consecutive finite steps.  This is
        fp16 AMP (the reference's unchecked TODO, reference README.md:68):
        bf16 — the TPU default policy — never needs it, fp16
        (compute_dtype=float16) does.

        telemetry: opt-in in-step observability (a
        `tiny_deepspeed_tpu.telemetry.Telemetry` instance, or any object
        with `on_step_output(aux)`).  When set, the compiled step also
        computes the packed on-device health vector (loss, grad/update/
        param global norms, non-finite grad count — telemetry/health.py)
        and `step()` pushes it into the telemetry object WITHOUT syncing;
        the vector rides the step output, so reading it costs the same
        single device->host transfer as reading the loss.  With
        telemetry=None (the default) the step program is byte-identical
        to an un-knobbed engine (tests/test_telemetry.py pins the HLO).
        A Telemetry constructed with layers=True additionally turns on
        per-layer health: the block scan taps every layer's output
        (parallel/schedule.layer_health_tap — the scheduler's probe
        slot) and the step also returns an (n_layer, 6) matrix of
        per-layer activation/activation-gradient/gradient norms and
        non-finite counts (telemetry/health.LAYER_FIELDS) — the
        first-NaN layer is localized in one step.  Composes with
        grad_buckets / quantized grad_comm / gather_prefetch / hpz via
        the composed scheduler lowering (pipeline forwards still
        refuse), model permitting (layer_health_capable: GPT-2/Llama;
        MoE is not).  With layers
        off the program is byte-identical to plain telemetry
        (tests/test_trace_flight.py pins the HLO).

        grad_comm: gradient-collective precision — "fp32" (default: the
        exact GSPMD path, compiled step byte-identical to an un-knobbed
        engine, pinned by tests/test_grad_comm.py), "int8" (blockwise
        absmax scales + stochastic rounding) or "fp8" (e4m3).  Quantized
        modes compute LOCAL grads inside a shard_map over the data axis
        and run the explicit schedule in parallel/comm.py: error-feedback
        residual (carried in TrainState.grad_residual, re-injected next
        step so quantization error cancels instead of accumulating),
        blockwise quantize, all-to-all reduce-scatter, quantized
        all-gather — ~4x less gradient wire than fp32 (ZeRO++ qgZ /
        EQuARX).  `grad_comm_block` sets the scale-block size;
        `grad_comm_groups` enables the hierarchical 2-hop schedule (that
        many consecutive ranks per low-precision intra-group hop, bf16
        across groups — for 2D meshes/tori where the inner group maps to
        the fast links); `grad_comm_error_feedback=False` drops the
        residual (saves its memory, costs convergence margin).  Needs a
        pure data-parallel mesh (no tp/sp/ep/pp — the explicit schedule
        replays the model inside a shard_map over the data axis, the
        same manual-region contract as the MoE pure-DP dispatch).
        Stages 0-2 run the legacy monolithic/bucketed lowerings
        unchanged; ZeRO-3 now composes too — the scheduler declares an
        implicit on-demand gather slot and runs the merged program
        (parallel/schedule.composed_step).  Composes with accumulation
        on the legacy lowerings (microbatches accumulate locally, ONE
        quantized sync per step; the composed lowering refuses accum
        loudly), grad clipping, loss scaling, and telemetry INCLUDING
        layers mode.  Under
        stage >= 2 the dequantized full gradient does materialize
        per-device before the sharding constraint re-slices it — the
        wire-vs-memory trade qgZ makes; keep fp32 when grad memory, not
        interconnect, is the binding constraint.  Inert (warning) on a
        1-device data axis.

        grad_buckets: bucketed backward-overlapped gradient release
        (parallel/schedule.GradBucketTap).  With K > 1 the gradient is split
        into K size-balanced buckets of consecutive layers (the stacked
        "h.*" leaves; K must divide n_layer) plus a tail bucket for the
        non-block leaves, and each layer bucket's collective — fp32
        pmean or the grad_comm int8/fp8 quantized schedule with
        per-bucket error-feedback residual slices — is emitted INSIDE
        the backward scan body via an identity custom_vjp on the bucket's
        param slice, as soon as that bucket's grads are final.  XLA's
        latency-hiding scheduler can then overlap bucket k's wire time
        with buckets k-1..0's backward compute — the reference's
        per-parameter backward-hook all-reduce (ddp/module.py:36-78) and
        its unshipped "communication bucketing" TODO (README.md:66-71).
        The monolithic schedule serializes ALL gradient wire behind the
        full backward; `utils/hlo_comm.overlap_report` measures the
        difference off the compiled HLO (the `grad_comm_overlap_frac`
        telemetry gauge).  grad_buckets=1 (default) keeps the exact
        monolithic program (byte-identical, pinned by
        tests/test_grad_buckets.py).  Same mesh contract as quantized
        grad_comm (pure data-parallel, model replayed with pctx=None
        inside a shard_map over the data axis) — plus the model must be
        grad_bucket_capable (GPT-2/Llama; MoE's scan carries an aux
        accumulator and is not).  ZeRO-3 and gather_quant compose via
        the scheduler's composed lowering (dW accumulates in f32 before
        each release, so no e4m3 cotangent reaches the wire).  Composes
        with grad_comm modes, accumulation (legacy lowering only, with
        buckets firing on the final microbatch), grad clip, loss
        scaling, and telemetry including layers.  Inert
        (warning) on a 1-device data axis.

        gather_prefetch: ZeRO-3 layer-ahead weight-gather prefetch
        (parallel/schedule.GatherPrefetchScan) — the forward/weight-side
        twin of grad_buckets.  With K >= 2 the block scan issues layer
        k+(K-1)'s parameter all-gather explicitly while layer k
        computes, holding at most K layers' gathered weights (K=2 =
        double buffer), on the forward AND the remat re-forward/backward
        (a custom_vjp reverse scan that also prefetches, and constrains
        each layer's dW to the sharded layout so the grad
        reduce-scatter stays in-loop) — DeepSpeed's stage-3 parameter
        prefetch, XLA-native (Xu et al. arXiv 2004.13336 is the
        weight-update-sharding precedent for making collective placement
        explicit rather than partitioner-implicit).  Composes with
        gather_quant="fp8" (the prefetched gathers move f8 bytes) and
        with accum / grad clip / loss scaling / dropout / telemetry.
        `gather_groups=m` adds the hierarchical 2-hop gather: resting
        precision (f8 when quantized) within m consecutive ranks,
        compute dtype across groups — mirroring grad_comm_groups; needs
        a pure data-parallel mesh (the gather runs a shard_map over the
        data axis).  ZeRO-3 only (stages 0-2 have no per-layer weight
        gather), scanned stack only (scan_unroll=1), no pipeline axis,
        and the model must be gather_prefetch_capable (GPT-2/Llama;
        MoE's scan carries an aux accumulator).  K in (0, 1) is OFF:
        the compiled step is byte-identical to an un-knobbed engine
        (pinned by tests/test_zero3_gather_prefetch.py).  Inert (warning) on
        a 1-device data axis.  Cost: K-1 extra clamped end-of-scan
        gathers per pass — (L+K-1)/L of the on-demand gather wire,
        priced in comm_report; placement measured by
        utils/hlo_comm.overlap_report (gather_overlap_frac).

        hpz: ZeRO++-style secondary weight partitioning
        (arXiv:2306.10209; parallel/schedule.py composed lowering).
        Each rank holds, next to its global fp32 ZeRO-3 shard, its
        SLICE's share of a full compute-dtype (bf16/fp8) block-weight
        replica — rebuilt once per step by a single top-level
        inter-slice all-gather — so every in-scan forward/backward
        weight gather runs over the intra-slice group only and moves
        ZERO DCN bytes (pinned via utils/hlo_comm.
        gather_link_split_in_loops on the emulated 2-slice mesh; the
        hpz_dcn_wire_bytes gauge).  The optimizer shards stay global
        ZeRO-3; the replica is stashed as a backward residual (HBM
        cost: compute-dtype block bytes / intra-slice ranks, per rank
        — PROFILE.md).  Requires ZeRO-3 + a pure-DP mesh with >= 2
        equal contiguous DCN granules (slices/processes;
        `hpz_granule_of` overrides the parallel/mesh.granule_map
        derivation for CPU-emulated tests).  Composes with
        gather_prefetch, grad_buckets/grad_comm, and telemetry layers.

        offload_opt_state: ZeRO-Offload-style placement — optimizer
        moments REST in host memory (NamedSharding memory_kind
        "pinned_host") instead of HBM, freeing ~8 bytes/param of chip
        memory between steps (f32 moments); the update STREAMS them
        through HBM one parameter leaf at a time (_offload_update:
        explicit transfer in -> update_one -> transfer out, barrier-
        chained so XLA cannot bulk-hoist the transfers — round-4 AOT
        topology measurement on gpt2-1.5b: compiled peak 12.8 GB streamed
        vs 17.0 GB bulk vs 15.2 GB unoffloaded; resting device state
        9.2 -> 3.1 GB).  Streaming granularity is one stacked leaf — the
        h.* tensors carry all L layers, so the largest in-flight chunk is
        one weight's (L, ...) moments.  The scalar step counter stays in
        device memory (its side-effecting placement annotation trips the
        SPMD partitioner).  TPU-runtime feature: XLA CPU does not
        implement the placement custom-call, so execution is covered by
        TPU-gated tests (tests/test_offload.py) and compilation by the
        TPU-topology AOT tests (tests/test_aot_topology.py)."""
        self.model = model
        self.optimizer = optimizer
        pp = int(pipeline_parallel)
        _unroll = getattr(getattr(model, "config", None), "scan_unroll", 1)
        if self.stage == 3 and (_unroll is True or _unroll not in (1, False)):
            # the documented footgun (GPTConfig.scan_unroll): ZeRO-3's
            # per-layer gather memory bound RELIES on the scan — an
            # unrolled stack lets XLA hoist the gathers and regrow
            # full-model HBM
            warnings.warn(
                "scan_unroll != 1 under ZeRO-3 defeats the per-layer "
                "all-gather memory bound (XLA may hoist every layer's "
                "gather); use the scanned stack (scan_unroll=1) for "
                "ZeRO-3 runs", stacklevel=2)
        if mesh is None:
            if not self.data_parallel:
                mesh = make_mesh(devices=[jax.devices()[0]])
            else:
                n = len(jax.devices())
                sp, tp = int(seq_parallel), int(tensor_parallel)
                ep = int(expert_parallel)
                if n % (sp * tp * ep * pp):
                    raise ValueError(
                        f"seq_parallel={sp} * tensor_parallel={tp} * "
                        f"expert_parallel={ep} * pipeline_parallel={pp} "
                        f"must divide device count {n}"
                    )
                shape, names = [n // (sp * tp * ep * pp)], ["data"]
                if sp > 1:
                    shape.append(sp); names.append("seq")
                if tp > 1:
                    shape.append(tp); names.append("model")
                if ep > 1:
                    shape.append(ep); names.append("expert")
                if pp > 1:
                    shape.append(pp); names.append("pipe")
                mesh = make_mesh(tuple(shape), tuple(names))
        self.mesh = mesh

        def _axis(name):
            return (
                name if name in mesh.axis_names
                and mesh.shape.get(name, 1) > 1 else None
            )

        self.seq_axis = _axis("seq")
        self.model_axis = _axis("model")
        self.expert_axis = _axis("expert")
        self.pipe_axis = _axis("pipe")
        # seq x pipe composes since pipeline v2: the pipeline's shard_map
        # goes manual over {pipe, seq} and ring attention runs inside it
        # (parallel/pipeline.py seq_axis, ops/attention.py dispatch)
        if self.pipe_axis is not None and not getattr(
            model, "pipeline_capable", False
        ):
            raise ValueError(
                f"{type(model).__name__} does not implement the pipeline "
                "forward (pipeline_capable=False); pipeline_parallel would "
                "silently run un-pipelined with the layer axis sharded"
            )
        # "interleaved:2" / "zbub:2" carry the virtual-stage count V in
        # the spec itself (the parse_sched_spec `pipe=KIND:V` form); an
        # explicit pipeline_virtual kwarg covers the programmatic path
        _psched = pipeline_schedule
        if ":" in _psched:
            _psched, _, _pv = _psched.partition(":")
            try:
                pipeline_virtual = int(_pv)
            except ValueError:
                raise ValueError(
                    f"pipeline_schedule {pipeline_schedule!r}: the ':V' "
                    f"suffix must be an integer virtual-stage count"
                ) from None
        if _psched not in ("gpipe", "1f1b", "interleaved", "zbub"):
            raise ValueError(
                f"pipeline_schedule must be 'gpipe', '1f1b', "
                f"'interleaved[:V]' or 'zbub[:V]', got "
                f"{pipeline_schedule!r}")
        self._use_1f1b = _psched == "1f1b"
        # table-driven schedules (interleaved / zero-bubble) compile a
        # static tick program via the composed scheduler's pipe slot
        self._use_pipe_table = _psched in ("interleaved", "zbub")
        self._pipe_kind = _psched
        self._pipe_virtual = max(int(pipeline_virtual), 1)
        if self._use_1f1b or self._use_pipe_table:
            # reject rather than silently run un-pipelined autodiff — a
            # user benchmarking "1f1b" must get the 1f1b code path
            if self.pipe_axis is None:
                raise ValueError(
                    f"pipeline_schedule={_psched!r} requires "
                    "pipeline_parallel > 1 (no 'pipe' mesh axis is "
                    "active)"
                )
        if self._use_1f1b and not getattr(model, "supports_1f1b", False):
            raise ValueError(
                f"{type(model).__name__} does not support the 1F1B "
                "schedule (no loss_and_grad_1f1b); use 'gpipe'"
            )
        if seq_impl not in ("ring", "ulysses"):
            raise ValueError(f"seq_impl must be 'ring' or 'ulysses', "
                             f"got {seq_impl!r}")
        if seq_impl == "ulysses" and self.seq_axis is not None:
            nh = getattr(getattr(model, "config", None), "n_head", None)
            tp_size = (mesh.shape[self.model_axis]
                       if self.model_axis is not None else 1)
            sp_size = mesh.shape[self.seq_axis]
            if nh is not None and (nh // tp_size) % sp_size:
                raise ValueError(
                    f"seq_impl='ulysses' needs local heads "
                    f"(n_head {nh} / tp {tp_size}) divisible by the seq "
                    f"axis size {sp_size} — use seq_impl='ring' instead"
                )
        self.pctx = ParallelContext(
            mesh=mesh, data_axis="data", seq_axis=self.seq_axis,
            model_axis=self.model_axis, expert_axis=self.expert_axis,
            pipe_axis=self.pipe_axis,
            pipe_microbatches=int(pipeline_microbatches or 0),
            seq_impl=seq_impl,
        )
        self.accum_steps = int(accum_steps)
        # dropout: the model's apply takes rng= when its config declares a
        # nonzero rate; the step derives a fresh key from the optimizer step
        # counter so every iteration (and every microbatch) draws new masks
        # without any state threading or re-jit
        self._dropout_active = bool(
            getattr(getattr(model, "config", None), "dropout", 0.0)
        )
        self.grad_clip = float(grad_clip) if grad_clip else None
        if loss_scale is not None and loss_scale != "dynamic" \
                and not isinstance(loss_scale, (int, float)):
            raise ValueError(
                f"loss_scale must be None, a number, or 'dynamic'; "
                f"got {loss_scale!r}"
            )
        self.loss_scale = loss_scale
        self.loss_scale_growth_interval = int(loss_scale_growth_interval)
        self.n_dev = mesh.devices.size
        # ZeRO sharding happens over the data axis only
        self.n_shard = mesh.shape["data"]

        # ---- the in-scan collective scheduler (parallel/schedule.py) ----
        # Every tap-style knob (grad_comm / grad_buckets / gather_prefetch
        # / hpz / telemetry layers) becomes a SLOT declaration; ONE
        # build_schedule call validates the composition and picks the
        # lowering -- legacy single-slot programs stay byte-identical, any
        # real composition runs the merged composed_step machine.
        from . import schedule as _sched
        from .comm import GRAD_COMM_MODES
        # "auto" = DCN-aware sizing: build_schedule derives the codec /
        # bucket count / inner-group factor from the mesh's granule map
        # (parallel/schedule.auto_comm_plan); the resolved values are
        # read back onto the engine attrs after the build below
        _auto = any(v == "auto"
                    for v in (grad_comm, grad_buckets, gather_groups))
        if grad_comm not in GRAD_COMM_MODES and grad_comm != "auto":
            raise ValueError(
                f"grad_comm must be one of {GRAD_COMM_MODES} or 'auto', "
                f"got {grad_comm!r}"
            )
        self.grad_comm = grad_comm
        self.grad_comm_block = int(grad_comm_block)
        self.grad_comm_groups = (
            int(grad_comm_groups) if grad_comm_groups else None
        )
        if grad_comm == "fp32" and self.grad_comm_groups:
            # loud rejection, not a silent fp32 run mislabeled as the
            # 2-hop schedule (the pipeline_schedule='1f1b' convention)
            raise ValueError(
                "grad_comm_groups requires grad_comm='int8' or 'fp8' "
                "(grad_comm='fp32' runs no quantized schedule)"
            )
        self.grad_comm_error_feedback = bool(grad_comm_error_feedback)
        self.grad_buckets = grad_buckets if grad_buckets == "auto" \
            else (int(grad_buckets) if grad_buckets else 1)
        if self.grad_buckets != "auto" and self.grad_buckets < 1:
            raise ValueError(
                f"grad_buckets must be >= 1, got {grad_buckets}"
            )
        if grad_comm_tail not in GRAD_COMM_MODES:
            raise ValueError(
                f"grad_comm_tail must be one of {GRAD_COMM_MODES}, "
                f"got {grad_comm_tail!r}"
            )
        self.grad_comm_tail = grad_comm_tail
        self.gather_prefetch = int(gather_prefetch) if gather_prefetch \
            else 0
        if self.gather_prefetch < 0:
            raise ValueError(
                f"gather_prefetch must be >= 0 (0/1 = the on-demand "
                f"gather; K >= 2 holds K layers), got {gather_prefetch}"
            )
        self.gather_groups = gather_groups if gather_groups == "auto" \
            else (int(gather_groups) if gather_groups else None)
        if self.gather_groups and self.gather_groups != "auto" \
                and self.gather_prefetch <= 1:
            # loud rejection, not a silently-flat gather mislabeled
            # as the 2-hop schedule (the grad_comm_groups convention)
            raise ValueError(
                "gather_groups requires gather_prefetch >= 2 (the "
                "2-hop gather lives in the explicit prefetched "
                "schedule)"
            )
        self.hpz = bool(hpz)
        if hpz_comm not in GRAD_COMM_MODES:
            raise ValueError(
                f"hpz_comm must be one of {GRAD_COMM_MODES}, "
                f"got {hpz_comm!r}"
            )
        self.hpz_comm = hpz_comm
        granule_of = hpz_granule_of
        if (self.hpz or _auto) and granule_of is None:
            from .mesh import granule_map
            granule_of = granule_map(mesh.devices.flatten())

        # telemetry attrs settle BEFORE the schedule build (the probe
        # slot comes from Telemetry(layers=True))
        self.telemetry = telemetry
        self._telemetry_on = telemetry is not None
        if self._telemetry_on and hasattr(telemetry, "attach"):
            telemetry.attach(self)
        self._layers_on = bool(
            self._telemetry_on and getattr(telemetry, "layers", False)
        )
        self._layer_count = int(
            getattr(getattr(model, "config", None), "n_layer", 0) or 0
        )

        busy_axes = (self.seq_axis, self.model_axis, self.expert_axis,
                     self.pipe_axis)
        self._schedule = _sched.build_schedule(
            model=model, stage=self.stage, n_shard=self.n_shard,
            busy_axes=busy_axes, accum_steps=self.accum_steps,
            scan_unroll=_unroll, grad_comm=grad_comm,
            grad_comm_block=self.grad_comm_block,
            grad_comm_groups=self.grad_comm_groups,
            grad_comm_error_feedback=self.grad_comm_error_feedback,
            grad_buckets=self.grad_buckets,
            grad_comm_tail=self.grad_comm_tail,
            gather_prefetch=self.gather_prefetch,
            gather_groups=self.gather_groups,
            hpz=self.hpz, hpz_comm=self.hpz_comm,
            granule_of=granule_of,
            telemetry_layers=self._layers_on,
            pipeline=self.pipe_axis is not None or self._use_1f1b,
            pipe_schedule=(self._pipe_kind if self._use_pipe_table
                           else None),
            pipe_stages=(mesh.shape[self.pipe_axis]
                         if self.pipe_axis is not None else 0),
            pipe_virtual=self._pipe_virtual,
            pipe_microbatches=self.pctx.pipe_microbatches,
        )
        self._lowering = self._schedule.lowering
        sg, sr = self._schedule.gather, self._schedule.grad
        if _auto:
            # read the DCN-aware plan's resolved values back so
            # describe()/telemetry/checkpoints see concrete knobs, never
            # the "auto" sentinel
            self.grad_comm = sr.mode if sr is not None else "fp32"
            self.grad_buckets = sr.buckets if sr is not None else 1
            self.gather_groups = sg.groups if sg is not None else None
        self._grad_comm_active = sr is not None and sr.mode != "fp32"
        self._bucketed_active = sr is not None and sr.buckets > 1
        self._gather_prefetch_active = sg is not None and sg.prefetch > 1

        shapes = model.param_shapes()
        # API-parity ownership table (the reference's cache rank map).
        self.rank_map = partition_tensors(
            shapes, self.n_shard, evenness_priority
        )
        if evenness_priority:
            # the knob is real for the TABLE but deliberately inert for the
            # layout: engines always shard evenly along tensor axes (SPMD)
            # rather than placing whole tensors per owner like the
            # reference; say so instead of silently ignoring the intent
            warnings.warn(
                "evenness_priority shapes only engine.rank_map (the "
                "reference-parity ownership report); the physical layout "
                "is always even axis-sharding.  For the reference's "
                "whole-tensor placement semantics use partition_tensors + "
                "materialize_owned directly (parallel/partition.py).",
                stacklevel=2,
            )

        # tensor/expert-parallel placements come from the model and are part
        # of EVERY spec (resting, shard, grad, optimizer) — ZeRO's data-axis
        # shard composes on a remaining dim.
        if self.model_axis is not None:
            # attention shards over heads: validate at init, not deep inside
            # a shard_map trace at step time (e.g. gpt2-1.5b has n_head=25)
            nh = getattr(getattr(model, "config", None), "n_head", None)
            tp_size = mesh.shape[self.model_axis]
            if nh is not None and nh % tp_size:
                raise ValueError(
                    f"n_head={nh} not divisible by tensor-parallel axis "
                    f"size {tp_size}"
                )

        reserved: Dict[str, Dict[int, str]] = {}
        for ax_attr, rules_fn in (
            (self.model_axis, "tp_rules"), (self.expert_axis, "ep_rules")
        ):
            if ax_attr is None:
                continue
            size = mesh.shape[ax_attr]
            for name, dim in getattr(model, rules_fn, dict)().items():
                if name not in shapes:
                    continue
                if shapes[name].shape[dim] % size:
                    raise ValueError(
                        f"{name} dim {dim} ({shapes[name].shape[dim]}) not "
                        f"divisible by {ax_attr} axis size {size}"
                    )
                reserved.setdefault(name, {})[dim] = ax_attr

        if self.pipe_axis is not None:
            # each pipeline stage owns a contiguous slab of the stacked
            # (n_layer, ...) block tensors: leading axis sharded over "pipe"
            pp_size = mesh.shape[self.pipe_axis]
            for name, s in shapes.items():
                if not name.startswith("h."):
                    continue
                if s.shape[0] % pp_size:
                    raise ValueError(
                        f"n_layer={s.shape[0]} not divisible by "
                        f"pipeline_parallel={pp_size}"
                    )
                reserved.setdefault(name, {})[0] = self.pipe_axis

        # fp8 gather: pin quant-eligible leaves' ZeRO shard to the IN dim
        # (dim 1 of the stacked (L, in, out)) so the shard axis differs
        # from the per-out-channel scale axis and the per-layer gathers
        # move f8 bytes (see _leaf_spec prefer_dim).  Under TP, o/down
        # reserve dim 1 for the model axis — those fall back to the walk.
        prefer_dims = {}
        if getattr(getattr(model, "config", None), "gather_quant", None) \
                and hasattr(model, "_quant_eligible"):
            prefer_dims = {
                n: 1 for n, s in shapes.items()
                if n.startswith("h.")
                and model._quant_eligible(n[len("h."):], s)
            }
        specs = _param_spec_tree(shapes, self.n_shard, reserved,
                                 prefer_dims=prefer_dims)
        self._shard_spec = specs  # even-shard spec per param
        self._shard_shardings = _to_shardings(specs, mesh)
        # base spec: tensor/expert placements only (no ZeRO data shard)
        base = _param_spec_tree(shapes, 1, reserved)
        # in-scan specs for the stacked block leaves (leading layer axis
        # sliced off): what each per-layer weight's gathered layout is —
        # consumed by the model's fp8-gather path (mesh.ParallelContext.
        # stacked_specs docstring)
        stacked_specs = {}
        for name, s in shapes.items():
            if not name.startswith("h."):
                continue
            entries = list(base[name]) + [None] * (
                len(s.shape) - len(base[name])
            )
            stacked_specs[name[len("h."):]] = P(*entries[1:])
        self.pctx = dataclasses.replace(
            self.pctx, stacked_specs=stacked_specs
        )
        self._prefetch_exec = None
        if self._schedule.gather is not None:
            # the scheduled gather needs BOTH per-layer layouts: gathered
            # (stacked_specs above — the gather target) and resting-
            # sharded (the gather source + the per-layer dW cotangent
            # constraint that keeps the reduce-scatter in-loop)
            stacked_shard = {}
            for name, s in shapes.items():
                if not name.startswith("h."):
                    continue
                entries = list(specs[name]) + [None] * (
                    len(s.shape) - len(specs[name])
                )
                stacked_shard[name[len("h."):]] = P(*entries[1:])
            self.pctx = dataclasses.replace(
                self.pctx,
                gather_prefetch=self.gather_prefetch,
                gather_groups=self.gather_groups,
                stacked_shard_specs=stacked_shard,
            )
            if self._lowering == "prefetch":
                # legacy single-slot lowering: the GatherPrefetchScan
                # executor passes through model.apply(sched=...) — same
                # ctor args as the pre-scheduler pctx branch, so the
                # traced program (and its HLO) is unchanged
                self._prefetch_exec = _sched.GatherPrefetchScan(
                    self.gather_prefetch, mesh, stacked_specs,
                    stacked_shard, groups=self.gather_groups,
                    data_axis="data",
                    compute_dtype=model.config.compute_dtype,
                )
        # where params LIVE between steps
        self._param_spec_rest = specs if self.stage >= 3 else base
        self._param_shardings = _to_shardings(self._param_spec_rest, mesh)

        opt_shapes = jax.eval_shape(optimizer.init, shapes)
        opt_specs = _opt_spec_tree(
            opt_shapes, specs, sharded=self.stage >= 1, base_specs=base
        )
        self._opt_shardings = _to_shardings(opt_specs, mesh)
        self.offload_opt_state = bool(offload_opt_state)
        # validated, not silently clamped (the old max(2, ...) floor ate
        # user intent): 1 is honored as "no double buffer" — each leaf's
        # inbound transfer chains on the PREVIOUS leaf's outbound, fully
        # serial streaming at minimum in-flight moment memory
        self.offload_prefetch = int(offload_prefetch)
        if self.offload_prefetch < 1:
            raise ValueError(
                f"offload_prefetch must be >= 1 (1 = serial streaming, "
                f"no double buffer; default 2), got {offload_prefetch}"
            )
        if self.offload_opt_state:
            from ..optim.base import Optimizer as _OptBase
            if type(optimizer).update is not _OptBase.update:
                # the streamed update path calls update_one per leaf; an
                # optimizer overriding update() (cross-parameter logic)
                # would be silently bypassed — refuse instead
                raise ValueError(
                    f"offload_opt_state streams moments via the per-leaf "
                    f"update_one contract, but {type(optimizer).__name__} "
                    f"overrides update(); offload is unsupported for it"
                )
            if jax.default_backend() != "tpu":
                warnings.warn(
                    "offload_opt_state needs the TPU runtime — XLA CPU "
                    "has no placement custom-call; expect "
                    "'annotate_device_placement' errors at init/step",
                    stacklevel=2,
                )
            # per-param moments to host memory; "step" (and any other
            # top-level scalar) stays device-resident.  The step streams
            # them through HBM for the update (_step_impl transfers in;
            # out_shardings put the new moments back) — TPU XLA refuses
            # mixed-memory-space arithmetic, so the transfer must be
            # explicit (caught by the round-4 AOT topology compile).
            self._opt_dev_shardings = self._opt_shardings["state"]
            self._opt_shardings = dict(
                self._opt_shardings,
                state=jax.tree.map(
                    lambda s: s.with_memory_kind("pinned_host"),
                    self._opt_shardings["state"],
                ),
            )
        self._scaler_shardings = (
            {"scale": NamedSharding(mesh, P()),
             "good": NamedSharding(mesh, P())}
            if self.loss_scale == "dynamic" else None
        )
        # error-feedback residual: per-device flat error, global shape
        # (n_shard, padded_elems) sharded over the data axis — each rank's
        # row is ITS quantization error (parallel/comm.py docstring)
        # bucketed-release geometry: layer-bucket / tail-pad sizes and the
        # residual layout (raises here, at init, when grad_buckets does
        # not divide n_layer)
        # bucket / residual geometry comes from the compiled Schedule:
        # legacy bucket lowering keeps the [b0 | ... | bK-1 | tail] row,
        # monolithic quant the whole-tree pad, composed ZeRO-3 drops the
        # tail slice (the tail reduce-scatters at full precision)
        self._bucket_layout = self._schedule.layout
        self._residual_shardings = None
        self._residual_shape = None
        if self._schedule.residual_len:
            self._residual_shape = (
                self.n_shard, self._schedule.residual_len
            )
            self._residual_shardings = NamedSharding(mesh, P("data"))
        self._dropout_shardings = (
            NamedSharding(mesh, P()) if self._dropout_active else None
        )

        if self.data_parallel:
            batch_spec = P("data", self.seq_axis)  # (B, T): tokens shard too
        else:
            batch_spec = P()
        self._eval_batch_sharding = NamedSharding(mesh, batch_spec)
        if self.accum_steps > 1:
            batch_spec = P(None, *batch_spec)
        self._batch_sharding = NamedSharding(mesh, batch_spec)

        self._build_step()

        def _eval_impl(params, ix, tg):
            from ..ops.dispatch import gspmd_auto_region
            kw = {}
            if self._lowering == "prefetch":
                # keep the legacy eval program: the forward-only pass
                # also runs the prefetched gather scan
                kw["sched"] = self._prefetch_exec
            with gspmd_auto_region(self.n_dev > 1):
                return self.model.apply(params, ix, tg, pctx=self.pctx,
                                        **kw)

        # forward-only loss (validation): no dropout (no rng), no grads, no
        # state change; always takes a plain (B, T) batch (no accum axis)
        self._eval = jax.jit(
            _eval_impl,
            in_shardings=(
                self._param_shardings,
                self._eval_batch_sharding, self._eval_batch_sharding,
            ),
            out_shardings=NamedSharding(mesh, P()),
        )

    def _build_step(self) -> None:
        from ..autotuner import get_default_tuner
        tuner = get_default_tuner()
        # the winner-table version this program was traced against; retune
        # rebuilds only when timing has produced new winners since
        self._tuner_version = getattr(tuner, "version", 0)
        self._step = jax.jit(
            self._step_impl,
            in_shardings=(
                TrainState(
                    params=self._param_shardings,
                    opt_state=self._opt_shardings,
                    scaler=self._scaler_shardings,
                    dropout_base=self._dropout_shardings,
                    grad_residual=self._residual_shardings,
                ),
                (self._batch_sharding, self._batch_sharding),
            ),
            out_shardings=(
                TrainState(
                    params=self._param_shardings,
                    opt_state=self._opt_shardings,
                    scaler=self._scaler_shardings,
                    dropout_base=self._dropout_shardings,
                    grad_residual=self._residual_shardings,
                ),
                NamedSharding(self.mesh, P()),
            ) + (
                # telemetry: the packed (5,) health vector rides along,
                # replicated like the loss — plus the (n_layer, 6)
                # layer-health matrix in layers mode
                (NamedSharding(self.mesh, P()),) if self._telemetry_on
                else ()
            ) + (
                (NamedSharding(self.mesh, P()),) if self._layers_on
                else ()
            ),
            donate_argnums=(0,),
        )

    def retune(self) -> int:
        """Autotune lifecycle step: ops consulted the default RuntimeAutoTuner
        during the first trace, which RECORDS candidate requests (timing
        cannot run inside a trace — autotuner/runtime_tuner.py).  This times
        them on the device now and rebuilds the jitted step so the winners
        are baked in.  Returns the number of sites tuned; no-op (0) without
        an installed tuner or pending requests.

        Usage:  engine.step(state, batch)   # first step: trace + record
                engine.retune()             # time candidates, re-jit
                engine.step(state, batch)   # tuned program from here on
        """
        from ..autotuner import get_default_tuner
        tuner = get_default_tuner()
        if tuner is None:
            return 0
        n = tuner.resolve_pending()
        # rebuild iff timing produced winners SINCE this program was traced —
        # covers another engine resolving our pending keys (version moved,
        # n == 0 here), and correctly skips the rebuild when every site was
        # satisfied from the ahead-of-time cache during the trace (version
        # unchanged: a re-trace would compile the identical program)
        if tuner.version != self._tuner_version:
            self._build_step()
        return n

    def revert_tune(self) -> None:
        """Undo autotuning: uninstall the process-default tuner and rebuild
        the step with every dispatch site's candidate[0] default — the
        guardrail counterpart to retune() for when the standalone-timed
        winners lose end-to-end (the hazard optim/adamw_pallas.py measured;
        bench.py's BENCH_AUTOTUNE pass uses this when the tuned step is
        slower than the default one)."""
        from ..autotuner import set_default_tuner
        set_default_tuner(None)
        self._build_step()

    # -- state creation ----------------------------------------------------

    def init(self, key) -> "TrainState":
        """Create params + optimizer state directly in their resting
        shardings (no full-replica materialization step — fixes the
        reference's full `.to(rank)` before wrapping, zero1/train.py:34)."""
        params = jax.jit(
            self.model.init, out_shardings=self._param_shardings
        )(key)
        opt_state = jax.jit(
            self.optimizer.init, out_shardings=self._opt_shardings
        )(params)
        scaler = None
        if self.loss_scale == "dynamic":
            scaler = jax.device_put(
                {"scale": jnp.float32(2.0 ** 15),
                 "good": jnp.zeros((), jnp.int32)},
                self._scaler_shardings,
            )
        # dropout base derived from the user's key (NOT the same stream as
        # param init) so seeded runs draw distinct mask sequences; lives in
        # the state (not a closure constant), so re-init with a new seed and
        # checkpoint restore both get the right stream with no re-jit
        dropout_base = None
        if self._dropout_active:
            dropout_base = jax.device_put(
                jax.random.fold_in(key, 0xD0), self._dropout_shardings
            )
        grad_residual = None
        if self._residual_shardings is not None:
            # zeros created directly in the (data,)-sharded layout
            grad_residual = jax.jit(
                partial(jnp.zeros, self._residual_shape, jnp.float32),
                out_shardings=self._residual_shardings,
            )()
        return TrainState(params=params, opt_state=opt_state, scaler=scaler,
                          dropout_base=dropout_base,
                          grad_residual=grad_residual)

    # -- the train step ----------------------------------------------------

    @staticmethod
    def _constrain(tree, shardings):
        return jax.tree.map(
            jax.lax.with_sharding_constraint, tree, shardings
        )

    def _offload_update(self, params, grads, opt_state, finite=None):
        """Optimizer update for `offload_opt_state`: moments REST in
        pinned_host and are STREAMED through HBM leaf by leaf — transfer
        in, update_one, transfer back — windowed: leaf i's inbound
        transfer is made data-dependent (optimization_barrier) on leaf
        i-`offload_prefetch`'s outbound copy, so at most `offload_prefetch`
        leaves' moments are in HBM while transfer and update compute
        overlap.  Without any chaining XLA hoists every transfer to the
        front and the full moments sit in HBM as one temp allocation,
        erasing the feature's point (measured on the round-4 AOT topology
        compile: 1.5B peak 17.0 GB unchained vs 12.8 GB double-buffered
        vs 15.2 GB unoffloaded).  `offload_prefetch` (round 5) makes the
        window explicit; the default stays 2 because the round-5 AOT
        schedule study came back NEGATIVE on widening at leaf
        granularity: w=4 compiles to 17.25 GB peak on the 1.5B bench
        config (four of the multi-GB stacked leaves in flight — over the
        16 GB chip) while the scheduler still refuses to hoist the
        dependency-free leading inbound copies under the fwd/bwd (first
        inbound copy-start sits at ~86% of the schedule for w=2/4/6
        alike), so the extra window buys HBM pressure, not overlap.  The
        knob remains for the chip A/B at sizes with headroom
        (tpu_batch.sh step 9b runs 774M w=2 vs w=4); within the update
        phase the w=2 chain already lets inbound(i) overlap both
        update(i-1) and outbound(i-1) (86/110 copy pairs overlap >=1
        fusion in the compiled schedule).
        `finite` (dynamic loss scaling) applies the keep-old MOMENTS
        selection ON DEVICE before the copy-out — host-space arithmetic is
        rejected by the TPU compiler; the params selection stays with the
        caller's _sel like the non-offload path.  Mirrors
        Optimizer.update's step/state contract via the public update_one
        hook; optimizers overriding update() are rejected at engine
        construction."""
        step_new = opt_state["step"] + 1
        new_params, new_state = {}, {}
        w = self.offload_prefetch  # in-flight window (leaves of moments)
        tokens = [()] * w
        for n, p in params.items():
            host_leaf = opt_state["state"][n]
            host_leaf, _ = jax.lax.optimization_barrier(
                (host_leaf, tokens[-w])
            )
            dev_leaf = jax.tree.map(
                jax.device_put, host_leaf, self._opt_dev_shardings[n]
            )
            np_, ns = self.optimizer.update_one(
                n, p, grads[n], dev_leaf, step_new
            )
            if finite is not None:
                ns = jax.tree.map(
                    lambda a, b: jnp.where(finite, a, b.astype(a.dtype)),
                    ns, dev_leaf,
                )
            ns_host = jax.tree.map(
                jax.device_put, ns, self._opt_shardings["state"][n]
            )
            new_params[n], new_state[n] = np_, ns_host
            tokens.append(tuple(jax.tree.leaves(ns_host)))
        step_out = (
            jnp.where(finite, step_new, opt_state["step"])
            if finite is not None else step_new
        )
        return new_params, {"step": step_out, "state": new_state}

    def _step_impl(self, state: "TrainState", batch):
        # trace-time marker: on a multi-device mesh this program is GSPMD
        # auto-partitioned, so naked Mosaic custom calls cannot lower —
        # the layernorm gate reads this and keeps the XLA path
        # (ops/dispatch.py; attention wraps its own shard_map instead)
        from ..ops.dispatch import gspmd_auto_region
        with gspmd_auto_region(self.n_dev > 1):
            return self._step_body(state, batch)

    def _step_body(self, state: "TrainState", batch):
        idx, targets = batch
        params = state.params
        dynamic = self.loss_scale == "dynamic"
        if dynamic:
            scale = state.scaler["scale"]
        elif self.loss_scale:
            scale = jnp.float32(self.loss_scale)
        else:
            scale = None

        rng = (
            jax.random.fold_in(state.dropout_base, state.opt_state["step"])
            if self._dropout_active else None
        )

        # per-layer health probe (telemetry layers mode): a zeros (L, 4)
        # array differentiated alongside the params — its "gradient" is
        # the per-layer activation/activation-gradient stats smuggled out
        # of the scan by parallel/schedule.layer_health_tap
        probe0 = None
        if self._layers_on:
            from .schedule import LAYER_PROBE_WIDTH
            probe0 = jnp.zeros(
                (self._layer_count, LAYER_PROBE_WIDTH), jnp.float32
            )

        def loss_fn(p, ix, tg, rng=None, probe=None):
            from .schedule import ProbeScan
            kw = {"rng": rng} if rng is not None else {}
            if probe is not None:
                # probe lowering: the executor adds the (L, 4) probe row
                # to the stacked scan tree — the plain-scan program is
                # byte-identical to the pre-scheduler health_probe= path
                kw["sched"] = ProbeScan(probe)
            elif self._lowering == "prefetch":
                kw["sched"] = self._prefetch_exec
            l = self.model.apply(p, ix, tg, pctx=self.pctx, **kw)
            # loss scaling happens INSIDE the differentiated fn so the
            # whole backward runs on scaled values (fp16 AMP)
            return l * scale if scale is not None else l

        def loss_and_grads(p, ix, tg, rng=None):
            """(loss, grads, probe cotangent or None)."""
            if self._use_pipe_table:
                # grads computed INSIDE the tick table (per-op vjp) —
                # the interleaved/zero-bubble program is a static
                # (tick, stage) schedule compiled by build_schedule
                # (parallel/pipe_schedule.py), not autodiff output
                l, g = self.model.loss_and_grad_pipe(
                    p, ix, tg, pctx=self.pctx,
                    program=self._schedule.pipe_program,
                    loss_seed=scale if scale is not None else 1.0,
                    rng=rng,
                )
                return l, g, None
            if self._use_1f1b:
                # grads computed INSIDE the pipeline (per-tick vjp) — the
                # 1F1B schedule can't be expressed through autodiff
                l, g = self.model.loss_and_grad_1f1b(
                    p, ix, tg, pctx=self.pctx,
                    loss_seed=scale if scale is not None else 1.0,
                    rng=rng,
                )
                return l, g, None
            if self._layers_on:
                l, (g, ps) = jax.value_and_grad(
                    loss_fn, argnums=(0, 4)
                )(p, ix, tg, rng, probe0)
                return l, g, ps
            l, g = jax.value_and_grad(loss_fn)(p, ix, tg, rng)
            return l, g, None

        new_residual = state.grad_residual
        layer_probe = None
        if self._lowering == "composed":
            # the merged scheduler machine (parallel/schedule.py): every
            # declared slot — explicit prefetched/hpZ gathers, bucketed
            # quantized releases, the health probe — in ONE custom_vjp
            # scan pair inside a shard_map over the data axis.  Grads
            # come back reduced and UNSCALED like the legacy explicit
            # paths below.
            from .schedule import composed_step
            loss, grads, new_residual, layer_probe = composed_step(
                self, state, idx, targets, rng, scale
            )
        elif self._lowering == "bucket":
            # bucketed backward-overlapped release (grad_buckets > 1):
            # per-bucket collectives emitted inside the backward scan
            # body, fp32 or quantized.  Grads come back reduced and
            # UNSCALED, like the quantized path below.
            from .schedule import bucketed_step
            loss, grads, new_residual = bucketed_step(
                self, state, idx, targets, rng, scale
            )
        elif self._lowering == "quant_mono":
            # quantized gradient collectives (parallel/comm.py): local
            # grads inside a shard_map over the data axis, explicit
            # error-feedback int8/fp8 reduce-scatter + all-gather.  Grads
            # come back UNSCALED (the residual must live in true gradient
            # units); the loss is still scaled like the GSPMD path.
            from .schedule import monolithic_quant_step
            loss, grads, new_residual = monolithic_quant_step(
                self, state, idx, targets, rng, scale
            )
        elif self.accum_steps == 1:
            loss, grads, layer_probe = loss_and_grads(
                params, idx, targets, rng
            )
        else:
            # Microbatch accumulation: batch is (accum, B, T) — the
            # reference's `require_backward_grad_sync` gating
            # (ddp/wrapper.py:25-33) as explicit loop semantics.  Stage
            # <= 1 (replicated grads): summed locally, ONE all-reduce at
            # the end.  Stage >= 2 trades that for memory: the constraint
            # below keeps the f32 accumulator SHARDED, so every microbatch
            # reduce-scatters into the shard — accum_steps x the wire
            # bytes (TPU-measured, PROFILE.md) but never a full-size
            # accumulator per device, which is the point in the big-model
            # tight-HBM case accumulation exists for.
            def body(carry, mb):
                acc_loss, acc_grads, acc_probe = carry
                ix, tg, mb_i = mb
                mb_rng = (jax.random.fold_in(rng, mb_i)
                          if rng is not None else None)
                l, g, ps = loss_and_grads(params, ix, tg, mb_rng)
                acc_grads = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32), acc_grads, g
                )
                if ps is not None:
                    # probe stats are raw sq-sums + counts, so summing
                    # across microbatches keeps global-batch semantics
                    # (norms taken once, in layer_health_matrix)
                    acc_probe = acc_probe + ps
                if self.stage >= 2:
                    # keep the f32 accumulator SHARDED across microbatches:
                    # each microbatch's grad reduce-scatters into the shard
                    # instead of carrying a full per-device replica through
                    # the scan — exactly the big-model tight-HBM case where
                    # accumulation matters (round-1 verdict weak #3).
                    acc_grads = self._constrain(
                        acc_grads, self._shard_shardings
                    )
                return (acc_loss + l, acc_grads, acc_probe), None

            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            if self.stage >= 2:
                zero_grads = self._constrain(
                    zero_grads, self._shard_shardings
                )
            (loss, grads, layer_probe), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zero_grads, probe0),
                (idx, targets, jnp.arange(self.accum_steps)),
            )
            loss = loss / self.accum_steps
            grads = jax.tree.map(
                lambda g, p: (g / self.accum_steps).astype(p.dtype),
                grads, params,
            )

        def _rescale(tree, factor):
            return jax.tree.map(
                lambda g: (g.astype(jnp.float32) * factor).astype(g.dtype),
                tree,
            )

        if scale is not None:
            loss = loss / scale
            if self._lowering in ("plain", "probe", "prefetch", "pipe"):
                # the explicit-schedule lowerings (composed / bucket /
                # quant_mono) already unscaled before their collectives
                grads = _rescale(grads, 1.0 / scale)
            if layer_probe is not None:
                # the backward ran on the scaled loss: the dact sq-sum
                # column (2) carries scale^2; the non-finite counts stay
                # as observed (AMP overflow IS the scaled-backward truth)
                layer_probe = layer_probe.at[:, 2].multiply(
                    1.0 / (scale * scale)
                )
        if dynamic:
            # finiteness judged on the UNSCALED grads, before clipping can
            # turn an inf norm into nans
            finite = jnp.bool_(True)
            for g in jax.tree.leaves(grads):
                finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
        if self.grad_clip is not None:
            gsq = sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree.leaves(grads)
            )
            grads = _rescale(grads, jnp.minimum(
                1.0, self.grad_clip / (jnp.sqrt(gsq) + 1e-6)
            ))

        if self.stage >= 2:
            # ZeRO-2/3: gradient sharding — the all-reduce XLA would emit for
            # replicated-param grads becomes a reduce-scatter.
            grads = self._constrain(grads, self._shard_shardings)

        if self.offload_opt_state:
            new_params, new_opt = self._offload_update(
                params, grads, state.opt_state,
                finite if dynamic else None,
            )
        else:
            new_params, new_opt = self.optimizer.update(
                params, grads, state.opt_state
            )
        new_scaler = state.scaler
        if dynamic:
            # overflow -> discard the whole update (params, moments, AND the
            # step counter: a skipped step must not advance bias correction),
            # halve the scale; grow it after `growth_interval` clean steps
            def _sel(new, old):
                return jax.tree.map(
                    lambda n, o: jnp.where(finite, n, o.astype(n.dtype)),
                    new, old,
                )
            new_params = _sel(new_params, params)
            if not self.offload_opt_state:
                # offloaded moments already selected on device inside
                # _offload_update (host-space where() won't compile on TPU)
                new_opt = _sel(new_opt, state.opt_state)
            if self._grad_comm_active and new_residual is not None:
                # the skipped step's sync consumed the carried residual
                # into a DISCARDED update; rolling it back with the rest
                # of the state keeps the deferred gradient signal from
                # being lost on every scale-halving step
                new_residual = _sel(new_residual, state.grad_residual)
            good = state.scaler["good"] + 1
            grow = good >= self.loss_scale_growth_interval
            new_scaler = {
                "scale": jnp.where(
                    finite,
                    jnp.where(grow, scale * 2.0, scale),
                    jnp.maximum(scale * 0.5, 1.0),
                ),
                "good": jnp.where(
                    jnp.logical_and(finite, jnp.logical_not(grow)), good, 0
                ).astype(jnp.int32),
            }
        # ZeRO-1/2: updated params all-gather back to replicated; ZeRO-3:
        # they stay sharded.  (The reference broadcasts per-param from the
        # owner in a python loop with no bucketing, zero1/optim.py:25-34.)
        new_params = self._constrain(new_params, self._param_shardings)
        new_state = TrainState(params=new_params, opt_state=new_opt,
                               scaler=new_scaler,
                               dropout_base=state.dropout_base,
                               grad_residual=new_residual)
        if self._telemetry_on:
            # on-device health metrics, packed into one (5,) vector: the
            # norms run over the logical (sharded) grads/params, so XLA
            # inserts the cross-shard psum and the numbers are global
            from ..telemetry.health import health_vector
            aux = health_vector(loss, grads, params, new_params)
            if self._layers_on:
                # (n_layer, 6) layer-health matrix: the probe cotangent
                # (act/dact stats from inside the scan) + per-layer grad
                # stats read off the stacked "h.*" gradient leaves
                from ..telemetry.health import layer_health_matrix
                mat = layer_health_matrix(layer_probe, grads)
                return new_state, loss, aux, mat
            return new_state, loss, aux
        return new_state, loss

    def step(self, state, batch):
        """One optimizer step.  batch = (idx, targets), each (B, T) int32 —
        or (accum, B, T) when accum_steps > 1.  Returns (state, loss)
        either way; with the telemetry knob the step's packed health
        vector (and, in layers mode, the per-layer health matrix) is
        pushed into the telemetry object un-synced."""
        if self._telemetry_on:
            if self._layers_on:
                state, loss, aux, mat = self._step(state, batch)
                self.telemetry.on_step_output(aux, layers=mat)
            else:
                state, loss, aux = self._step(state, batch)
                self.telemetry.on_step_output(aux)
            return state, loss
        return self._step(state, batch)

    def eval_loss(self, state, batch):
        """Mean loss on one (B, T) batch — forward only: deterministic (no
        dropout), no gradients, no state change.  The validation half of
        the train/eval contract (the reference has no eval path at all)."""
        idx, targets = batch
        return self._eval(state.params, idx, targets)

    def state_target(self) -> "TrainState":
        """The restore target for this engine's TrainState: a pytree of
        ShapeDtypeStruct(+NamedSharding) describing where every leaf
        should land — params replicated or ZeRO-3-sharded, optimizer
        state ZeRO-sharded, scaler/dropout/residual as configured.
        Consumed by utils.checkpoint.load_checkpoint and the elastic
        resume path (resilience/elastic.py), which swaps individual
        sub-targets when the checkpoint was written on a different
        topology."""
        shapes = jax.eval_shape(
            lambda: self.init(jax.random.PRNGKey(0))
        )
        shardings = TrainState(
            params=self._param_shardings,
            opt_state=self._opt_shardings,
            scaler=self._scaler_shardings,
            dropout_base=self._dropout_shardings,
            grad_residual=getattr(self, "_residual_shardings", None),
        )
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            shapes,
            shardings,
        )

    def elastic_descriptor(self) -> Dict[str, Any]:
        """JSON-safe identity of this engine's topology-dependent layout,
        persisted in the checkpoint meta sidecar so a resume onto a
        DIFFERENT mesh can decide what must be re-derived and what must
        be refused (resilience/elastic.py::check_reshapeable).  Every
        field is derivable state, not configuration — params/optimizer
        global shapes are topology-independent (Orbax reshards them on
        read); the residual shape and the non-data axes are not."""
        from .mesh import mesh_descriptor
        return {
            "engine": type(self).__name__,
            "stage": int(self.stage),
            "mesh": mesh_descriptor(self.mesh),
            "n_shard": int(self.n_shard),
            "accum_steps": int(self.accum_steps),
            "residual_shape": (
                list(self._residual_shape)
                if getattr(self, "_residual_shape", None) is not None
                else None
            ),
        }

    def gather_params(self, state):
        """Fully-replicated copy of the params — the bridge from a sharded
        TrainState to single-program consumers like `model.generate()`
        (under ZeRO-3 the resting params are axis-sharded; the decode jit
        is not mesh-aware).  One all-gather per leaf; prefer calling once
        per sampling session, not per token."""
        rep = NamedSharding(self.mesh, P())
        return jax.tree.map(lambda x: jax.device_put(x, rep), state.params)

    # -- reporting ---------------------------------------------------------

    def describe(self) -> str:
        name = type(self).__name__
        extras = ""
        if self.grad_clip is not None:
            extras += f", grad_clip={self.grad_clip}"
        if self.loss_scale is not None:
            extras += f", loss_scale={self.loss_scale}"
        if self.offload_opt_state:
            extras += ", opt state offloaded=pinned_host"
        if self._telemetry_on:
            extras += (", telemetry=layers" if self._layers_on
                       else ", telemetry=on")
        if self._grad_comm_active:
            extras += f", grad_comm={self.grad_comm}"
            if self.grad_comm_groups:
                extras += f"(2-hop inner={self.grad_comm_groups})"
            if not self.grad_comm_error_feedback:
                extras += "(no-ef)"
            if getattr(self, "grad_comm_tail", "fp32") != "fp32":
                extras += f", grad_comm_tail={self.grad_comm_tail}"
        if self._bucketed_active:
            extras += f", grad_buckets={self.grad_buckets}"
        if self._gather_prefetch_active:
            extras += f", gather_prefetch={self.gather_prefetch}"
            if self.gather_groups:
                extras += f"(2-hop inner={self.gather_groups})"
        if getattr(self, "hpz", False):
            extras += ", hpz=on"
            if getattr(self, "hpz_comm", "fp32") != "fp32":
                extras += f"[{self.hpz_comm}]"
        if getattr(self, "_lowering", "plain") not in ("plain",):
            extras += f", sched={self._schedule.describe()}"
        return (
            f"{name}(stage={self.stage}, devices={self.n_dev}, "
            f"accum={self.accum_steps}, params sharded="
            f"{self.stage >= 3}, grads sharded={self.stage >= 2}, "
            f"opt state sharded={self.stage >= 1}{extras})"
        )


class SingleDevice(ZeroEngine):
    """Stage-0, one device (reference example/single_device/train.py)."""
    stage = 0
    data_parallel = False


class DDP(ZeroEngine):
    """Replicated params, sharded batch, all-reduced grads
    (reference ddp/wrapper.py:15-33)."""
    stage = 0


class Zero1(ZeroEngine):
    """+ optimizer state sharded (reference zero1/)."""
    stage = 1


class Zero2(ZeroEngine):
    """+ gradients sharded via reduce-scatter (reference zero2/)."""
    stage = 2


class Zero3(ZeroEngine):
    """+ parameters sharded at rest, gathered per-layer on demand
    (reference zero3/ — completed here; the reference's is broken,
    SURVEY §2.18)."""
    stage = 3
